"""Run the dense bench at HIGGS scale points (4M / 8M / 11M — the
BASELINE.json north star) and record a committed artifact.

Each size runs twice in fresh processes: the first pays any XLA compiles for
the new shapes ("cold"), the second measures the steady state ("warm").
Partial results are flushed after every run so a TPU-worker crash still
leaves an artifact.

DEFAULT PATH (ISSUE 10): the combined full grid runs IN ONE PROCESS with
mesh sharding forced on (TRANSMOGRIFAI_TPU_MESH=1) and chunked host→device
streaming, so the dataset is bounded by aggregate HBM across the mesh and
transfer staging is O(TRANSMOGRIFAI_DEVICE_CHUNK_BYTES) — the regime that
used to hard-fault a single worker (BENCH_11M_ATTEMPTS_r4.json).

FALLBACK (--subprocess-ladder): the retired PER-FAMILY subprocess isolation
(VERDICT r4 next #3) — each candidate family's CV grid in a fresh process
over identical data with an automated budget/cache retry ladder, scalar CV
metrics merged into one full-grid record.  Kept for single-device hardware
or post-mortems, no longer the default.

Usage: python scripts/run_scale_bench.py [--subprocess-ladder] [out.json] [sizes...]
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from bench import last_json_line  # noqa: E402

# retry ladder for a crashed family run: progressively tighter HBM budgets
# (device-transfer cache cap, tree-histogram budget).  NOTE (ISSUE 15): the
# default mesh path now degrades IN-PROCESS via the memory governor's
# shrink-and-retry ladder (parallel/memory.py) — this env ladder survives
# only for the --subprocess-ladder fallback, where each step costs a fresh
# process and a re-paid feature-engineering pass.
_LADDER = [
    {"TRANSMOGRIFAI_DEVICE_CACHE_BYTES": str(256 << 20),
     "TRANSMOGRIFAI_TREE_BUDGET_GB": "4"},
    {"TRANSMOGRIFAI_DEVICE_CACHE_BYTES": str(128 << 20),
     "TRANSMOGRIFAI_TREE_BUDGET_GB": "3"},
    {"TRANSMOGRIFAI_DEVICE_CACHE_BYTES": str(64 << 20),
     "TRANSMOGRIFAI_TREE_BUDGET_GB": "2"},
]


def _run_bench(n, extra_env, timeout_s=3600):
    env = {**os.environ, "BENCH_WORKLOAD": "dense", "BENCH_ROWS": str(n),
           # cold/warm semantics rely on exactly ONE process per run: a
           # silent in-bench subprocess retry would report a crashed "warm"
           # run as rc=0 measured cold
           "BENCH_NO_RETRY": "1", **extra_env}
    # supervised child: SIGTERM→SIGKILL escalation reclaims a bench whose
    # native init hung (plain subprocess timeout leaves the hang alive —
    # the OUTAGE_r5 / BENCH_11M_ATTEMPTS_r4 failure mode); rc=124 keeps
    # the ladder's historical timeout convention
    from transmogrifai_tpu.parallel.supervisor import run_supervised
    r = run_supervised([sys.executable, os.path.join(ROOT, "bench.py")],
                       timeout_s=timeout_s, grace_s=30.0, env=env, cwd=ROOT)
    rec = {"rc": r.rc, "proc_wall_s": round(r.wall_s, 1)}
    if r.escalated:
        rec["escalated_sigkill"] = True
    line = last_json_line(r.stdout)
    if line:
        rec["result"] = json.loads(line)
        # hoist the memory-governor block (plan, shrink level, peak RSS) so
        # scanning a scale artifact for OOM pressure doesn't require digging
        # through each run's full aux
        mem = (rec["result"].get("aux") or {}).get("memory")
        if mem:
            rec["memory"] = mem
    if r.rc != 0:
        rec["stderr_tail"] = ("timeout" if r.timed_out
                              else (r.stderr or ""))[-2000:]
    return rec


def _per_family(n, flush):
    """Each family's grid in its own process with the budget ladder; the
    parent merges scalars into one full-grid record."""
    fams = {}
    for fam in ("lr", "rf", "gbt"):
        for step, budgets in enumerate(_LADDER):
            rec = _run_bench(n, {"BENCH_FAMILIES": fam, **budgets})
            rec["ladder_step"] = step
            fams[fam] = rec
            flush()
            print(json.dumps({"family": fam, **rec})[:2000], flush=True)
            if rec["rc"] == 0:
                break
    ok = all(r["rc"] == 0 for r in fams.values())
    merged = {"rows": n, "phase": "per_family_isolated",
              "rc": 0 if ok else 1, "families": fams}
    if ok:
        # model name → (metric, source family key), sourced from whichever
        # process reported it — no hardcoded class-name table, so a renamed
        # or additional candidate cannot raise StopIteration here
        cv, src = {}, {}
        larger_better = True
        for fam_key, r in fams.items():
            aux = r["result"]["aux"]
            larger_better = bool(aux.get("metric_larger_better", True))
            for name, v in (aux.get("family_cv_metrics") or {}).items():
                cv[name], src[name] = v, fam_key
        merged["family_cv_metrics"] = cv
        if not cv:
            merged["rc"] = 1
            merged["note"] = ("family processes reported no CV metrics; "
                              "winner merge skipped")
            return merged
        # best per the validation evaluator's own direction (AuPR is
        # larger-better, but e.g. a regression RMSE selector is not)
        winner = (max if larger_better else min)(cv, key=cv.get)
        merged["winner"] = winner
        merged["metric_larger_better"] = larger_better
        # the winning family's process already refit its winner on the full
        # matrix and evaluated train AuROC — that IS the full grid's outcome
        merged["train_auroc"] = fams[src[winner]]["result"]["aux"][
            "train_auroc"]
        merged["combined_wall_s"] = round(sum(
            r["result"]["value"] for r in fams.values()), 2)
        merged["note"] = ("full grid as three isolated family processes "
                          "(identical data; winner selected across all "
                          "candidates); combined_wall_s = sum of family "
                          "walls, each re-paying feature engineering")
    return merged


def main():
    argv = list(sys.argv[1:])
    use_ladder = "--subprocess-ladder" in argv
    if use_ladder:
        argv.remove("--subprocess-ladder")
    out_path = argv[0] if argv else os.path.join(ROOT, "BENCH_11M.json")
    sizes = ([int(float(a)) for a in argv[1:]]
             or [4_000_000, 8_000_000, 11_000_000])
    out = {"workload": "dense HIGGS-difficulty (bench.py run_dense)",
           "path": "subprocess_ladder" if use_ladder else "mesh_sharded",
           "runs": []}

    def flush():
        with open(out_path, "w") as fh:
            json.dump(out, fh, indent=2)

    for n in sizes:
        combined_ok = False
        for phase in ("cold", "warm"):
            extra = {}
            if use_ladder:
                if n >= 8_000_000:
                    # cumulative HBM residency is what hard-faults the
                    # worker at 10M+ (VERDICT r3 #2): shrink the
                    # host→device transfer cache so stale raw-column copies
                    # evict, and lower the tree histogram budget below the
                    # near-capacity trigger
                    extra = dict(_LADDER[0])
            else:
                # one-process mesh-sharded sweep (ISSUE 10): force the mesh
                # on regardless of the row threshold and stream the matrix
                # over in bounded chunks — resident data scales with
                # aggregate HBM, staging with the chunk budget
                extra = {"TRANSMOGRIFAI_TPU_MESH": "1"}
                extra.setdefault("TRANSMOGRIFAI_DEVICE_CHUNK_BYTES",
                                 os.environ.get(
                                     "TRANSMOGRIFAI_DEVICE_CHUNK_BYTES",
                                     str(256 << 20)))
            rec = {"rows": n, "phase": phase, **_run_bench(n, extra)}
            out["runs"].append(rec)
            flush()
            print(json.dumps(rec)[:2000], flush=True)
            if rec["rc"] != 0:
                print(f"size {n} {phase} failed", flush=True)
            elif phase == "warm":
                combined_ok = True
        if not combined_ok:
            if not use_ladder:
                print(f"size {n}: mesh-sharded run failed; re-run with "
                      "--subprocess-ladder for per-family isolation",
                      flush=True)
                continue
            print(f"size {n}: combined grid failed; isolating families",
                  flush=True)
            merged = _per_family(n, flush)
            out["runs"].append(merged)
            flush()
            print(json.dumps(merged)[:2000], flush=True)


if __name__ == "__main__":
    main()
