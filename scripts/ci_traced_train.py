"""CI smoke for the telemetry layer (ISSUE 5): run a tiny traced train,
export the Chrome-trace JSON, and validate the span tree.

Usage:
    python scripts/ci_traced_train.py run OUT_DIR       # train + export
    python scripts/ci_traced_train.py validate TRACE    # parse + assert

``validate`` asserts the trace parses as Chrome trace-event JSON and that
it contains a ``selector.sweep`` span nested (via the parentId chain in
``args``) under a ``workflow.train`` span — the acceptance shape for the
traced-train timeline.
"""

import json
import os
import sys

import numpy as np

# runnable as `python scripts/ci_traced_train.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_records(n, seed=7):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        x1 = float(rng.normal())
        x2 = float(rng.normal())
        recs.append({
            "y": 1.0 if (x1 + 0.5 * x2 + rng.normal() * 0.3) > 0 else 0.0,
            "x1": x1, "x2": x2,
            "cat": ["a", "b", "c"][i % 3],
            "sparse": x2 if i % 4 == 0 else None,
        })
    return recs


def run(out_dir):
    from transmogrifai_tpu import types as T
    from transmogrifai_tpu.features import features_from_schema
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, ModelCandidate, grid)
    from transmogrifai_tpu.telemetry import (Tracer, use_tracer,
                                             write_telemetry_summary)
    from transmogrifai_tpu.workflow import Workflow

    schema = {"y": T.RealNN, "x1": T.Real, "x2": T.Real,
              "cat": T.PickList, "sparse": T.Real}
    y, predictors = features_from_schema(schema, response="y")
    fv = transmogrify(predictors)
    checked = y.sanity_check(fv, remove_bad_features=True)
    sel = BinaryClassificationModelSelector(models=[
        ModelCandidate(OpLogisticRegression(),
                       grid(reg_param=[0.01, 0.1]),
                       "OpLogisticRegression")])
    sel.set_input(y, checked)
    wf = (Workflow().set_input_records(make_records(150))
          .set_result_features(sel.get_output()))

    os.makedirs(out_dir, exist_ok=True)
    tracer = Tracer(run_name="ci-traced-train")
    with use_tracer(tracer):
        model = wf.train()
        model.score()
    trace_path = tracer.export_chrome_trace(
        os.path.join(out_dir, "trace-train.json"))
    write_telemetry_summary(os.path.join(out_dir, "telemetry.json"), tracer)
    print(f"wrote {trace_path} ({len(tracer)} spans)")
    return 0


def validate(trace_path):
    from transmogrifai_tpu.telemetry import (load_trace,
                                             render_trace_summary)
    with open(trace_path) as fh:
        doc = json.load(fh)
    assert "traceEvents" in doc, "not a Chrome trace-event file"
    assert all(e.get("ph") == "X" for e in doc["traceEvents"])

    spans = load_trace(trace_path)
    assert spans, "trace contains no spans"
    by_id = {s["spanId"]: s for s in spans if s.get("spanId")}
    names = {s["name"] for s in spans}
    assert "workflow.train" in names, f"no workflow.train span in {names}"
    assert "selector.sweep" in names, f"no selector.sweep span in {names}"

    def chain(s):
        out, seen = [], set()
        while s is not None and s.get("spanId") not in seen:
            seen.add(s.get("spanId"))
            out.append(s["name"])
            s = by_id.get(s.get("parentId"))
        return out

    sweeps = [s for s in spans if s["name"] == "selector.sweep"]
    nested = [s for s in sweeps if "workflow.train" in chain(s)[1:]]
    assert nested, ("selector.sweep span is not nested under "
                    "workflow.train: " + repr([chain(s) for s in sweeps]))
    errors = [s["name"] for s in spans if s.get("status") == "error"]
    assert not errors, f"error spans in a clean train: {errors}"
    print(f"OK: {len(spans)} spans; selector.sweep chain: "
          + " -> ".join(chain(nested[0])))
    print(render_trace_summary(trace_path, top_n=8))
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate TRACE_FILE")
