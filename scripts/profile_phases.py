"""Phase-level timing of the bench workload: cold (compile) vs warm (execute)
wall for each candidate family's grid fit, plus the feature/sanity DAG.

Usage: python scripts/profile_phases.py [N]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def t(fn, *a, **k):
    t0 = time.time()
    out = fn(*a, **k)
    import jax
    jax.block_until_ready(jax.tree.leaves(out))
    return time.time() - t0, out


def main():
    import jax

    N = int(float(sys.argv[1])) if len(sys.argv) > 1 else 1_000_000
    D = 28
    from bench import make_data
    X, y = make_data(N, D)

    print(f"platform={jax.devices()[0].platform} N={N} D={D}", flush=True)

    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.models.trees import (OpGBTClassifier,
                                                OpRandomForestClassifier)

    # 3-fold masks like the validator builds
    rng = np.random.default_rng(42)
    perm = rng.permutation(N)
    folds = np.array_split(perm, 3)
    W = np.zeros((3, N), np.float32)
    for f in range(3):
        for j in range(3):
            if j != f:
                W[f, folds[j]] = 1.0

    y32 = y.astype(np.float32)

    lr = OpLogisticRegression()
    lr_grid = [dict(reg_param=r, elastic_net_param=0.1, max_iter=50)
               for r in (0.001, 0.01, 0.1, 0.2)]
    dt, _ = t(lr.fit_arrays_grid, X, y32, W, lr_grid)
    print(f"LR grid cold: {dt:.1f}s", flush=True)
    dt, _ = t(lr.fit_arrays_grid, X, y32, W, lr_grid)
    print(f"LR grid warm: {dt:.1f}s", flush=True)

    rf = OpRandomForestClassifier()
    rf_grid = [dict(num_trees=20, max_depth=6, min_instances_per_node=10)]
    dt, _ = t(rf.fit_arrays_grid, X, y32, W, rf_grid)
    print(f"RF grid cold: {dt:.1f}s", flush=True)
    dt, _ = t(rf.fit_arrays_grid, X, y32, W, rf_grid)
    print(f"RF grid warm: {dt:.1f}s", flush=True)

    gbt = OpGBTClassifier()
    gbt_grid = [dict(max_iter=20, max_depth=3, min_instances_per_node=10)]
    dt, _ = t(gbt.fit_arrays_grid, X, y32, W, gbt_grid)
    print(f"GBT grid cold: {dt:.1f}s", flush=True)
    dt, _ = t(gbt.fit_arrays_grid, X, y32, W, gbt_grid)
    print(f"GBT grid warm: {dt:.1f}s", flush=True)


if __name__ == "__main__":
    main()
