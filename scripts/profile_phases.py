"""Phase-level timing of the bench workload: cold (compile) vs warm (execute)
wall for each candidate family's grid fit, plus the feature/sanity DAG.

Usage: python scripts/profile_phases.py [N]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def t(fn, *a, **k):
    """Time fn to COMPLETION: block_until_ready does not reliably wait on the
    tunneled 'axon' platform, so force a scalar device→host pull over every
    array leaf (measured: dispatch returns in ~0ms while the device still has
    seconds of queued work)."""
    import jax
    import jax.numpy as jnp

    t0 = time.time()
    out = fn(*a, **k)
    leaves = [l for l in jax.tree.leaves(out) if isinstance(l, jax.Array)]
    if leaves:
        float(jnp.stack([jnp.sum(jnp.asarray(l, jnp.float32).ravel()[:1])
                         for l in leaves]).sum())
    return time.time() - t0, out


def main():
    import jax

    N = int(float(sys.argv[1])) if len(sys.argv) > 1 else 1_000_000
    D = 28
    from bench import make_data
    X, y = make_data(N, D)

    print(f"platform={jax.devices()[0].platform} N={N} D={D}", flush=True)

    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.models.trees import (OpGBTClassifier,
                                                OpRandomForestClassifier)

    # 3-fold masks like the validator builds
    rng = np.random.default_rng(42)
    perm = rng.permutation(N)
    folds = np.array_split(perm, 3)
    W = np.zeros((3, N), np.float32)
    for f in range(3):
        for j in range(3):
            if j != f:
                W[f, folds[j]] = 1.0

    y32 = y.astype(np.float32)

    lr = OpLogisticRegression()
    lr_grid = [dict(reg_param=r, elastic_net_param=0.1, max_iter=50)
               for r in (0.001, 0.01, 0.1, 0.2)]
    dt, _ = t(lr.fit_arrays_grid, X, y32, W, lr_grid)
    print(f"LR grid cold: {dt:.1f}s", flush=True)
    dt, _ = t(lr.fit_arrays_grid, X, y32, W, lr_grid)
    print(f"LR grid warm: {dt:.1f}s", flush=True)

    rf = OpRandomForestClassifier()
    rf_grid = [dict(num_trees=20, max_depth=6, min_instances_per_node=10)]
    dt, _ = t(rf.fit_arrays_grid, X, y32, W, rf_grid)
    print(f"RF grid cold: {dt:.1f}s", flush=True)
    dt, _ = t(rf.fit_arrays_grid, X, y32, W, rf_grid)
    print(f"RF grid warm: {dt:.1f}s", flush=True)

    gbt = OpGBTClassifier()
    gbt_grid = [dict(max_iter=20, max_depth=3, min_instances_per_node=10)]
    dt, _ = t(gbt.fit_arrays_grid, X, y32, W, gbt_grid)
    print(f"GBT grid cold: {dt:.1f}s", flush=True)
    dt, _ = t(gbt.fit_arrays_grid, X, y32, W, gbt_grid)
    print(f"GBT grid warm: {dt:.1f}s", flush=True)


if __name__ == "__main__" and "--train" not in sys.argv:
    main()


def profile_train(N=1_000_000, D=28):
    """Run the REAL bench workload with per-phase forced-sync timing."""
    import jax
    import jax.numpy as jnp

    from bench import make_data
    from transmogrifai_tpu import dag as dag_mod
    from transmogrifai_tpu.columns import Column, ColumnBatch
    from transmogrifai_tpu.evaluators import Evaluators
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.models.trees import (OpGBTClassifier,
                                                OpRandomForestClassifier)
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.selector import (BinaryClassificationModelSelector,
                                            ModelCandidate, ModelSelector,
                                            grid)
    from transmogrifai_tpu.types import RealNN
    from transmogrifai_tpu.workflow import Workflow

    def sync(tag, t0):
        # the device stream is in-order: pulling one fresh scalar waits for
        # all previously queued work (block_until_ready does not, on axon)
        float(jnp.zeros(()).sum())
        print(f"  {tag}: {time.time()-t0:.2f}s", flush=True)

    X, y = make_data(N, D)
    label = FeatureBuilder.RealNN("label").as_response()
    feats = [FeatureBuilder.RealNN(f"f{i}").as_predictor() for i in range(D)]
    checked = label.sanity_check(transmogrify(feats), remove_bad_features=True)
    models = [
        ModelCandidate(OpLogisticRegression(),
                       grid(reg_param=[0.001, 0.01, 0.1, 0.2],
                            elastic_net_param=[0.1], max_iter=[50]), "LR"),
        ModelCandidate(OpRandomForestClassifier(),
                       grid(num_trees=[20], max_depth=[6],
                            min_instances_per_node=[10]), "RF"),
        ModelCandidate(OpGBTClassifier(),
                       grid(max_iter=[20], max_depth=[3],
                            min_instances_per_node=[10]), "GBT"),
    ]
    selector = BinaryClassificationModelSelector(models=models)
    selector.set_input(label, checked)
    pred = selector.get_output()
    cols = {"label": Column(RealNN, y)}
    for i in range(D):
        cols[f"f{i}"] = Column(RealNN, X[:, i])
    batch = ColumnBatch(cols, N)
    wf = Workflow().set_input_batch(batch).set_result_features(pred)

    orig_fit_layer = dag_mod.fit_layer

    def timed_fit_layer(b, layer):
        t0 = time.time()
        out = orig_fit_layer(b, layer)
        names = [type(s).__name__ for s in layer]
        sync(f"fit_layer {names}", t0)
        return out

    dag_mod.fit_layer = timed_fit_layer
    import transmogrifai_tpu.workflow as wf_mod
    wf_mod.fit_layer = timed_fit_layer

    orig_find = ModelSelector.find_best_estimator
    orig_refit = ModelSelector._refit_reusing_grid_executable
    orig_eval_all = ModelSelector._evaluate_all

    def timed_find(self, *a, **k):
        t0 = time.time()
        out = orig_find(self, *a, **k)
        sync("selector.find_best_estimator", t0)
        return out

    def timed_refit(self, *a, **k):
        t0 = time.time()
        out = orig_refit(self, *a, **k)
        sync("selector.refit", t0)
        return out

    def timed_eval_all(self, *a, **k):
        t0 = time.time()
        out = orig_eval_all(self, *a, **k)
        sync("selector.evaluate_all", t0)
        return out

    ModelSelector.find_best_estimator = timed_find
    ModelSelector._refit_reusing_grid_executable = timed_refit
    ModelSelector._evaluate_all = timed_eval_all

    t0 = time.time()
    model = wf.train()
    print(f"TOTAL train: {time.time()-t0:.2f}s", flush=True)
    t0 = time.time()
    m = model.evaluate(Evaluators.BinaryClassification.auROC(), batch=batch)
    print(f"evaluate: {time.time()-t0:.2f}s AuROC={m['AuROC']:.4f}", flush=True)


if __name__ == "__main__" and "--train" in sys.argv:
    _pos = [a for a in sys.argv[1:] if not a.startswith("-")]
    profile_train(N=int(float(_pos[0])) if _pos else 1_000_000)
