"""Closed-loop chaos harness for the device-runtime supervisor (train side).

The serving control plane has ``chaos_slo.py``; this is the same discipline
for the OUTAGE_r5 failure modes on the training path.  It injects, via the
``supervisor.*`` injection points and the probe chaos preludes, the faults
that outage actually produced — a native init hang, a SIGTERM-ignoring hung
process, a dead probe child, a stalled host→device chunk, and a mid-sweep
device loss — and asserts the supervision contract:

* a hung init resolves to a TYPED outage verdict within the
  timeout+grace watchdog budget (never an unbounded stall);
* a SIGTERM-ignoring child is reclaimed by the SIGKILL escalation and is
  actually gone afterwards — zero hung processes survive the harness;
* the heartbeat trips AVAILABLE→DEGRADED→OUTAGE under consecutive probe
  kills, writes the standardized outage record, and records the recovery —
  every transition lands in the failure log and telemetry;
* a stalled transfer chunk surfaces as ``TransferStallError`` (typed),
  not a hang;
* a mid-sweep device loss degrades to the surviving mesh and the resumed
  sweep selects the IDENTICAL winner (name + params) as an uninterrupted
  run, replaying checkpointed families instead of refitting them.

Artifacts written to ``--out-dir``: ``outcomes.jsonl`` (one line per
scenario), ``metrics.txt`` (final telemetry snapshot), ``summary.json``
(the verdict, also printed), ``trace-chaos-train.json`` and the
``OUTAGE_*.json`` record the heartbeat produced.  Exit 0 on a clean pass,
1 on any contract violation.

Usage:
    python scripts/chaos_train.py --out-dir /tmp/chaos_train \
        [--seed 0] [--probe-timeout-s 2] [--grace-s 3] [--rows 560]
"""

import argparse
import json
import os
import sys
import time

# the mesh-degrade scenario needs the virtual 8-device CPU topology; must be
# set before jax initializes (mirrors tests/conftest.py)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# runnable as `python scripts/chaos_train.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class _FakeClock:
    """Deterministic heartbeat clock: the breaker's reset timeout elapses
    when the scenario says so, not wall time."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _two_family_sweep(n, seed, resume_from=None):
    """Two LR families with widely-separated regularisation (reduction-order
    float noise on a shrunken mesh cannot flip the winner); LR_A checkpoints
    before LR_B scores, so a device loss at LR_B's scoring proves replay."""
    from transmogrifai_tpu.columns import Column, ColumnBatch
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, ModelCandidate, grid)
    from transmogrifai_tpu.types import RealNN
    from transmogrifai_tpu.workflow import Workflow

    d = 6
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
    label = FeatureBuilder.RealNN("label").as_response()
    feats = [FeatureBuilder.RealNN(f"f{i}").as_predictor() for i in range(d)]
    checked = label.sanity_check(transmogrify(feats),
                                 remove_bad_features=True)
    sel = BinaryClassificationModelSelector(models=[
        ModelCandidate(OpLogisticRegression(),
                       grid(reg_param=[0.001, 3.0], max_iter=[25]), "LR_A"),
        ModelCandidate(OpLogisticRegression(),
                       grid(reg_param=[10.0, 30.0], max_iter=[25]), "LR_B"),
    ])
    sel.set_input(label, checked)
    pred = sel.get_output()
    cols = {"label": Column(RealNN, y)}
    for i in range(d):
        cols[f"f{i}"] = Column(RealNN, X[:, i])
    wf = Workflow().set_input_batch(ColumnBatch(cols, n)) \
                   .set_result_features(pred)
    model = wf.train(resume_from=resume_from)
    s = model.selected_model.summary
    competed = [r for r in s.validation_results if not r.raced_out
                and np.isfinite(r.metric_values[s.evaluation_metric])]
    best = max(competed, key=lambda r: r.metric_values[s.evaluation_metric])
    return s.best_model_name, dict(best.params), model.failure_log


def run_chaos_train(*, seed=0, probe_timeout_s=2.0, grace_s=3.0, rows=560,
                    out_dir=None):
    """Run the harness; returns the summary dict (``summary["passed"]`` is
    the verdict).  Importable — the chaos test suite and the weekly CI job
    drive exactly this loop."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from transmogrifai_tpu.parallel import make_mesh, stream_to_device
    from transmogrifai_tpu.parallel import supervisor as sup
    from transmogrifai_tpu.resilience import (FailureLog, FaultInjector,
                                              inject_faults,
                                              use_failure_log)
    from transmogrifai_tpu.telemetry import REGISTRY, Tracer, use_tracer

    budget_s = probe_timeout_s + grace_s + 30.0   # + spawn/reap overhead
    tracer = Tracer(run_name="chaos-train")
    flog = FailureLog()
    outcomes = []
    sup.reset_surviving_devices()

    def row(scenario, **kw):
        r = {"scenario": scenario, **kw}
        outcomes.append(r)
        return r

    with use_tracer(tracer), use_failure_log(flog):
        # -- 1. native init hang → typed outage within the watchdog budget
        t0 = time.monotonic()
        v = sup.probe_devices(timeout_s=probe_timeout_s, grace_s=grace_s,
                              chaos="hang", key="chaos-init-hang")
        hang_wall = time.monotonic() - t0
        row("init_hang", status=v.status, cause=v.cause,
            wall_s=round(hang_wall, 1), within_budget=hang_wall <= budget_s)

        # -- 2. SIGTERM-ignoring hung process reclaimed by SIGKILL
        t0 = time.monotonic()
        r = sup.run_supervised(
            [sys.executable, "-c", sup.CHAOS_PRELUDES["hang_ignore_sigterm"]],
            timeout_s=probe_timeout_s, grace_s=grace_s)
        kill_wall = time.monotonic() - t0
        try:
            os.kill(r.pid, 0)
            reclaimed = False
        except OSError:
            reclaimed = True
        row("sigterm_ignored", rc=r.rc, escalated=r.escalated,
            reclaimed=reclaimed, wall_s=round(kill_wall, 1),
            within_budget=kill_wall <= budget_s)

        # -- 3. probe child dies → outage verdict, not an exception
        v_die = sup.probe_devices(timeout_s=probe_timeout_s, chaos="die",
                                  key="chaos-probe-die")
        row("probe_kill", status=v_die.status, cause=v_die.cause)

        # -- 4. heartbeat trips to OUTAGE under consecutive probe kills,
        #       writes the standardized record, recovers when probes heal
        clk = _FakeClock()
        hb = sup.Heartbeat(probe=lambda: sup.probe_devices(
                               timeout_s=60, platform="cpu",
                               key="chaos-heartbeat"),
                           interval_s=10.0, failure_threshold=2,
                           reset_timeout_s=30.0, clock=clk,
                           outage_dir=out_dir,
                           context="chaos_train.py heartbeat scenario")
        outages_before = REGISTRY.counter("supervisor.outages_total").value
        with inject_faults(FaultInjector(
                fail_keys={"supervisor.heartbeat": ["1", "2"]}, seed=seed)):
            states = [(hb.tick().status, hb.state)]      # 0: healthy
            states.append((hb.tick().status, hb.state))  # 1: killed → DEGRADED
            states.append((hb.tick().status, hb.state))  # 2: killed → OUTAGE
            clk.t += 31.0                 # breaker reset timeout elapses
            states.append((hb.tick().status, hb.state))  # 3: healed
        hb_actions = [e.action for e in flog
                      if e.point == "supervisor.heartbeat"]
        records = [f for f in os.listdir(out_dir)
                   if f.startswith("OUTAGE_")] if out_dir else []
        rec_ok = False
        if records:
            rec = json.load(open(os.path.join(out_dir, records[0])))
            rec_ok = set(rec) == set(sup.OUTAGE_RECORD_KEYS)
        row("heartbeat", states=[s for _, s in states],
            actions=hb_actions, outage_record=records[:1],
            record_schema_ok=rec_ok,
            outages_total_delta=REGISTRY.counter(
                "supervisor.outages_total").value - outages_before)

        # -- 5. stalled host→device chunk → typed TransferStallError
        mesh = make_mesh(min(8, len(jax.devices())))
        X = np.ones((64, 4), np.float32)
        with inject_faults(FaultInjector(
                rates={"supervisor.chunk_stall": 1.0}, seed=seed)):
            try:
                stream_to_device(X, mesh)
                stall = "no-error"
            except sup.TransferStallError as e:
                stall = "typed"
                stall_classified = sup.is_device_loss(e)
            except Exception as e:  # noqa: BLE001 — contract violation
                stall = f"untyped: {type(e).__name__}"
                stall_classified = False
        row("chunk_stall", outcome=stall,
            classifies_as_device_loss=stall_classified)

        # -- 6. mid-sweep device loss → surviving-mesh resume, same winner
        os.environ["TRANSMOGRIFAI_TPU_MESH"] = "1"
        import tempfile
        sweep_dir = os.path.join(out_dir or tempfile.mkdtemp(
            prefix="chaos-train-"), "sweep")
        try:
            w0, p0, _ = _two_family_sweep(rows, seed)
            sup.reset_surviving_devices()
            degrades_before = REGISTRY.counter(
                "supervisor.mesh_degrades_total").value
            with inject_faults(FaultInjector(
                    fail_keys={"supervisor.device_loss": ["LR_B:score:a0"]},
                    seed=seed)) as inj:
                w1, p1, sweep_log = _two_family_sweep(
                    rows, seed, resume_from=sweep_dir)
            sweep_actions = [(e.action, e.point) for e in sweep_log]
            row("mesh_degrade",
                baseline_winner=w0, recovered_winner=w1,
                same_winner=(w1 == w0 and p1 == p0),
                device_cap=sup.device_cap(),
                loss_fired=("supervisor.device_loss",
                            "LR_B:score:a0") in inj.fired,
                degrade_recorded=("degraded",
                                  "supervisor.device_loss") in sweep_actions,
                resumed_from_checkpoint=any(
                    a == "resumed" for a, _ in sweep_actions),
                mesh_degrades_delta=REGISTRY.counter(
                    "supervisor.mesh_degrades_total").value - degrades_before)
        finally:
            sup.reset_surviving_devices()
            os.environ.pop("TRANSMOGRIFAI_TPU_MESH", None)

    by = {r["scenario"]: r for r in outcomes}
    checks = {
        "init_hang_typed_outage_within_budget":
            by["init_hang"]["status"] == "outage"
            and by["init_hang"]["cause"] == "hang"
            and by["init_hang"]["within_budget"],
        "sigterm_ignoring_child_reclaimed":
            by["sigterm_ignored"]["rc"] == 124
            and by["sigterm_ignored"]["escalated"]
            and by["sigterm_ignored"]["reclaimed"]
            and by["sigterm_ignored"]["within_budget"],
        "probe_kill_is_outage": by["probe_kill"]["status"] == "outage",
        "heartbeat_trips_and_recovers":
            by["heartbeat"]["states"] == ["available", "degraded",
                                          "outage", "available"]
            and "outage" in by["heartbeat"]["actions"]
            and "recovered" in by["heartbeat"]["actions"]
            and by["heartbeat"]["outages_total_delta"] >= 1,
        "outage_record_schema_ok": (by["heartbeat"]["record_schema_ok"]
                                    or out_dir is None),
        "chunk_stall_typed": by["chunk_stall"]["outcome"] == "typed"
            and by["chunk_stall"]["classifies_as_device_loss"],
        "degrade_resume_same_winner": by["mesh_degrade"]["same_winner"]
            and by["mesh_degrade"]["loss_fired"],
        "sweep_ran_on_surviving_mesh": by["mesh_degrade"]["device_cap"] == 7,
        "every_degrade_recorded": by["mesh_degrade"]["degrade_recorded"]
            and by["mesh_degrade"]["mesh_degrades_delta"] >= 1,
        "resume_replayed_checkpoint":
            by["mesh_degrade"]["resumed_from_checkpoint"],
    }
    summary = {
        "passed": all(checks.values()),
        "checks": checks,
        "seed": seed,
        "probeTimeoutS": probe_timeout_s,
        "graceS": grace_s,
        "watchdogBudgetS": budget_s,
        "rows": rows,
        "failureSummary": flog.summary(),
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "outcomes.jsonl"), "w") as fh:
            for r in outcomes:
                fh.write(json.dumps(r) + "\n")
        with open(os.path.join(out_dir, "metrics.txt"), "w") as fh:
            json.dump(REGISTRY.snapshot(), fh, indent=2)
        with open(os.path.join(out_dir, "summary.json"), "w") as fh:
            json.dump(summary, fh, indent=2)
        tracer.export_chrome_trace(
            os.path.join(out_dir, "trace-chaos-train.json"))
    return summary


def run_chaos_hostgroup(*, out_dir, seed=0, rows=560):
    """Lost-host drill (ISSUE 14): drive the ci_hostgroup_smoke harness —
    2-process group vs single-process control, SIGKILL rank 1 mid-sweep,
    relaunch at world 1, checkpoint resume, identical winner — and fold its
    checks into the chaos summary contract."""
    import subprocess
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "ci_hostgroup_smoke.py")
    env = dict(os.environ,
               HOSTGROUP_SMOKE_ROWS=str(rows),
               HOSTGROUP_SMOKE_SEED=str(seed))
    os.makedirs(out_dir, exist_ok=True)
    checks = {}
    for phase in ("run", "validate"):
        r = subprocess.run([sys.executable, script, phase, out_dir],
                           env=env, capture_output=True, text=True,
                           timeout=1200)
        checks[f"hostgroup_{phase}_rc0"] = r.returncode == 0
        if r.returncode != 0:
            print(r.stdout[-4000:], file=sys.stderr)
            print(r.stderr[-4000:], file=sys.stderr)
            break
    smoke_path = os.path.join(out_dir, "hostgroup_smoke.json")
    checks["hostgroup_outage_artifact"] = False
    if os.path.exists(smoke_path):
        with open(smoke_path) as fh:
            smoke = json.load(fh)
        rec = (smoke.get("chaos") or {}).get("outageRecord")
        checks["hostgroup_outage_artifact"] = isinstance(rec, dict)
    summary = {"passed": all(checks.values()), "checks": checks,
               "seed": seed, "rows": rows, "mode": "hostgroup"}
    with open(os.path.join(out_dir, "summary.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    return summary


def run_chaos_oom(*, out_dir, seed=0, rows=560):
    """Device-memory-pressure drill (ISSUE 15): drive the ci_memory_smoke
    harness — tiny-budget preflight plan, OOM-vs-device-loss classifier
    disjointness, injected mid-sweep OOM walking the shrink-and-retry
    ladder to the identical winner with zero worker deaths — and fold its
    checks into the chaos summary contract."""
    import subprocess
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "ci_memory_smoke.py")
    env = dict(os.environ,
               MEMORY_SMOKE_ROWS=str(rows),
               MEMORY_SMOKE_SEED=str(seed))
    os.makedirs(out_dir, exist_ok=True)
    checks = {}
    for phase in ("run", "validate"):
        r = subprocess.run([sys.executable, script, phase, out_dir],
                           env=env, capture_output=True, text=True,
                           timeout=1200)
        checks[f"oom_{phase}_rc0"] = r.returncode == 0
        if r.returncode != 0:
            print(r.stdout[-4000:], file=sys.stderr)
            print(r.stderr[-4000:], file=sys.stderr)
            break
    smoke_path = os.path.join(out_dir, "memory-smoke.json")
    checks["oom_drill_converged"] = False
    if os.path.exists(smoke_path):
        with open(smoke_path) as fh:
            smoke = json.load(fh)
        drill = smoke.get("drill") or {}
        checks["oom_drill_converged"] = bool(
            drill.get("same_winner") and drill.get("device_cap") is None)
    summary = {"passed": all(checks.values()), "checks": checks,
               "seed": seed, "rows": rows, "mode": "oom"}
    with open(os.path.join(out_dir, "summary.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--probe-timeout-s", type=float, default=2.0)
    ap.add_argument("--grace-s", type=float, default=3.0)
    ap.add_argument("--rows", type=int, default=560,
                    help="sweep rows; must divide by 8 AND 7 so the mesh "
                         "forms before and after the injected device loss")
    ap.add_argument("--mode", choices=("full", "hostgroup", "oom"),
                    default="full",
                    help="'full' runs the in-process supervisor drills; "
                         "'hostgroup' runs the multi-process lost-host "
                         "drill (real ranks, SIGKILL, relaunch, resume); "
                         "'oom' runs the memory-governor pressure drill "
                         "(injected device OOM, shrink ladder, same winner)")
    args = ap.parse_args(argv)
    if args.mode == "hostgroup":
        summary = run_chaos_hostgroup(out_dir=args.out_dir, seed=args.seed,
                                      rows=args.rows)
    elif args.mode == "oom":
        summary = run_chaos_oom(out_dir=args.out_dir, seed=args.seed,
                                rows=args.rows)
    else:
        summary = run_chaos_train(
            seed=args.seed, probe_timeout_s=args.probe_timeout_s,
            grace_s=args.grace_s, rows=args.rows, out_dir=args.out_dir)
    print(json.dumps(summary, indent=2))
    if not summary["passed"]:
        failing = [k for k, ok in summary["checks"].items() if not ok]
        print(f"chaos train FAILED: {failing}", file=sys.stderr)
        return 1
    print("chaos train passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
