"""CI smoke for distributed tracing (ISSUE 13): boot a 2-worker serving
pool with ``trace_dir`` set, send traffic carrying a client-supplied
``traceparent``, run a supervised child under the same trace, merge the
per-process trace files with the ``trace-merge`` CLI, and require

  * a ``serving.request`` span in the merged trace on the client's
    trace_id,
  * a ``serving.batch`` span that links back to a request span on that
    trace_id,
  * a ``supervisor.child`` span (the cross-process env propagation) on
    that same trace_id,
  * one clock_sync metadata event per merged file,
  * a parseable OpenMetrics exemplar on the pool's merged /metrics whose
    trace_id is the client's,
  * the pool admin ``/traces`` endpoint listing every worker trace file.

Usage:
    python scripts/ci_trace_propagation_smoke.py run OUT_DIR
    python scripts/ci_trace_propagation_smoke.py validate OUT_DIR

``run`` writes OUT_DIR/trace-smoke.json with the measurements; ``validate``
asserts them so the CI failure mode is a readable diff of the summary.
"""

import json
import os
import re
import sys
import threading
import time
import urllib.request

import numpy as np

# runnable as `python scripts/ci_trace_propagation_smoke.py` from the root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SUMMARY_NAME = "trace-smoke.json"
MERGED_NAME = "merged-trace.json"

RECORDS = [{"x1": -0.25, "x2": 1.0, "cat": "a"},
           {"x1": 0.1, "x2": 9.5, "cat": "b"},
           {"x1": 2.0, "x2": 0.0, "cat": "c"}]

_EXEMPLAR_RE = re.compile(r' # \{trace_id="([0-9a-f]{32})"\} [0-9.eE+-]+')


def _make_records(n, seed=7):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        x1 = float(rng.normal())
        x2 = float(rng.uniform(0, 10))
        recs.append({
            "y": 1.0 if (x1 + 0.2 * x2 + rng.normal() * 0.3) > 1.0 else 0.0,
            "x1": x1, "x2": x2, "cat": ["a", "b", "c"][i % 3],
        })
    return recs


def _post(port, payload, traceparent, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": traceparent})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def run(out_dir):
    from transmogrifai_tpu import types as T
    from transmogrifai_tpu.cli import main as cli_main
    from transmogrifai_tpu.features import features_from_schema
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.parallel.supervisor import run_supervised
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, ModelCandidate, grid)
    from transmogrifai_tpu.serving.pool import (ServingPool,
                                                _make_admin_server)
    from transmogrifai_tpu.telemetry import Tracer, use_tracer
    from transmogrifai_tpu.workflow import Workflow

    os.makedirs(out_dir, exist_ok=True)
    trace_dir = os.path.join(out_dir, "traces")
    schema = {"y": T.RealNN, "x1": T.Real, "x2": T.Real, "cat": T.PickList}
    y, predictors = features_from_schema(schema, response="y")
    sel = BinaryClassificationModelSelector(models=[
        ModelCandidate(OpLogisticRegression(), grid(reg_param=[0.01]),
                       "OpLogisticRegression")])
    sel.set_input(y, transmogrify(predictors))
    model = (Workflow().set_input_records(_make_records(200))
             .set_result_features(sel.get_output()).train())
    bundle = os.path.join(out_dir, "model")
    model.save(bundle)

    tracer = Tracer("trace-smoke")
    summary = {"traceId": tracer.trace_id}
    with use_tracer(tracer):
        pool = ServingPool(bundle, workers=2, max_batch=16,
                           queue_bound=256, trace_dir=trace_dir,
                           run_dir=os.path.join(out_dir, "pool-run"))
        admin = _make_admin_server(pool, "127.0.0.1", 0)
        threading.Thread(target=admin.serve_forever, daemon=True).start()
        try:
            pool.start()
            # client-supplied traceparent on the pool's shared trace
            client = tracer.root_context().child()
            statuses = []
            for _ in range(12):
                code, _body, hdrs = _post(pool.port, RECORDS,
                                          client.to_traceparent())
                statuses.append(code)
                assert hdrs["X-Request-Id"] == tracer.trace_id
            summary["requestStatuses"] = sorted(set(statuses))
            summary["responseTraceparentTraceId"] = \
                hdrs["traceparent"].split("-")[1]

            # supervised child under the same trace (env propagation)
            with tracer.span("smoke.trigger"):
                r = run_supervised(
                    [sys.executable, "-c",
                     "import os; print(os.environ.get("
                     "'TRANSMOGRIFAI_TRACEPARENT', ''))"],
                    timeout_s=120)
            summary["supervisedRc"] = r.rc
            summary["supervisedChildTraceId"] = \
                (r.stdout.strip().split("-") + ["", ""])[1]

            # merged /metrics must carry a parseable exemplar
            merged_metrics = pool.metrics()
            summary["exemplarTraceIds"] = sorted(
                set(_EXEMPLAR_RE.findall(merged_metrics)))
        finally:
            pool.stop(grace_s=60.0)

        # the admin /traces listing sees the exported worker files
        with urllib.request.urlopen(
                f"http://127.0.0.1:{admin.server_address[1]}/traces",
                timeout=30) as resp:
            summary["tracesEndpoint"] = json.loads(resp.read())
        admin.shutdown()
        admin.server_close()

    # the parent process exports its own spans next to the workers'
    parent_trace = os.path.join(trace_dir, "trace-parent.json")
    tracer.export_chrome_trace(parent_trace)

    files = sorted(os.path.join(trace_dir, f)
                   for f in os.listdir(trace_dir)
                   if f.startswith("trace-") and f.endswith(".json"))
    summary["traceFiles"] = [os.path.basename(f) for f in files]
    merged_path = os.path.join(out_dir, MERGED_NAME)
    rc = cli_main(["trace-merge", *files, "--out", merged_path])
    assert rc == 0
    summary["mergedPath"] = merged_path

    with open(merged_path) as fh:
        merged = json.load(fh)
    evs = merged["traceEvents"]
    tid = tracer.trace_id
    xs = [e for e in evs if e.get("ph") == "X"]

    def on_trace(name):
        return [e for e in xs if e["name"] == name
                and e.get("args", {}).get("traceId") == tid]

    req_spans = on_trace("serving.request")
    batch_linked = [e for e in on_trace("serving.batch")
                    if any(l.get("traceId") == tid
                           for l in e["args"].get("links", []))]
    summary["requestSpans"] = len(req_spans)
    summary["batchSpansLinkedToRequest"] = len(batch_linked)
    summary["supervisorChildSpans"] = len(on_trace("supervisor.child"))
    summary["clockSyncs"] = sum(1 for e in evs if e.get("ph") == "c")
    summary["mergedFiles"] = len(merged["otherData"]["files"])

    with open(os.path.join(out_dir, SUMMARY_NAME), "w") as fh:
        json.dump(summary, fh, indent=2)
    print(json.dumps(summary, indent=2))
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, SUMMARY_NAME)) as fh:
        s = json.load(fh)
    tid = s["traceId"]
    assert s["requestStatuses"] == [200], \
        f"non-200 responses: {s['requestStatuses']}"
    assert s["responseTraceparentTraceId"] == tid, \
        "response traceparent did not adopt the client trace"
    assert s["supervisedRc"] == 0
    assert s["supervisedChildTraceId"] == tid, \
        "TRANSMOGRIFAI_TRACEPARENT did not reach the supervised child"
    assert s["requestSpans"] > 0, "no serving.request span on the trace"
    assert s["batchSpansLinkedToRequest"] > 0, \
        "no serving.batch span links back to a request span"
    assert s["supervisorChildSpans"] > 0, \
        "no supervisor.child span on the trace"
    assert tid in s["exemplarTraceIds"], \
        (f"client trace {tid} missing from /metrics exemplars "
         f"{s['exemplarTraceIds']}")
    assert s["mergedFiles"] == len(s["traceFiles"]) >= 3, \
        f"expected parent + 2 worker trace files: {s['traceFiles']}"
    assert s["clockSyncs"] == s["mergedFiles"], \
        "merged trace lost clock_sync metadata"
    listed = {t["name"] for t in s["tracesEndpoint"]["traces"]}
    assert {"trace-worker-0.json", "trace-worker-1.json"} <= listed, \
        f"/traces endpoint missing worker files: {sorted(listed)}"
    print(f"OK: one trace {tid} across {s['mergedFiles']} processes — "
          f"{s['requestSpans']} request spans, "
          f"{s['batchSpansLinkedToRequest']} linked batch spans, "
          f"{s['supervisorChildSpans']} supervised child spans, "
          f"exemplar on /metrics, /traces lists {sorted(listed)}")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
