"""CI smoke for the SO_REUSEPORT serving pool (ISSUE 12): train a tiny
model, save an AOT bundle, boot a 2-worker pool on one shared port, and
require

  * both workers score with ZERO backend compiles (the shipped AOT
    executables absorbed the cold start in every process, not just one),
  * a columnar round-trip on the shared port that lands bitwise on the
    JSON path's floats,
  * the parent's aggregated /metrics summing per-worker counters,
  * a clean SIGTERM drain that leaves no orphan processes.

Usage:
    python scripts/ci_serving_pool_smoke.py run OUT_DIR
    python scripts/ci_serving_pool_smoke.py validate OUT_DIR

``run`` writes OUT_DIR/pool-smoke.json with the measurements; ``validate``
asserts them so the failure mode in CI is a readable diff of the summary,
not a half-dead pool.
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

import numpy as np

# runnable as `python scripts/ci_serving_pool_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SUMMARY_NAME = "pool-smoke.json"

RECORDS = [{"x1": -0.25, "x2": 1.0, "cat": "a"},
           {"x1": 0.1, "x2": 9.5, "cat": "b"},
           {"x1": 2.0, "x2": 0.0, "cat": "c"},
           {"x1": None, "x2": 4.2, "cat": "a"}]


def _make_records(n, seed=7):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        x1 = float(rng.normal())
        x2 = float(rng.uniform(0, 10))
        recs.append({
            "y": 1.0 if (x1 + 0.2 * x2 + rng.normal() * 0.3) > 1.0 else 0.0,
            "x1": x1, "x2": x2, "cat": ["a", "b", "c"][i % 3],
        })
    return recs


def _post(port, body, content_type, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score", data=body,
        headers={"Content-Type": content_type})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get(port, path, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.read().decode()


def _metric(text, name, default=None):
    """The value of the UNLABELED sample of family ``name``."""
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        if head.rstrip() == name:
            return float(value)
    if default is None:
        raise AssertionError(f"metric {name} missing")
    return default


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def run(out_dir):
    from transmogrifai_tpu import types as T
    from transmogrifai_tpu.features import features_from_schema
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, ModelCandidate, grid)
    from transmogrifai_tpu.serving import wire
    from transmogrifai_tpu.serving.pool import ServingPool
    from transmogrifai_tpu.workflow import Workflow

    os.makedirs(out_dir, exist_ok=True)
    schema = {"y": T.RealNN, "x1": T.Real, "x2": T.Real, "cat": T.PickList}
    y, predictors = features_from_schema(schema, response="y")
    sel = BinaryClassificationModelSelector(models=[
        ModelCandidate(OpLogisticRegression(), grid(reg_param=[0.01]),
                       "OpLogisticRegression")])
    sel.set_input(y, transmogrify(predictors))
    model = (Workflow().set_input_records(_make_records(200))
             .set_result_features(sel.get_output()).train())

    bundle = os.path.join(out_dir, "model")
    os.environ["TRANSMOGRIFAI_AOT_LADDER_MAX"] = "16"
    model.save(bundle)

    pool = ServingPool(bundle, workers=2, max_batch=16, queue_bound=256,
                       run_dir=os.path.join(out_dir, "pool-run"))
    summary = {"bundle": bundle, "port": pool.port}
    pids = []
    try:
        t0 = time.time()
        pool.start()
        summary["bootWallS"] = round(time.time() - t0, 2)

        # -- columnar round-trip on the shared port, bitwise vs JSON -------
        status, jraw = _post(pool.port, json.dumps(RECORDS).encode(),
                             "application/json")
        assert status == 200
        jout = json.loads(jraw)["results"]
        pred_name = next(iter(jout[0]))
        status, craw = _post(pool.port, wire.encode_records(RECORDS),
                             wire.CONTENT_TYPE)
        assert status == 200
        arrays = wire.decode_response(craw)
        parity_fields = []
        for field in ("prediction", "probability_0", "probability_1"):
            cvals = np.asarray(arrays[f"{pred_name}.{field}"][0],
                               dtype=np.float64)
            jvals = np.array([r[pred_name][field] for r in jout],
                             dtype=np.float64)
            assert np.array_equal(cvals.view(np.uint64),
                                  jvals.view(np.uint64)), \
                f"columnar/JSON bit mismatch on {field}"
            parity_fields.append(field)
        summary["parityFields"] = parity_fields

        # spread a little more traffic so the shared port sees real load
        for _ in range(20):
            _post(pool.port, wire.encode_records(RECORDS),
                  wire.CONTENT_TYPE)

        # -- per-worker admin metrics: AOT absorbed every cold start -------
        per_worker = {}
        for slot in pool.slots:
            admin = slot.ready["adminPort"]
            text = _get(admin, "/metrics")
            per_worker[str(slot.worker_id)] = {
                "backendCompiles": _metric(
                    text, "transmogrifai_serving_backend_compiles_total"),
                "aotExecutablesLoaded": _metric(
                    text,
                    "transmogrifai_serving_aot_executables_loaded_total"),
                "requests": _metric(
                    text, "transmogrifai_serving_requests_total"),
                "pid": slot.ready["pid"],
            }
        summary["perWorker"] = per_worker

        # -- parent aggregation: counters sum across workers ---------------
        merged = pool.metrics()
        summary["aggregate"] = {
            "requests": _metric(merged,
                                "transmogrifai_serving_requests_total"),
            "poolWorkers": _metric(
                merged, "transmogrifai_serving_pool_workers"),
            "poolWorkersAlive": _metric(
                merged, "transmogrifai_serving_pool_workers_alive"),
        }
        summary["aggregateHasWorkerLabels"] = (
            'worker_id="0"' in merged and 'worker_id="1"' in merged)

        pids = [w["pid"] for w in per_worker.values()]
    finally:
        # -- clean SIGTERM drain, then prove nothing survived --------------
        t0 = time.time()
        pool.stop(grace_s=60.0)
        summary["stopWallS"] = round(time.time() - t0, 2)
    time.sleep(0.5)
    summary["orphanPids"] = [p for p in pids if _alive(p)]

    with open(os.path.join(out_dir, SUMMARY_NAME), "w") as fh:
        json.dump(summary, fh, indent=2)
    print(json.dumps(summary, indent=2))
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, SUMMARY_NAME)) as fh:
        s = json.load(fh)
    workers = s["perWorker"]
    assert len(workers) == 2, f"expected 2 workers: {workers}"
    for wid, w in workers.items():
        assert w["backendCompiles"] == 0, \
            f"worker {wid} compiled {w['backendCompiles']} programs"
        assert w["aotExecutablesLoaded"] > 0, \
            f"worker {wid} loaded no AOT executables"
    assert s["parityFields"], "no columnar/JSON parity fields checked"
    agg = s["aggregate"]
    assert agg["poolWorkers"] == 2 and agg["poolWorkersAlive"] == 2
    per_worker_requests = sum(w["requests"] for w in workers.values())
    assert agg["requests"] == per_worker_requests, \
        (f"aggregate requests {agg['requests']} != sum of per-worker "
         f"{per_worker_requests}")
    assert agg["requests"] > 0, "no traffic was recorded"
    assert s["aggregateHasWorkerLabels"], \
        "merged /metrics lost worker_id labels"
    assert s["orphanPids"] == [], f"orphan workers: {s['orphanPids']}"
    print(f"OK: 2 workers on port {s['port']}, 0 compiles each, "
          f"{agg['requests']:.0f} requests aggregated, bitwise columnar "
          f"parity on {s['parityFields']}, clean stop in "
          f"{s['stopWallS']}s with no orphans")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
