"""CI smoke for the one device data plane (ISSUE 19): sparse COO payloads
ride the same mesh/streaming/registry machinery as dense rows.

Phase A — mesh parity: a fresh subprocess runs a hashed-text CV sweep at an
indivisible row count (8 ∤ 2051) on a forced 8-virtual-device mesh, a second
subprocess runs the identical sweep single-device.  Validate requires

* the mesh sweep really sharded (``device_table.*`` stats populated, 8
  shards, mesh device gauge == 8),
* winner parity with metrics allclose and IDENTICAL racing prunes,
* ZERO degraded ``selector.racing`` / ``selector.mesh`` notes — the sparse
  carve-out is gone, not rerouted,
* peak host staging <= 2x the streaming chunk budget (the double-buffer
  bound now covers the three flat COO components).

Phase B — registry warm train: a cold subprocess train (single device — the
registry seam addresses unsharded leaves) populates the program registry and
the managed compile cache; a second FRESH subprocess re-train must report
``new_compiles_during_train == 0``: fleet-warm sparse trains.

Usage:
    python scripts/ci_sparse_mesh_smoke.py run OUT_DIR
    python scripts/ci_sparse_mesh_smoke.py validate OUT_DIR
"""

import json
import os
import subprocess
import sys

# runnable as `python scripts/ci_sparse_mesh_smoke.py` from the repo root
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SUMMARY_NAME = "sparse-mesh-smoke.json"
ROWS = int(os.environ.get("SPARSE_MESH_SMOKE_ROWS", "2051"))  # 8 ∤ 2051
CHUNK_BYTES = int(os.environ.get("SPARSE_MESH_SMOKE_CHUNK_BYTES", "65536"))
METRIC_RTOL = 1e-4

# sweep probe: hashed-text LR sweep; prints one JSON line with the winner,
# per-candidate metrics/prunes, degraded notes, and the sparse data-plane
# stats (device_table + streaming) so validate can pin the staging bound
_SWEEP_CHILD = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

n = int(sys.argv[1])
rng = np.random.default_rng(3)
half = 2000
vpos = np.asarray([f"pos{i}" for i in range(half)])
vneg = np.asarray([f"neg{i}" for i in range(half)])
y = rng.integers(0, 2, n)
toks_pos = vpos[rng.integers(0, half, size=(n, 8))]
toks_neg = vneg[rng.integers(0, half, size=(n, 8))]
txt = np.where(y[:, None] == 1, toks_pos, toks_neg)
records = [{"label": float(y[i]), "txt": " ".join(txt[i]), "x0": float(v)}
           for i, v in enumerate(rng.normal(size=n))]

from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models.linear import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrify import transmogrify
from transmogrifai_tpu.selector import (BinaryClassificationModelSelector,
                                        ModelCandidate, grid)
from transmogrifai_tpu.workflow import Workflow
from transmogrifai_tpu.parallel.device_table import device_table_stats
from transmogrifai_tpu.parallel.streaming import streaming_stats
from transmogrifai_tpu.parallel.memory import last_plan
from transmogrifai_tpu.telemetry import REGISTRY

label = FeatureBuilder.RealNN("label").as_response()
t = FeatureBuilder.Text("txt").as_predictor()
x0 = FeatureBuilder.Real("x0").as_predictor()
fv = transmogrify([t, x0], num_hashes=4096)
sel = BinaryClassificationModelSelector(models=[
    ModelCandidate(OpLogisticRegression(),
                   grid(reg_param=[0.001, 0.01, 0.03, 0.1, 0.3, 1.0],
                        max_iter=[30]),
                   "OpLogisticRegression")])
sel.set_input(label, fv)
wf = (Workflow().set_input_records(records)
      .set_result_features(sel.get_output()))
model = wf.train()
s = model.selected_model.summary
snap = REGISTRY.snapshot()
plan = last_plan()
print(json.dumps({
    "devices": len(jax.devices()),
    "mesh_devices_gauge": snap["gauges"].get("mesh.devices"),
    "chunk_bytes_gauge": snap["gauges"].get("mesh.chunk_bytes"),
    "winner": s.best_model_name,
    "metrics": {str(sorted(r.params.items())):
                float(r.metric_values[s.evaluation_metric])
                for r in s.validation_results},
    "raced_out": sorted(str(sorted(r.params.items()))
                        for r in s.validation_results if r.raced_out),
    "degraded_notes": sorted(
        f"{e.point}:{e.action}" for e in model.failure_log.events
        if e.action == "degraded"
        and e.point in ("selector.racing", "selector.mesh")),
    "device_table": device_table_stats(),
    "streaming": streaming_stats(),
    "memory_plan": plan.to_json() if plan is not None else None,
}))
"""

# registry probe: train the same sparse workflow with compile listeners on;
# argv[1] = bundle dir or "-" to skip saving (the warm re-train)
_TRAIN_CHILD = r"""
import json, sys, time
t0 = time.time()
from transmogrifai_tpu.profiling import (install_compile_listeners,
                                         new_compile_count)
install_compile_listeners()
import numpy as np
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models.linear import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrify import transmogrify
from transmogrifai_tpu.selector import (BinaryClassificationModelSelector,
                                        ModelCandidate, grid)
from transmogrifai_tpu.workflow import Workflow

rng = np.random.default_rng(7)
n = 160
y = rng.integers(0, 2, n)
vocab = np.asarray([f"w{i}" for i in range(400)])
toks = vocab[rng.integers(0, 400, size=(n, 6))]
records = [{"label": float(y[i]),
            "txt": " ".join(toks[i]) + (" hot" if y[i] else " cold"),
            "x0": float(v)}
           for i, v in enumerate(rng.normal(size=n))]
label = FeatureBuilder.RealNN("label").as_response()
t = FeatureBuilder.Text("txt").as_predictor()
x0 = FeatureBuilder.Real("x0").as_predictor()
fv = transmogrify([t, x0], num_hashes=4096)
sel = BinaryClassificationModelSelector(models=[
    ModelCandidate(OpLogisticRegression(),
                   grid(reg_param=[0.01, 0.1], max_iter=[25]),
                   "OpLogisticRegression")])
sel.set_input(label, fv)
wf = (Workflow().set_input_records(records)
      .set_result_features(sel.get_output()))
model = wf.train()
from transmogrifai_tpu.aot import pretrace_drain
pretrace_drain()
train_compiles = new_compile_count()
if sys.argv[1] != "-":
    model.save(sys.argv[1])
from transmogrifai_tpu.aot_registry import registry_stats
print(json.dumps({
    "new_compiles_during_train": train_compiles,
    "winner": model.selected_model.summary.best_model_name,
    "registry": registry_stats(),
    "wall_s": round(time.time() - t0, 1),
}))
"""


def _child(code, args, env):
    p = subprocess.run([sys.executable, "-c", code, *args],
                       capture_output=True, text=True, env=env, cwd=REPO,
                       timeout=600)
    line = next((ln for ln in reversed(p.stdout.splitlines())
                 if ln.startswith("{")), None)
    if p.returncode != 0 or not line:
        sys.stderr.write(p.stderr[-4000:])
        raise SystemExit(f"child failed (rc={p.returncode})")
    return json.loads(line)


def run(out_dir):
    os.makedirs(out_dir, exist_ok=True)
    base = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("TRANSMOGRIFAI_AOT_REGISTRY", "TRANSMOGRIFAI_NO_AOT",
              "TRANSMOGRIFAI_COMPILATION_CACHE", "XLA_FLAGS"):
        base.pop(k, None)

    # phase A: mesh parity.  Both runs force 8 virtual devices so numerics
    # differ only by the mesh policy, never by the platform config
    eight = dict(base, XLA_FLAGS="--xla_force_host_platform_device_count=8",
                 TRANSMOGRIFAI_DEVICE_CHUNK_BYTES=str(CHUNK_BYTES))
    single = _child(_SWEEP_CHILD, [str(ROWS)],
                    dict(eight, TRANSMOGRIFAI_TPU_MESH="0"))
    mesh = _child(_SWEEP_CHILD, [str(ROWS)],
                  dict(eight, TRANSMOGRIFAI_TPU_MESH="1"))

    # phase B: registry-warm sparse train.  Single device: the registry
    # seam addresses unsharded leaves (sharded grid calls bypass it)
    registry_root = os.path.join(out_dir, "registry")
    reg_env = dict(base, TRANSMOGRIFAI_TPU_MESH="0",
                   TRANSMOGRIFAI_AOT_LADDER_MAX="16",
                   TRANSMOGRIFAI_AOT_REGISTRY=registry_root,
                   TRANSMOGRIFAI_COMPILE_CACHE=os.path.join(
                       registry_root, "compile-cache"))
    cold = _child(_TRAIN_CHILD, [os.path.join(out_dir, "model")], reg_env)
    warm = _child(_TRAIN_CHILD, ["-"], reg_env)

    summary = {
        "rows": ROWS,
        "chunk_bytes": CHUNK_BYTES,
        "single": single,
        "mesh": mesh,
        "cold": cold,
        "warm": warm,
    }
    path = os.path.join(out_dir, SUMMARY_NAME)
    with open(path, "w") as fh:
        json.dump(summary, fh, indent=2)
    print(f"wrote {path}: winner {mesh['winner']} "
          f"(single {single['winner']}), "
          f"{mesh['device_table']['shards']} sparse shards, warm train "
          f"{warm['new_compiles_during_train']} compiles "
          f"(cold {cold['new_compiles_during_train']})")
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, SUMMARY_NAME)) as fh:
        s = json.load(fh)
    single, mesh, cold, warm = s["single"], s["mesh"], s["cold"], s["warm"]

    # the sparse sweep really sharded — not a silent single-device fallback
    assert mesh["devices"] == 8 and mesh["mesh_devices_gauge"] == 8, mesh
    dt = mesh["device_table"]
    assert dt["tables"] > 0 and dt["shards"] >= 8, dt
    assert dt["nnz_streamed"] > 0, dt
    assert single["device_table"]["tables"] == 0, \
        "control sweep sharded too — parity check is vacuous"
    plan = mesh["memory_plan"]
    assert plan and plan.get("nnz"), \
        f"mesh sweep planned without an nnz budget: {plan}"

    # winner parity, metric agreement, identical racing prunes
    assert mesh["winner"] == single["winner"], (mesh["winner"],
                                                single["winner"])
    assert mesh["metrics"].keys() == single["metrics"].keys()
    for k, v0 in single["metrics"].items():
        v1 = mesh["metrics"][k]
        assert abs(v1 - v0) <= METRIC_RTOL * max(1.0, abs(v0)), (k, v0, v1)
    assert mesh["raced_out"] == single["raced_out"], (single["raced_out"],
                                                      mesh["raced_out"])
    assert mesh["raced_out"], "racing pruned nothing — screen not exercised"

    # honest-degrade bar: ZERO degraded racing/mesh notes on the mesh run
    assert mesh["degraded_notes"] == [], mesh["degraded_notes"]

    # the transfer bound covers sparse: peak staging <= 2x the chunk budget
    st = mesh["streaming"]
    budget = mesh["chunk_bytes_gauge"] or s["chunk_bytes"]
    assert st["bytes_streamed"] > 0 and st["chunks"] > 0, st
    assert st["peak_staging_bytes"] <= 2 * budget, (
        f"peak host staging {st['peak_staging_bytes']} B > {2 * budget} B "
        "(2x chunk) — sparse streaming is buffering more than two chunks")

    # registry-warm sparse train: the compile ledger
    assert cold["registry"]["publishes"] > 0 or cold["registry"]["hits"] > 0,\
        f"cold train neither published nor hit: {cold['registry']}"
    assert cold["new_compiles_during_train"] > 0, \
        "cold sparse train compiled nothing — the warm assert is vacuous"
    assert warm["new_compiles_during_train"] == 0, \
        f"registry-warm fresh-process sparse train compiled " \
        f"{warm['new_compiles_during_train']} programs"
    assert warm["registry"]["hits"] > 0, \
        f"warm train never hit the registry: {warm['registry']}"
    assert warm["winner"] == cold["winner"] == mesh["winner"], \
        (cold["winner"], warm["winner"], mesh["winner"])

    print(f"OK: winner {mesh['winner']} on both layouts, "
          f"{len(mesh['raced_out'])}/{len(mesh['metrics'])} raced out "
          f"identically, {dt['shards']} sparse shards / "
          f"{dt['nnz_streamed']} entries streamed, peak staging "
          f"{st['peak_staging_bytes']} B <= {2 * budget} B, warm sparse "
          f"train {warm['new_compiles_during_train']} compiles "
          f"(cold {cold['new_compiles_during_train']})")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
