"""CI smoke for the sparse feature subsystem (ISSUE 7): train + score a
5k-row x 50k-hashed-column text workflow in ONE process and assert the
peak RSS stays well under the dense ``[N, num_hashes]`` matrix that the
pre-sparse path would have materialized — the memory bound IS the feature.

Usage:
    python scripts/ci_sparse_smoke.py run OUT_DIR       # train+score+export
    python scripts/ci_sparse_smoke.py validate OUT_DIR  # parse + assert

``run`` reuses the ``text_sparse`` bench workload so CI uploads the same
one-JSON-line artifact shape the bench emits; ``validate`` asserts the
planted-vocab accuracy, a non-trivial nnz/density, and the peak-RSS bound.
"""

import json
import os
import sys

# runnable as `python scripts/ci_sparse_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROWS = int(os.environ.get("SPARSE_SMOKE_ROWS", "5000"))
HASHES = int(os.environ.get("SPARSE_SMOKE_HASHES", "50000"))
# the 5k x 50k dense equivalent is ~1 GB; the sparse run (including the
# ~250 MB Python+JAX process baseline) must stay under 60% of it
RSS_BOUND_FRACTION = 0.6


def run(out_dir):
    os.environ["BENCH_SPARSE_HASHES"] = str(HASHES)
    import bench

    os.makedirs(out_dir, exist_ok=True)
    record = bench.run_text_sparse(ROWS, False, "cpu")
    path = os.path.join(out_dir, "sparse-bench.json")
    with open(path, "w") as fh:
        fh.write(json.dumps(record) + "\n")
    aux = record["aux"]
    print(f"wrote {path}: train {record['value']}s, "
          f"score {aux['score_wall_s']}s, acc {aux['train_accuracy']}, "
          f"nnz {aux['nnz_total']}, peak RSS {aux['peak_rss_mb']} MB "
          f"vs dense-equivalent {aux['dense_equivalent_mb']} MB")
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, "sparse-bench.json")) as fh:
        record = json.loads(fh.readline())
    aux = record["aux"]
    assert aux["rows"] == ROWS and aux["num_hashes"] == HASHES, aux
    # planted disjoint pos/neg vocab: the sparse LR must separate it
    assert aux["train_accuracy"] >= 0.99, aux
    assert aux["score_rows_per_s"] > 0, aux
    # the hash block really was sparse: nnz present, density far below 1
    assert aux["nnz_total"] > 0, aux
    assert 0 < aux["density"] < 0.01, aux
    # THE acceptance bound: peak memory scales with nnz, not rows x cols —
    # a dense [N, num_hashes] materialization anywhere in train or score
    # would alone exceed this fraction of the dense-equivalent bytes
    bound_mb = RSS_BOUND_FRACTION * aux["dense_equivalent_mb"]
    assert aux["peak_rss_mb"] < bound_mb, (
        f"peak RSS {aux['peak_rss_mb']} MB >= {bound_mb} MB "
        f"({RSS_BOUND_FRACTION} x dense equivalent "
        f"{aux['dense_equivalent_mb']} MB) — a dense [N, num_hashes] "
        "materialization has crept back into the sparse path")
    print(f"OK: peak RSS {aux['peak_rss_mb']} MB < {bound_mb:.0f} MB bound, "
          f"nnz={aux['nnz_total']}, density={aux['density']}, "
          f"acc={aux['train_accuracy']}")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
