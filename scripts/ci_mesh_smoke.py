"""CI smoke for mesh-sharded CV sweeps (ISSUE 10): run the SAME small
selector sweep unsharded and then on a forced 8-virtual-device mesh with
chunked host→device streaming, in one process, and assert

* a mesh really was constructed (device gauge == 8, streamed arrays > 0),
* the sharded sweep picks the same winner with metrics allclose,
* racing pruned the SAME candidates with ZERO degraded ``selector.racing``
  notes (racing is un-gated on the mesh path now),
* peak host staging stayed <= 2x the configured chunk budget (the
  double-buffering bound that makes streaming O(chunk), not O(matrix)),
* a Perfetto-loadable trace with ``mesh.stream_chunk`` spans was written
  (uploaded as a CI artifact next to this record).

Usage:
    python scripts/ci_mesh_smoke.py run OUT_DIR       # sweep twice + export
    python scripts/ci_mesh_smoke.py validate OUT_DIR  # parse + assert
"""

import json
import os
import sys

# runnable as `python scripts/ci_mesh_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROWS = int(os.environ.get("MESH_SMOKE_ROWS", "4099"))  # 8 ∤ 4099 → pad path
CHUNK_BYTES = int(os.environ.get("MESH_SMOKE_CHUNK_BYTES", "2048"))
METRIC_RTOL = 1e-4


def _sweep(n, d=6):
    """LR-only 6-point sweep; returns winner/metrics/raced/degraded-count."""
    import numpy as np

    from transmogrifai_tpu.columns import Column, ColumnBatch
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, ModelCandidate, grid)
    from transmogrifai_tpu.types import RealNN
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
    label = FeatureBuilder.RealNN("label").as_response()
    feats = [FeatureBuilder.RealNN(f"f{i}").as_predictor() for i in range(d)]
    checked = label.sanity_check(transmogrify(feats),
                                 remove_bad_features=True)
    sel = BinaryClassificationModelSelector(models=[
        ModelCandidate(OpLogisticRegression(),
                       grid(reg_param=[0.001, 0.01, 0.03, 0.1, 0.3, 1.0]),
                       "OpLogisticRegression"),
    ])
    sel.set_input(label, checked)
    pred = sel.get_output()
    cols = {"label": Column(RealNN, y)}
    for i in range(d):
        cols[f"f{i}"] = Column(RealNN, X[:, i])
    wf = Workflow().set_input_batch(ColumnBatch(cols, n)) \
                   .set_result_features(pred)
    model = wf.train()
    s = model.selected_model.summary
    return {
        "winner": s.best_model_name,
        "metrics": {str(sorted(r.params.items())):
                    float(r.metric_values[s.evaluation_metric])
                    for r in s.validation_results},
        "raced_out": sorted(str(sorted(r.params.items()))
                            for r in s.validation_results if r.raced_out),
        "racing_degraded": sum(
            1 for e in model.failure_log.events
            if e.action == "degraded" and e.point == "selector.racing"),
    }


def run(out_dir):
    # 8 virtual devices must exist before jax initialises
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["TRANSMOGRIFAI_DEVICE_CHUNK_BYTES"] = str(CHUNK_BYTES)

    import jax
    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 8, jax.devices()

    from transmogrifai_tpu.parallel.streaming import (reset_streaming_stats,
                                                      streaming_stats)
    from transmogrifai_tpu.telemetry import REGISTRY, Tracer, use_tracer

    os.makedirs(out_dir, exist_ok=True)

    os.environ["TRANSMOGRIFAI_TPU_MESH"] = "0"
    base = _sweep(ROWS)

    os.environ["TRANSMOGRIFAI_TPU_MESH"] = "1"
    reset_streaming_stats()
    tracer = Tracer(run_name=f"ci_mesh_smoke:{ROWS}")
    with use_tracer(tracer):
        mesh = _sweep(ROWS)
    trace_path = os.path.join(out_dir, "mesh-trace.json")
    tracer.export_chrome_trace(trace_path)

    snap = REGISTRY.snapshot()
    record = {
        "rows": ROWS,
        "devices": len(jax.devices()),
        "chunk_bytes": CHUNK_BYTES,
        "unsharded": base,
        "mesh": mesh,
        "mesh_devices_gauge": snap["gauges"].get("mesh.devices"),
        "streaming": streaming_stats(),
        "host_to_device_bytes_total": snap["counters"].get(
            "host_to_device_bytes_total"),
        "stream_chunk_spans": sum(1 for s in tracer.spans
                                  if s.name == "mesh.stream_chunk"),
    }
    path = os.path.join(out_dir, "mesh-smoke.json")
    with open(path, "w") as fh:
        fh.write(json.dumps(record) + "\n")
    print(f"wrote {path}: winner {mesh['winner']} "
          f"(unsharded {base['winner']}), "
          f"{record['streaming']['chunks']} chunks, peak staging "
          f"{record['streaming']['peak_staging_bytes']} B, trace "
          f"{record['stream_chunk_spans']} stream spans")
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, "mesh-smoke.json")) as fh:
        record = json.loads(fh.readline())
    base, mesh, st = record["unsharded"], record["mesh"], record["streaming"]

    # the mesh path really engaged — not a silent single-device fallback
    assert record["mesh_devices_gauge"] == record["devices"] == 8, record
    assert st["arrays"] > 0 and st["chunks"] > st["arrays"], st
    assert record["host_to_device_bytes_total"] and \
        record["host_to_device_bytes_total"] >= st["bytes_streamed"], record

    # winner parity and metric agreement across sharding layouts
    assert mesh["winner"] == base["winner"], (mesh["winner"], base["winner"])
    assert mesh["metrics"].keys() == base["metrics"].keys()
    for k, v0 in base["metrics"].items():
        v1 = mesh["metrics"][k]
        assert abs(v1 - v0) <= METRIC_RTOL * max(1.0, abs(v0)), (k, v0, v1)

    # racing ran un-degraded on the mesh and pruned the same candidates
    assert mesh["racing_degraded"] == 0, mesh
    assert mesh["raced_out"] == base["raced_out"], (base["raced_out"],
                                                    mesh["raced_out"])
    assert mesh["raced_out"], "racing pruned nothing — screen not exercised"

    # THE transfer bound: double buffering keeps host staging O(chunk)
    bound = 2 * record["chunk_bytes"]
    assert st["peak_staging_bytes"] <= bound, (
        f"peak host staging {st['peak_staging_bytes']} B > {bound} B "
        "(2x chunk) — streaming is buffering more than two chunks")

    # the trace artifact is loadable and shows the chunked transfers
    with open(os.path.join(out_dir, "mesh-trace.json")) as fh:
        doc = json.load(fh)
    names = [e.get("name") for e in doc.get("traceEvents", [])]
    assert record["stream_chunk_spans"] > 0
    assert names.count("mesh.stream_chunk") == record["stream_chunk_spans"]
    assert "mesh.stream_to_device" in names, sorted(set(names))[:20]

    print(f"OK: winner {mesh['winner']} on both paths, "
          f"{len(mesh['raced_out'])}/{len(mesh['metrics'])} raced out "
          f"identically, peak staging {st['peak_staging_bytes']} B <= "
          f"{bound} B, {record['stream_chunk_spans']} stream-chunk spans")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
