"""Closed-loop data-fuzz chaos drill for the poison-data firewall.

Fuzzes records against BOTH halves of the lifecycle:

* **train** — 120 clean rows plus fatally-poisoned rows (garbage
  strings, ±inf/NaN, nested maps, huge strings, hostile encodings) at
  pinned indices must quarantine EXACTLY the poison rows and fit a
  winner bitwise-identical to a control trained on the clean subset
  directly; a poison storm past ``maxQuarantineFraction`` must abort
  with the typed ``DataQualityError``;
* **serve** — N concurrent closed-loop clients storm a LIVE
  ``SO_REUSEPORT`` pool with a seeded mix of clean records and fuzzed
  records (missing fields, unknown extras, wrong types, ±inf/NaN
  storms, huge strings, mixed encodings) plus byte-corrupted columnar
  bodies, and every outcome must be classified:

  - zero 5xx, zero hangs, zero connection drops;
  - fuzz rejections are TYPED ONLY: 422 with a violation list drawn
    from the taxonomy (or 400 for structurally corrupt columnar
    bodies), never a bare error;
  - tolerated fuzz (missing/extra fields under ``coerce``) scores 200;
  - clean columnar requests stay bitwise-equal to a pre-storm control;
  - quarantine accounting closes: the pool's merged
    ``quality_quarantined_records_total`` delta equals the number of
    records the clients saw rejected.

Artifacts written to ``--out-dir``: ``outcomes-data.jsonl`` (one line
per request), ``metrics-data.txt`` (final merged ``/metrics``), and
``summary-data.json`` (the verdict, also printed).  Exit 0 on a clean
pass, 1 on any contract violation.

Usage:
    python scripts/chaos_data.py --out-dir /tmp/chaos_data \
        [--clients 12] [--requests 25] [--seed 0]
"""

import argparse
import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np

# runnable as `python scripts/chaos_data.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

POISON_IDX = (5, 25, 45, 65, 85, 105)

# fuzz categories → (mutator, statuses the firewall may answer with)
FUZZ = {
    "missing_field": (lambda rec, rng: _drop(rec, "x1"), {200}),
    "extra_field": (lambda rec, rng: {**rec, "zzz_unknown": "?"}, {200}),
    "coercible_type": (lambda rec, rng: {**rec, "x1": str(rec["x1"])},
                       {200}),
    "wrong_type": (lambda rec, rng: {**rec, "x1": "garbage"}, {422}),
    "nested_map": (lambda rec, rng: {**rec, "x1": {"a": {"b": 1}}}, {422}),
    "nan": (lambda rec, rng: {**rec, "x1": float("nan")}, {422}),
    "pos_inf": (lambda rec, rng: {**rec, "x1": float("inf")}, {422}),
    "neg_inf": (lambda rec, rng: {**rec, "x2": -float("inf")}, {422}),
    "overflow_literal": (lambda rec, rng: {**rec, "x1": "1e400"}, {422}),
    "huge_string": (lambda rec, rng: {**rec, "x1": "A" * 100_000}, {422}),
    "mixed_encoding": (lambda rec, rng: {**rec, "x1": "Ünïcödé-€-\x00\x7f"},
                       {422}),
}


def _drop(rec, key):
    out = dict(rec)
    out.pop(key, None)
    return out


def _make_records(n=120, seed=11):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        x1 = float(rng.normal())
        x2 = float(rng.uniform(0, 10))
        recs.append({
            "y": 1.0 if (x1 + 0.2 * x2 + rng.normal() * 0.3) > 1.0 else 0.0,
            "x1": x1, "x2": x2,
        })
    return recs


def _train(records):
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrify import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, ModelCandidate, grid)
    from transmogrifai_tpu.workflow import Workflow
    y = FeatureBuilder.RealNN("y").as_response()
    x1 = FeatureBuilder.Real("x1").as_predictor()
    x2 = FeatureBuilder.Real("x2").as_predictor()
    sel = BinaryClassificationModelSelector(models=[
        ModelCandidate(OpLogisticRegression(), grid(reg_param=[0.01]),
                       "LR")])
    sel.set_input(y, transmogrify([x1, x2]))
    pred = sel.get_output()
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    return model, pred.name


def _post(port, body, content_type, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score", data=body,
        headers={"Content-Type": content_type})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _metric(text, name, default=0.0):
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        line = line.split(" # ")[0]        # drop any exemplar suffix
        head, _, value = line.rpartition(" ")
        if head.rstrip() == name:
            return float(value)
    return default


def _fuzz_train(summary):
    """Train under fatal poison; require exact quarantine + winner parity
    and the typed abort past the fraction limit."""
    from transmogrifai_tpu.local import score_function
    from transmogrifai_tpu.quality import DataQualityError
    from transmogrifai_tpu.telemetry import REGISTRY

    clean = _make_records()
    fatal = ["garbage", float("nan"), float("inf"), {"a": 1},
             "B" * 100_000, "Ünïcödé-€-\x00"]
    poisoned = list(clean)
    for slot, idx in enumerate(POISON_IDX):
        poisoned[idx] = {**clean[idx], "x1": fatal[slot % len(fatal)]}
    control_recs = [r for i, r in enumerate(clean) if i not in POISON_IDX]

    before = REGISTRY.counters().get("quality.rows_quarantined_total", 0)
    m_poison, pred_p = _train(poisoned)
    after = REGISTRY.counters().get("quality.rows_quarantined_total", 0)
    summary["train"] = {"rowsQuarantined": after - before,
                        "poisonInjected": len(POISON_IDX)}

    m_control, pred_c = _train(control_recs)
    fp, fc = score_function(m_poison), score_function(m_control)
    parity = True
    for v in (-2.0, -0.5, 0.0, 0.5, 2.0):
        rec = {"x1": v, "x2": 10.0 - abs(v)}
        a, b = fp(rec)[pred_p], fc(rec)[pred_c]
        for field in ("prediction", "probability_0", "probability_1"):
            parity &= bool(np.float64(a[field]).view(np.uint64)
                           == np.float64(b[field]).view(np.uint64))
    summary["train"]["winnerBitwiseParity"] = parity

    storm = [({**r, "x1": "junk"} if i < 40 else r)
             for i, r in enumerate(clean)]
    try:
        _train(storm)
        summary["train"]["stormAbort"] = None
    except DataQualityError as e:
        summary["train"]["stormAbort"] = {"quarantined": e.quarantined,
                                          "total": e.total}
    return m_poison


def _fuzz_serve(model, out_dir, clients, requests, seed, summary):
    """Storm a live pool with fuzzed + clean + corrupt-columnar traffic."""
    from transmogrifai_tpu.serving import wire
    from transmogrifai_tpu.serving.pool import ServingPool

    bundle = os.path.join(out_dir, "model")
    model.save(bundle)
    pool = ServingPool(bundle, workers=1, max_batch=8, queue_bound=256,
                       run_dir=os.path.join(out_dir, "pool-run"))
    outcomes = []
    lock = threading.Lock()
    clean_rec = {"x1": 0.4, "x2": 5.0}
    try:
        pool.start()
        port = pool.port
        clean_body = wire.encode_records([clean_rec])
        status, control_bytes = _post(port, clean_body, wire.CONTENT_TYPE)
        summary["serve"] = {"controlStatus": status}

        categories = sorted(FUZZ)

        def client(cid):
            rng = np.random.default_rng(seed * 1000 + cid)
            for i in range(requests):
                roll = rng.random()
                out = {"client": cid, "i": i}
                try:
                    if roll < 0.35:               # clean columnar
                        out["kind"] = "clean"
                        code, body = _post(port, clean_body,
                                           wire.CONTENT_TYPE, timeout=90)
                        out["status"] = code
                        out["bitwise"] = (body == control_bytes)
                    elif roll < 0.45:             # corrupt columnar bytes
                        out["kind"] = "corrupt_columnar"
                        mutated = bytearray(clean_body)
                        for _ in range(int(rng.integers(1, 4))):
                            pos = int(rng.integers(0, len(mutated)))
                            mutated[pos] = int(rng.integers(0, 256))
                        code, body = _post(port, bytes(mutated),
                                           wire.CONTENT_TYPE, timeout=90)
                        out["status"] = code
                    else:                          # record fuzz, JSON path
                        cat = categories[int(rng.integers(0,
                                                          len(categories)))]
                        mutator, allowed = FUZZ[cat]
                        out["kind"] = cat
                        rec = mutator(dict(clean_rec), rng)
                        code, body = _post(
                            port, json.dumps(rec).encode(),
                            "application/json", timeout=90)
                        out["status"] = code
                        if code == 422:
                            payload = json.loads(body)
                            out["violationKinds"] = sorted(
                                {v["kind"]
                                 for v in payload.get("violations", [])})
                except Exception as e:           # hang / drop / reset
                    out["status"] = None
                    out["error"] = f"{type(e).__name__}: {e}"
                with lock:
                    outcomes.append(out)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        summary["serve"]["expected"] = clients * requests
        summary["serve"]["completed"] = len(outcomes)

        merged = pool.metrics()
        with open(os.path.join(out_dir, "metrics-data.txt"), "w") as fh:
            fh.write(merged)
        summary["serve"]["quarantinedMetric"] = _metric(
            merged, "transmogrifai_serving_quality_quarantined_records_total")
        summary["serve"]["violationsMetric"] = _metric(
            merged, "transmogrifai_serving_quality_violations_total")
    finally:
        pool.stop(grace_s=60.0)

    with open(os.path.join(out_dir, "outcomes-data.jsonl"), "w") as fh:
        for out in outcomes:
            fh.write(json.dumps(out) + "\n")
    return outcomes


def _verdict(outcomes, summary):
    from transmogrifai_tpu.quality import VIOLATION_KINDS
    violations = []
    t = summary["train"]
    if t["rowsQuarantined"] != t["poisonInjected"]:
        violations.append(
            f"train quarantined {t['rowsQuarantined']} rows, injected "
            f"{t['poisonInjected']}")
    if not t["winnerBitwiseParity"]:
        violations.append(
            "poisoned-train winner drifted from the clean-subset control")
    if not t["stormAbort"] or t["stormAbort"]["quarantined"] != 40:
        violations.append(
            f"no typed DataQualityError past maxQuarantineFraction: "
            f"{t['stormAbort']}")

    s = summary["serve"]
    if s["controlStatus"] != 200:
        violations.append(f"pre-storm control scored {s['controlStatus']}")
    if s["completed"] != s["expected"]:
        violations.append(
            f"{s['expected'] - s['completed']} requests never completed")
    rejected_records = 0
    by_kind = {}
    for out in outcomes:
        code = out["status"]
        kind = out["kind"]
        by_kind.setdefault(kind, {}).setdefault(str(code), 0)
        by_kind[kind][str(code)] += 1
        if code is None:
            violations.append(f"hang/drop: {out}")
        elif code >= 500:
            violations.append(f"5xx: {out}")
        elif kind == "clean":
            if code != 200:
                violations.append(f"clean request rejected: {out}")
            elif not out.get("bitwise"):
                violations.append(
                    f"clean response drifted from pre-storm control: {out}")
        elif kind == "corrupt_columnar":
            if code not in (200, 400, 422):
                violations.append(f"corrupt columnar unclassified: {out}")
            if code in (400, 422):
                rejected_records += 1
        else:
            _, allowed = FUZZ[kind]
            if code not in allowed:
                violations.append(
                    f"fuzz {kind} answered {code}, allowed {allowed}")
            if code == 422:
                rejected_records += 1
                kinds = out.get("violationKinds") or []
                if not kinds or any(k not in VIOLATION_KINDS
                                    for k in kinds):
                    violations.append(
                        f"422 without taxonomy violations: {out}")
    summary["serve"]["outcomesByKind"] = by_kind
    summary["serve"]["rejectedSeenByClients"] = rejected_records
    # corrupt columnar bodies are rejected at decode (400) BEFORE the
    # quarantine counter; only 422s count against it
    fuzz_422 = sum(
        1 for out in outcomes
        if out["kind"] not in ("clean", "corrupt_columnar")
        and out["status"] == 422)
    if summary["serve"]["quarantinedMetric"] != fuzz_422:
        violations.append(
            f"quarantine accounting open: metric "
            f"{summary['serve']['quarantinedMetric']} != {fuzz_422} "
            f"client-observed 422s")
    summary["violations"] = violations
    summary["pass"] = not violations
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--clients", type=int, default=12)
    ap.add_argument("--requests", type=int, default=25)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    os.makedirs(args.out_dir, exist_ok=True)

    summary = {"seed": args.seed, "clients": args.clients,
               "requests": args.requests}
    model = _fuzz_train(summary)
    outcomes = _fuzz_serve(model, args.out_dir, args.clients,
                           args.requests, args.seed, summary)
    _verdict(outcomes, summary)

    with open(os.path.join(args.out_dir, "summary-data.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    print(json.dumps(summary, indent=2))
    if not summary["pass"]:
        print(f"FAIL: {len(summary['violations'])} contract violations",
              file=sys.stderr)
        return 1
    print("OK: poison-train parity + typed-rejection-only fuzz storm with "
          "closed quarantine accounting")
    return 0


if __name__ == "__main__":
    sys.exit(main())
