"""CI smoke for the memory governor (ISSUE 15): prove, in one process,
that device-memory pressure degrades instead of killing the sweep —

* a tiny forced device budget makes the preflight planner emit SMALLER
  transfer chunks than the 256 MB default (the plan reacts to the budget,
  it is not a constant);
* device OOM classifies as MEMORY EXHAUSTION and NOT device loss, and
  DEVICE_LOST/UNAVAILABLE classify as device loss and NOT memory
  exhaustion — the two recovery paths stay disjoint;
* an injected ``memory.device_oom`` mid-sweep walks the shrink-and-retry
  ladder and CONVERGES: the resumed sweep selects the IDENTICAL winner
  (name + params) as the unpressured control, replaying checkpointed
  families instead of refitting them;
* ZERO worker deaths: the mesh never shrinks (``device_cap`` stays None)
  — OOM recovery is a work-shape change, not a topology change;
* every shrink lands in the failure log (``degraded`` at
  ``memory.device_oom``) and telemetry (``memory.shrinks_total``), and a
  ladder that runs dry surfaces as a typed ``MemoryExhaustedError`` with
  the attempted plan attached.

Usage:
    python scripts/ci_memory_smoke.py run OUT_DIR       # drill + record
    python scripts/ci_memory_smoke.py validate OUT_DIR  # parse + assert
"""

import json
import os
import sys

# the sweep needs the virtual 8-device CPU topology; must be set before
# jax initializes (mirrors tests/conftest.py)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# runnable as `python scripts/ci_memory_smoke.py` from the repo root; the
# scripts dir itself is added so the sweep fixture is shared with the chaos
# harness instead of forked
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

ROWS = int(os.environ.get("MEMORY_SMOKE_ROWS", "560"))
SEED = int(os.environ.get("MEMORY_SMOKE_SEED", "0"))


def run(out_dir):
    import jax
    jax.config.update("jax_platforms", "cpu")

    from chaos_train import _two_family_sweep
    from transmogrifai_tpu.parallel import memory as mem
    from transmogrifai_tpu.parallel import supervisor as sup
    from transmogrifai_tpu.parallel.streaming import device_chunk_bytes
    from transmogrifai_tpu.resilience import FaultInjector, inject_faults
    from transmogrifai_tpu.telemetry import REGISTRY

    os.makedirs(out_dir, exist_ok=True)

    # 1. tiny forced budget → the preflight plan shrinks its chunks
    default_chunk = device_chunk_bytes()
    os.environ["TRANSMOGRIFAI_DEVICE_MEM_BYTES"] = str(32 << 20)
    try:
        plan = mem.plan_sweep_memory(rows=1_000_000, cols=32, folds=3,
                                     grid_width=8, devices=8)
        planner = {"budget_bytes": plan.device_budget,
                   "default_chunk_bytes": default_chunk,
                   "plan": plan.to_json()}
    finally:
        os.environ.pop("TRANSMOGRIFAI_DEVICE_MEM_BYTES", None)

    # 2. classifier disjointness on the real allocator message shapes
    oom = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                       "allocate 68719476736 bytes.")
    lost = RuntimeError("DEVICE_LOST: device lost: TPU worker disappeared")
    typed = mem.as_memory_exhausted(oom)
    classify = {
        "oom_is_memory_exhaustion": mem.is_memory_exhaustion(oom),
        "oom_is_device_loss": sup.is_device_loss(oom),
        "device_lost_is_memory_exhaustion": mem.is_memory_exhaustion(lost),
        "device_lost_is_device_loss": sup.is_device_loss(lost),
        "typed_error": type(typed).__name__,
        "typed_has_plan": typed.plan is not None,
    }

    # 3. injected device OOM mid-sweep → shrink ladder + checkpoint resume
    #    converge on the control winner; the mesh never shrinks
    os.environ["TRANSMOGRIFAI_TPU_MESH"] = "1"
    sweep_dir = os.path.join(out_dir, "sweep")
    try:
        sup.reset_surviving_devices()
        mem.reset_memory_degrade()
        w0, p0, _ = _two_family_sweep(ROWS, SEED)
        shrinks_before = REGISTRY.counter("memory.shrinks_total").value
        with inject_faults(FaultInjector(
                fail_keys={"memory.device_oom": ["LR_B:score:o0"]},
                seed=SEED)) as inj:
            w1, p1, sweep_log = _two_family_sweep(ROWS, SEED,
                                                  resume_from=sweep_dir)
        sweep_actions = [(e.action, e.point) for e in sweep_log]
        drill = {
            "control_winner": w0, "control_params": p0,
            "pressured_winner": w1, "pressured_params": p1,
            "same_winner": bool(w1 == w0 and p1 == p0),
            "oom_fired": ("memory.device_oom", "LR_B:score:o0") in inj.fired,
            "shrink_recorded": ("degraded",
                                "memory.device_oom") in sweep_actions,
            "resumed_from_checkpoint": any(
                a == "resumed" for a, _ in sweep_actions),
            "shrinks_total_delta": REGISTRY.counter(
                "memory.shrinks_total").value - shrinks_before,
            "oom_attempt_budget": mem.max_oom_recoveries(),
            "final_shrink_level": mem.shrink_level(),
            "device_cap": sup.device_cap(),
        }
    finally:
        sup.reset_surviving_devices()
        mem.reset_memory_degrade()
        os.environ.pop("TRANSMOGRIFAI_TPU_MESH", None)

    record = {"rows": ROWS, "seed": SEED, "planner": planner,
              "classify": classify, "drill": drill}
    path = os.path.join(out_dir, "memory-smoke.json")
    with open(path, "w") as fh:
        fh.write(json.dumps(record) + "\n")
    print(f"wrote {path}: plan chunk {plan.chunk_bytes} bytes under a "
          f"{32 << 20}-byte budget (default {default_chunk}), injected OOM "
          f"-> winner {w1} (control {w0}), shrinks "
          f"{drill['shrinks_total_delta']}, device_cap {sup.device_cap()}")
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, "memory-smoke.json")) as fh:
        record = json.loads(fh.readline())

    # the plan reacted to the tiny budget: strictly smaller chunks
    pl = record["planner"]
    assert pl["budget_bytes"] == 32 << 20, pl
    assert pl["plan"]["chunkBytes"] < pl["default_chunk_bytes"], pl
    assert pl["plan"]["estDeviceBytes"] > 0, pl

    # classification is typed and the two recovery routes are disjoint
    cl = record["classify"]
    assert cl["oom_is_memory_exhaustion"] and not cl["oom_is_device_loss"], cl
    assert (cl["device_lost_is_device_loss"]
            and not cl["device_lost_is_memory_exhaustion"]), cl
    assert cl["typed_error"] == "MemoryExhaustedError", cl
    assert cl["typed_has_plan"], cl

    # the drill converged: same winner, within the attempt budget, every
    # shrink recorded, zero worker deaths (mesh untouched)
    dr = record["drill"]
    assert dr["oom_fired"], dr
    assert dr["same_winner"], dr
    assert dr["shrink_recorded"], dr
    assert dr["resumed_from_checkpoint"], dr
    assert dr["shrinks_total_delta"] >= 1, dr
    assert 1 <= dr["final_shrink_level"] <= dr["oom_attempt_budget"], dr
    assert dr["device_cap"] is None, dr

    print(f"OK: tiny budget -> {pl['plan']['chunkBytes']}-byte chunks "
          f"(default {pl['default_chunk_bytes']}), OOM typed + disjoint "
          f"from device loss, injected OOM converged to the control winner "
          f"{dr['control_winner']} after {dr['shrinks_total_delta']} "
          f"shrink(s) with the mesh untouched")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
