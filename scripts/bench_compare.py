#!/usr/bin/env python
"""Diff a fresh bench run against the standing perf record.

``bench.py`` appends every aggregate run to ``BENCH_STANDING.json``; this
script is the regression gate between the two: it compares a fresh run's
per-workload headline (wall seconds or rows/s, direction-aware) and the
stability counters that historically precede a wall regression
(``new_compiles_during_train``, ``selector_compile_s``, memory shrink
level) against the newest standing run, within tolerances, and exits 1 on
any regression.  CI runs it as a non-blocking step with the report
uploaded as an artifact, so a perf cliff is visible on the PR without a
flaky runner blocking merges.

Usage::

    python scripts/bench_compare.py fresh.log            # bench stdout
    python scripts/bench_compare.py fresh.json           # aggregate record
    python scripts/bench_compare.py fresh.log --tolerance 0.25 \
        --report bench_compare_report.json

The fresh input may be the bench's raw stdout (the last JSON line is the
aggregate record), the aggregate record itself, or a standing-format
document (``{"runs": [...]}`` — newest run is used).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

DEFAULT_STANDING = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_STANDING.json")

#: Aux counters gated in absolute terms: any increase past the allowance
#: is a regression even when the wall squeaked under tolerance.
AUX_ABSOLUTE_ALLOWANCE = {
    # warm-path invariant: training must not compile more than the
    # standing run did (a couple of slack compiles for grid jitter)
    "new_compiles_during_train": 2,
    # shrink level > standing means the run hit the memory ladder harder
    "memory_shrink_level": 0,
}

#: Aux counters gated relatively (same tolerance as the headline).
AUX_RELATIVE_HIGHER_IS_WORSE = (
    "selector_compile_s",
    "peak_staging_bytes",
    "host_peak_rss_bytes",
)


def last_json_line(text: str) -> Optional[Dict[str, Any]]:
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def load_workloads(path: str) -> Dict[str, Dict[str, Any]]:
    """Fresh input (stdout log / aggregate record / standing doc) → the
    ``{workload: record}`` map."""
    with open(path) as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = last_json_line(text)
    if not isinstance(doc, dict):
        raise SystemExit(f"no JSON record found in {path!r}")
    if "runs" in doc:                      # standing-format document
        runs = doc.get("runs") or []
        if not runs:
            raise SystemExit(f"{path!r} has no runs")
        return runs[-1].get("workloads") or {}
    aux = doc.get("aux") or {}
    if "workloads" in aux:                 # bench aggregate record
        return aux["workloads"]
    if "workloads" in doc:
        return doc["workloads"]
    if "value" in doc:                     # single-workload record
        return {"headline": doc}
    raise SystemExit(f"unrecognized bench record shape in {path!r}")


def load_standing(path: str) -> Dict[str, Dict[str, Any]]:
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as fh:
            runs = json.load(fh).get("runs") or []
    except (OSError, ValueError) as e:
        raise SystemExit(f"standing record {path!r} unreadable: {e}")
    return (runs[-1].get("workloads") or {}) if runs else {}


def _higher_is_better(unit: str) -> bool:
    # wall-style units regress upward, throughput units regress downward
    return "/s" in (unit or "")


def compare(fresh: Dict[str, Dict[str, Any]],
            standing: Dict[str, Dict[str, Any]],
            tolerance: float) -> Dict[str, Any]:
    findings: List[Dict[str, Any]] = []
    compared = 0
    for name in sorted(set(fresh) & set(standing)):
        f, s = fresh[name], standing[name]
        fv, sv = f.get("value"), s.get("value")
        if isinstance(fv, (int, float)) and isinstance(sv, (int, float)) \
                and sv > 0:
            compared += 1
            hib = _higher_is_better(str(f.get("unit") or s.get("unit")))
            ratio = fv / sv
            regressed = (ratio < 1.0 - tolerance if hib
                         else ratio > 1.0 + tolerance)
            findings.append({
                "workload": name, "kind": "headline",
                "unit": f.get("unit"), "fresh": fv, "standing": sv,
                "ratio": round(ratio, 4),
                "direction": "higher-better" if hib else "lower-better",
                "regressed": regressed})
        faux = f.get("aux") or {}
        saux = s.get("aux") or {}
        for key, allow in AUX_ABSOLUTE_ALLOWANCE.items():
            fa, sa = faux.get(key), saux.get(key)
            if isinstance(fa, (int, float)) and isinstance(sa, (int, float)):
                compared += 1
                findings.append({
                    "workload": name, "kind": f"aux:{key}",
                    "fresh": fa, "standing": sa, "allowance": allow,
                    "regressed": fa > sa + allow})
        for key in AUX_RELATIVE_HIGHER_IS_WORSE:
            fa, sa = faux.get(key), saux.get(key)
            if isinstance(fa, (int, float)) and isinstance(sa, (int, float)) \
                    and sa > 0:
                compared += 1
                findings.append({
                    "workload": name, "kind": f"aux:{key}",
                    "fresh": fa, "standing": sa,
                    "ratio": round(fa / sa, 4),
                    "regressed": fa / sa > 1.0 + tolerance})
    regressions = [f for f in findings if f["regressed"]]
    return {"tolerance": tolerance, "compared": compared,
            "freshWorkloads": sorted(fresh),
            "standingWorkloads": sorted(standing),
            "findings": findings,
            "regressions": regressions,
            "ok": not regressions and compared > 0}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("fresh", help="fresh bench output: stdout log, "
                                 "aggregate JSON record, or standing-format "
                                 "document")
    p.add_argument("--standing", default=DEFAULT_STANDING,
                   help="standing perf record (default: repo "
                        "BENCH_STANDING.json)")
    p.add_argument("--tolerance", type=float, default=0.15,
                   help="relative tolerance on headline + relative aux "
                        "comparisons (default 0.15)")
    p.add_argument("--report", help="also write the comparison report JSON "
                                    "here (CI artifact)")
    args = p.parse_args(argv)

    fresh = load_workloads(args.fresh)
    standing = load_standing(args.standing)
    report = compare(fresh, standing, args.tolerance)
    if args.report:
        with open(args.report, "w") as fh:
            json.dump(report, fh, indent=2)
    if not standing:
        print(f"bench_compare: no standing record at {args.standing}; "
              "nothing to gate against")
        return 0
    if report["compared"] == 0:
        print("bench_compare: no overlapping workloads between fresh and "
              "standing runs")
        return 0
    for f in report["findings"]:
        mark = "REGRESSED" if f["regressed"] else "ok"
        extra = (f" ratio={f['ratio']}" if "ratio" in f
                 else f" allowance={f.get('allowance')}")
        print(f"[{mark:>9}] {f['workload']}/{f['kind']}: "
              f"fresh={f['fresh']} standing={f['standing']}{extra}")
    if report["regressions"]:
        print(f"bench_compare: {len(report['regressions'])} regression(s) "
              f"past tolerance {args.tolerance}")
        return 1
    print(f"bench_compare: {report['compared']} comparison(s) within "
          f"tolerance {args.tolerance}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
