"""CI smoke for the compiled-program registry (ISSUE 18): cold ≈ warm
everywhere, not just at the serving edge.

One train populates the registry (plus the managed compile cache under the
same root).  Then:

* a FRESH subprocess trains the same workflow and must report
  ``new_compiles_during_train == 0`` — the whole train compile wall came
  off the disk,
* a registry-OFF control train (no registry, no compile cache) must reach
  the SAME winner and bitwise-identical scores, proving the registry only
  moves compiles, never numbers,
* two "pool worker" subprocesses boot a ScoringEngine on an AOT-STRIPPED
  copy of the bundle (no shipped executables — the registry is the only
  warm source) and must compile at most ONE program between them,
* one process activates the same stripped bundle as TWO tenants and must
  share ONE installed executable (shared_hits > 0, zero loaded-table
  growth on the second activation).

Usage:
    python scripts/ci_registry_smoke.py run OUT_DIR
    python scripts/ci_registry_smoke.py validate OUT_DIR

``run`` writes OUT_DIR/registry-smoke.json (the registry hit/miss summary
CI uploads as an artifact).
"""

import json
import os
import shutil
import subprocess
import sys

# runnable as `python scripts/ci_registry_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SUMMARY_NAME = "registry-smoke.json"

# fresh-process train probe: listeners install before anything compiles so
# every backend compile in this process is observed.  argv[1] = bundle out
# dir or "-" to skip saving.
_TRAIN_CHILD = r"""
import hashlib, json, sys, time
t0 = time.time()
from transmogrifai_tpu.profiling import (install_compile_listeners,
                                         new_compile_count)
install_compile_listeners()
import numpy as np
from transmogrifai_tpu import types as T
from transmogrifai_tpu.features import features_from_schema
from transmogrifai_tpu.models.linear import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrify import transmogrify
from transmogrifai_tpu.selector import (BinaryClassificationModelSelector,
                                        ModelCandidate, grid)
from transmogrifai_tpu.serving.engine import records_to_batch
from transmogrifai_tpu.workflow import Workflow

def make_records(n, seed=7):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        x1 = float(rng.normal()); x2 = float(rng.uniform(0, 10))
        recs.append({"y": 1.0 if (x1 + 0.2*x2 + rng.normal()*0.3) > 1.0
                     else 0.0,
                     "x1": x1, "x2": x2, "cat": ["a", "b", "c"][i % 3]})
    return recs

schema = {"y": T.RealNN, "x1": T.Real, "x2": T.Real, "cat": T.PickList}
y, predictors = features_from_schema(schema, response="y")
fv = transmogrify(predictors)
checked = y.sanity_check(fv, remove_bad_features=True)
sel = BinaryClassificationModelSelector(models=[
    ModelCandidate(OpLogisticRegression(), grid(reg_param=[0.01, 0.1]),
                   "OpLogisticRegression")])
sel.set_input(y, checked)
wf = (Workflow().set_input_records(make_records(200))
      .set_result_features(sel.get_output()))
model = wf.train()
from transmogrifai_tpu.aot import pretrace_drain
pretrace_drain()
train_compiles = new_compile_count()

# bitwise score fingerprint: same records, same order, hash of raw bytes
pred = next(f.name for f in model.result_features)
batch = records_to_batch(model.raw_features, make_records(32, seed=11))
scored = model.score(batch=batch)
h = hashlib.sha256()
for k in sorted(scored[pred].values):
    h.update(k.encode())
    h.update(np.ascontiguousarray(np.asarray(
        scored[pred].values[k], dtype=np.float64)).tobytes())

if sys.argv[1] != "-":
    model.save(sys.argv[1])

from transmogrifai_tpu.aot_registry import registry_stats
print(json.dumps({
    "new_compiles_during_train": train_compiles,
    "winner": model.selected_model.summary.best_model_name,
    "score_sha256": h.hexdigest(),
    "registry": registry_stats(),
    "wall_s": round(time.time() - t0, 1),
}))
"""

# fresh-process pool-worker probe: ScoringEngine on an AOT-less bundle —
# the registry is the only possible source of warm executables
_WORKER_CHILD = r"""
import json, sys
from transmogrifai_tpu.profiling import (install_compile_listeners,
                                         new_compile_count)
install_compile_listeners()
from transmogrifai_tpu.serving.engine import ScoringEngine
eng = ScoringEngine(sys.argv[1], max_batch=16, linger_ms=0.0)
out, _ = eng.score_record({"x1": 0.4, "x2": 3.0, "cat": "a"})
stats = eng.stats()
eng.close()
from transmogrifai_tpu.aot_registry import registry_stats
print(json.dumps({
    "new_compiles": new_compile_count(),
    "result_keys": sorted(out),
    "aot_executables": stats.get("aot_executables", 0),
    "registry": registry_stats(),
}))
"""

# one process, two byte-identical tenant bundles: the second activation
# must reuse the first's installed executables (one copy in memory)
_TENANT_CHILD = r"""
import json, sys
import numpy as np
from transmogrifai_tpu.profiling import (install_compile_listeners,
                                         new_compile_count)
install_compile_listeners()
from transmogrifai_tpu.serving.engine import ScoringEngine
from transmogrifai_tpu.aot_registry import loaded_count, registry_stats
rec = {"x1": 0.4, "x2": 3.0, "cat": "a"}
eng_a = ScoringEngine(sys.argv[1], max_batch=16, linger_ms=0.0)
out_a, _ = eng_a.score_record(rec)
loaded_after_a = loaded_count()
shared_before = registry_stats()["shared_hits"]
eng_b = ScoringEngine(sys.argv[2], max_batch=16, linger_ms=0.0)
out_b, _ = eng_b.score_record(rec)
loaded_after_b = loaded_count()
eng_a.close(); eng_b.close()
equal = (sorted(out_a) == sorted(out_b) and
         all(np.array_equal(np.asarray(out_a[k]), np.asarray(out_b[k]))
             for k in out_a))
print(json.dumps({
    "loaded_after_a": loaded_after_a,
    "loaded_after_b": loaded_after_b,
    "shared_hits_gained": registry_stats()["shared_hits"] - shared_before,
    "new_compiles": new_compile_count(),
    "scores_equal": bool(equal),
}))
"""


def _child(code, args, env):
    p = subprocess.run([sys.executable, "-c", code, *args],
                       capture_output=True, text=True, env=env, timeout=600)
    line = next((ln for ln in reversed(p.stdout.splitlines())
                 if ln.startswith("{")), None)
    if p.returncode != 0 or not line:
        sys.stderr.write(p.stderr[-4000:])
        raise SystemExit(f"child failed (rc={p.returncode})")
    return json.loads(line)


def _strip_aot(bundle, dest):
    """Copy ``bundle`` with every aot-* platform dir removed and a
    regenerated MANIFEST: a JIT-only bundle whose model content (and
    therefore registry family digest) is unchanged."""
    from transmogrifai_tpu.checkpoint import read_manifest, write_manifest
    shutil.copytree(bundle, dest)
    for name in list(os.listdir(dest)):
        if name.startswith("aot-"):
            shutil.rmtree(os.path.join(dest, name))
    extra = {k: v for k, v in read_manifest(dest).items()
             if k not in ("files", "createdAt", "formatVersion", "aot")}
    write_manifest(dest, extra=extra)
    return dest


def run(out_dir):
    os.makedirs(out_dir, exist_ok=True)
    registry_root = os.path.join(out_dir, "registry")
    bundle = os.path.join(out_dir, "model")

    base = dict(os.environ)
    for k in ("TRANSMOGRIFAI_AOT_REGISTRY", "TRANSMOGRIFAI_COMPILE_CACHE",
              "TRANSMOGRIFAI_COMPILATION_CACHE", "TRANSMOGRIFAI_NO_AOT"):
        base.pop(k, None)
    base["TRANSMOGRIFAI_AOT_LADDER_MAX"] = "16"
    reg_env = dict(base,
                   TRANSMOGRIFAI_AOT_REGISTRY=registry_root,
                   TRANSMOGRIFAI_COMPILE_CACHE=os.path.join(
                       registry_root, "compile-cache"))

    # 1. cold train populates registry + managed compile cache, saves the
    #    bundle (export publishes the scoring executables)
    cold = _child(_TRAIN_CHILD, [bundle], reg_env)
    # 2. the headline: a fresh process against the warm registry root
    warm = _child(_TRAIN_CHILD, ["-"], reg_env)
    # 3. registry-off, cache-off control: same winner, bitwise-same scores.
    # TRANSMOGRIFAI_COMPILATION_CACHE=0 also turns off the legacy default
    # /tmp jax cache, which earlier runs on the same host may have warmed —
    # the control really must compile from scratch.
    control = _child(_TRAIN_CHILD, ["-"], dict(
        base, TRANSMOGRIFAI_AOT_REGISTRY="0",
        TRANSMOGRIFAI_COMPILATION_CACHE="0"))

    # 4. two pool workers on an AOT-stripped bundle copy: with no shipped
    #    executables, only the registry can absorb the boot compiles
    stripped = _strip_aot(bundle, os.path.join(out_dir, "model-noaot"))
    workers = [_child(_WORKER_CHILD, [stripped], reg_env)
               for _ in range(2)]

    # 5. two tenants of the same family x rung in one process share one
    #    installed executable
    tenant_a = _strip_aot(stripped, os.path.join(out_dir, "tenant-a"))
    tenant_b = _strip_aot(stripped, os.path.join(out_dir, "tenant-b"))
    tenants = _child(_TENANT_CHILD, [tenant_a, tenant_b], reg_env)

    summary = {
        "registryRoot": registry_root,
        "cold": cold, "warm": warm, "control": control,
        "workers": workers, "tenants": tenants,
    }
    with open(os.path.join(out_dir, SUMMARY_NAME), "w") as fh:
        json.dump(summary, fh, indent=2)
    print(json.dumps(summary, indent=2))
    return 0


def validate(out_dir):
    with open(os.path.join(out_dir, SUMMARY_NAME)) as fh:
        s = json.load(fh)
    cold, warm, control = s["cold"], s["warm"], s["control"]
    workers, tenants = s["workers"], s["tenants"]

    # the first train must have fed the registry — or found it already
    # fleet-warm (CI restores the directory via actions/cache, in which
    # case cold == warm is exactly the point)
    assert cold["registry"]["publishes"] > 0 or \
        cold["registry"]["hits"] > 0, \
        f"first train neither published nor hit: {cold['registry']}"
    # vacuousness guard on the always-cold control: this workload really
    # does demand compiles when nothing absorbs them
    assert control["new_compiles_during_train"] > 0, \
        "control train compiled nothing — the warm assert is vacuous"

    # the acceptance bar: registry-warm, process-fresh train = ZERO compiles
    assert warm["new_compiles_during_train"] == 0, \
        f"warm fresh-process train compiled " \
        f"{warm['new_compiles_during_train']} programs"
    assert warm["registry"]["hits"] > 0, \
        f"warm train never hit the registry: {warm['registry']}"

    # the registry moves compiles, never numbers: winner + scores bitwise
    assert cold["winner"] == warm["winner"] == control["winner"], \
        f"winner drift: {cold['winner']}/{warm['winner']}/{control['winner']}"
    assert cold["score_sha256"] == warm["score_sha256"] == \
        control["score_sha256"], "score drift across registry/control runs"
    assert control["registry"]["enabled"] is False, \
        "control ran with the registry on — parity check is vacuous"

    # N-worker pool boot on a bundle with NO shipped executables: <=1
    # compile total, both workers fully served
    pool_compiles = sum(w["new_compiles"] for w in workers)
    assert pool_compiles <= 1, \
        f"2-worker boot compiled {pool_compiles} programs " \
        f"({[w['new_compiles'] for w in workers]})"
    for w in workers:
        assert w["aot_executables"] == 0, \
            f"stripped bundle still shipped executables: {w}"
        assert w["result_keys"], "worker returned no score fields"
        assert w["registry"]["hits"] > 0, \
            f"worker never hit the registry: {w['registry']}"

    # tenant sharing: second activation reuses the first's executables
    assert tenants["scores_equal"], "tenant copies scored differently"
    assert tenants["shared_hits_gained"] > 0, \
        f"second tenant installed its own executables: {tenants}"
    assert tenants["loaded_after_b"] == tenants["loaded_after_a"], \
        f"loaded-executable table grew on the second tenant: {tenants}"

    hits = warm["registry"]["hits"] + sum(w["registry"]["hits"]
                                          for w in workers)
    print(f"OK: warm train {warm['new_compiles_during_train']} compiles "
          f"(cold {cold['new_compiles_during_train']}), pool boot "
          f"{pool_compiles} compiles, {hits} registry hits, "
          f"{tenants['shared_hits_gained']} shared tenant installs, "
          f"bitwise winner/score parity vs no-registry control")
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "run":
        sys.exit(run(sys.argv[2]))
    if len(sys.argv) == 3 and sys.argv[1] == "validate":
        sys.exit(validate(sys.argv[2]))
    sys.exit(f"usage: {sys.argv[0]} run OUT_DIR | validate OUT_DIR")
