"""Evaluators — the TPU-native re-design of the reference's evaluator family
(core/src/main/scala/com/salesforce/op/evaluators/OpEvaluatorBase.scala:113,
OpBinaryClassificationEvaluator.scala:67-185, OpMultiClassificationEvaluator
.scala, OpRegressionEvaluator.scala, OpBinScoreEvaluator.scala,
OpForecastEvaluator.scala, factory Evaluators.scala:40).

Metrics are vectorised array reductions (sort-based AUC, one-hot confusion
counts) rather than Spark RDD passes; everything takes (y [N], pred dict of
arrays) and returns a plain-dict metrics object.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

DEFAULT_THRESHOLDS = np.linspace(0.0, 1.0, 101)


# --------------------------------------------------------------------------
# metric primitives
# --------------------------------------------------------------------------

def _scores_from_pred(pred: Dict[str, np.ndarray]) -> np.ndarray:
    """Positive-class score: probability_1 if present else rawPrediction_1
    else the prediction itself."""
    if pred.get("probability") is not None:
        p = np.asarray(pred["probability"])
        return p[:, 1] if p.ndim == 2 else p
    if pred.get("rawPrediction") is not None:
        r = np.asarray(pred["rawPrediction"])
        return r[:, 1] if r.ndim == 2 else r
    return np.asarray(pred["prediction"], dtype=np.float64)


def auroc(y: np.ndarray, scores: np.ndarray) -> float:
    """Area under ROC via the rank-sum (Mann-Whitney) identity with midrank
    tie handling (fully vectorised — one sort + group cumsums)."""
    y = np.asarray(y) > 0.5
    n_pos = int(y.sum())
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0
    order = np.argsort(scores, kind="mergesort")
    s = scores[order]
    boundary = np.r_[True, s[1:] != s[:-1]]
    gid = np.cumsum(boundary) - 1                  # tie-group id per sorted row
    counts = np.bincount(gid)
    cum = np.cumsum(counts).astype(np.float64)     # last 1-based rank in group
    mid = cum - (counts - 1) / 2.0                 # average rank of the group
    ranks = np.empty(len(s), dtype=np.float64)
    ranks[order] = mid[gid]
    rank_sum = ranks[y].sum()
    return float((rank_sum - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def aupr(y: np.ndarray, scores: np.ndarray) -> float:
    """Area under the precision-recall curve (step-wise, as MLlib computes)."""
    y = np.asarray(y) > 0.5
    n_pos = int(y.sum())
    if n_pos == 0:
        return 0.0
    order = np.argsort(-scores, kind="mergesort")
    ys = y[order].astype(np.float64)
    tp = np.cumsum(ys)
    fp = np.cumsum(1.0 - ys)
    scores_sorted = scores[order]
    # keep only threshold boundaries (last index of each distinct score)
    distinct = np.r_[scores_sorted[1:] != scores_sorted[:-1], True]
    tp, fp = tp[distinct], fp[distinct]
    precision = tp / np.maximum(tp + fp, 1e-12)
    recall = tp / n_pos
    # MLlib prepends (0, p[0]) and integrates with trapezoids over recall
    recall = np.r_[0.0, recall]
    precision = np.r_[1.0, precision]
    return float(np.trapezoid(precision, recall))


def binary_confusion(y: np.ndarray, yhat: np.ndarray) -> Dict[str, float]:
    y = np.asarray(y) > 0.5
    yhat = np.asarray(yhat) > 0.5
    tp = float(np.sum(y & yhat))
    tn = float(np.sum(~y & ~yhat))
    fp = float(np.sum(~y & yhat))
    fn = float(np.sum(y & ~yhat))
    precision = tp / (tp + fp) if tp + fp > 0 else 0.0
    recall = tp / (tp + fn) if tp + fn > 0 else 0.0
    f1 = 2 * precision * recall / (precision + recall) if precision + recall > 0 else 0.0
    n = max(len(y), 1)
    return {"TP": tp, "TN": tn, "FP": fp, "FN": fn,
            "Precision": precision, "Recall": recall, "F1": f1,
            "Error": (fp + fn) / n}


def threshold_metrics(y: np.ndarray, scores: np.ndarray,
                      thresholds: np.ndarray = DEFAULT_THRESHOLDS) -> Dict[str, List[float]]:
    """Per-threshold confusion counts in one vectorised pass
    (≙ OpBinaryClassificationEvaluator thresholds output)."""
    y = (np.asarray(y) > 0.5)[None, :]
    pred = scores[None, :] >= thresholds[:, None]
    tp = np.sum(y & pred, axis=1).astype(float)
    fp = np.sum(~y & pred, axis=1).astype(float)
    fn = np.sum(y & ~pred, axis=1).astype(float)
    tn = np.sum(~y & ~pred, axis=1).astype(float)
    precision = tp / np.maximum(tp + fp, 1.0)
    recall = tp / np.maximum(tp + fn, 1.0)
    return {"thresholds": thresholds.tolist(),
            "precisionByThreshold": precision.tolist(),
            "recallByThreshold": recall.tolist(),
            "truePositivesByThreshold": tp.tolist(),
            "falsePositivesByThreshold": fp.tolist(),
            "trueNegativesByThreshold": tn.tolist(),
            "falseNegativesByThreshold": fn.tolist()}


# --------------------------------------------------------------------------
# evaluator stages
# --------------------------------------------------------------------------

@dataclass
class EvaluationMetrics:
    metrics: Dict[str, Any] = field(default_factory=dict)

    def __getattr__(self, name):
        try:
            return self.metrics[name]
        except KeyError:
            raise AttributeError(name)

    def __getitem__(self, name):
        return self.metrics[name]

    def to_json(self) -> Dict[str, Any]:
        return dict(self.metrics)


class OpEvaluatorBase:
    """≙ OpEvaluatorBase.evaluateAll.  ``default_metric`` picks the scalar
    used by the ModelSelector; ``is_larger_better`` its direction."""

    name: str = "evaluator"
    default_metric: str = ""
    is_larger_better: bool = True
    # What the fused CV panels (evaluate_masked_grid / _fold_grid) expect in
    # each S column: "scores" = any rank-preserving score (margins suffice),
    # "predictions" = the model's actual prediction values (class ids for
    # classification, real values for regression).  The validator uses this
    # to build the right panel per model family.
    grid_panel_input: str = "scores"

    def __init__(self, default_metric: Optional[str] = None,
                 is_larger_better: Optional[bool] = None):
        if default_metric is not None:
            self.default_metric = default_metric
        if is_larger_better is not None:
            self.is_larger_better = is_larger_better

    def evaluate_all(self, y: np.ndarray, pred: Dict[str, np.ndarray]) -> EvaluationMetrics:
        raise NotImplementedError

    def evaluate(self, y: np.ndarray, pred: Dict[str, np.ndarray]) -> float:
        return float(self.evaluate_all(y, pred)[self.default_metric])

    def evaluate_masked(self, y_dev, device_out: Dict[str, Any],
                        w_dev, defer: bool = False):
        """Device fast path for the CV loop: score ``device_out`` (a model's
        ``device_scores`` result) over the 0/1 row mask ``w_dev`` without any
        bulk device→host transfer.  Returns None when this evaluator/metric
        has no device implementation (caller falls back to the host path).

        ``defer=True`` keeps the result as a DEVICE scalar when the metric is
        a pure device reduction — the caller batches many candidates' scalars
        into one host pull (a float() each costs a full link round trip)."""
        return None

    def evaluate_all_device(self, y_dev, device_out: Dict[str, Any],
                            w_dev) -> Optional[EvaluationMetrics]:
        """Device fast path for the FULL metric panel (≙ evaluate_all): every
        reduction runs in HBM and only scalars cross the host link.  Returns
        None when unavailable (caller falls back to the host path)."""
        return None

    def evaluate_masked_grid(self, y_dev, S, W):
        """Default metric for K candidate SCORE COLUMNS at once: S [N, K]
        (any rank-preserving score, e.g. linear margins), W [K, N] per-
        candidate validation masks → [K] device scalars.  One program + one
        batched pull replaces K per-candidate metric dispatches in the CV
        grid.  None when this evaluator has no grid implementation."""
        return None

    def evaluate_masked_fold_grid(self, y_dev, S, W):
        """Default metric for the whole (fold × grid) panel in one program:
        S [N, F, G] scores, W [F, N] fold validation masks → [F, G] device
        values.  None when unavailable (caller falls back per fold)."""
        return None


class OpBinaryClassificationEvaluator(OpEvaluatorBase):
    """≙ OpBinaryClassificationEvaluator.scala:67-185."""

    name = "binEval"
    default_metric = "AuPR"

    def __init__(self, thresholds: Optional[np.ndarray] = None, **kw):
        super().__init__(**kw)
        self.thresholds = DEFAULT_THRESHOLDS if thresholds is None else np.asarray(thresholds)

    def evaluate_all(self, y, pred) -> EvaluationMetrics:
        y = np.asarray(y, dtype=np.float64)
        scores = _scores_from_pred(pred)
        yhat = np.asarray(pred["prediction"], dtype=np.float64)
        m = binary_confusion(y, yhat)
        m["AuROC"] = auroc(y, scores)
        m["AuPR"] = aupr(y, scores)
        m.update(threshold_metrics(y, scores, self.thresholds))
        return EvaluationMetrics(m)

    def evaluate(self, y, pred) -> float:
        # fast single-metric path for the CV loop — skips the per-threshold
        # panel the selector never reads
        y = np.asarray(y, dtype=np.float64)
        m = self.default_metric
        if m == "AuROC":
            return auroc(y, _scores_from_pred(pred))
        if m == "AuPR":
            return aupr(y, _scores_from_pred(pred))
        if m in ("Precision", "Recall", "F1", "Error"):
            return binary_confusion(
                y, np.asarray(pred["prediction"], dtype=np.float64))[m]
        return super().evaluate(y, pred)

    def evaluate_masked(self, y_dev, device_out, w_dev,
                        defer: bool = False):
        from .metrics_device import (masked_aupr, masked_auroc,
                                     masked_binary_confusion)
        m = self.default_metric
        if m in ("AuROC", "AuPR"):
            s = self._device_scores_vec(device_out)
            if s is None:
                return None
            fn = masked_auroc if m == "AuROC" else masked_aupr
            out = fn(y_dev, s, w_dev)
            return out if defer else float(out)
        if m in ("Precision", "Recall", "F1", "Error"):
            pred = device_out.get("prediction")
            if pred is None:
                return None
            tp, fp, tn, fn_ = (float(v) for v in np.asarray(
                masked_binary_confusion(y_dev, pred, w_dev)))
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn_) if tp + fn_ > 0 else 0.0
            if m == "Precision":
                return precision
            if m == "Recall":
                return recall
            if m == "F1":
                return (2 * precision * recall / (precision + recall)
                        if precision + recall > 0 else 0.0)
            return (fp + fn_) / max(tp + fp + tn + fn_, 1.0)
        return None

    def evaluate_masked_grid(self, y_dev, S, W):
        from .metrics_device import masked_aupr_grid, masked_auroc_grid
        m = self.default_metric
        if m == "AuROC":
            return masked_auroc_grid(y_dev, S, W)
        if m == "AuPR":
            return masked_aupr_grid(y_dev, S, W)
        return None

    def evaluate_masked_fold_grid(self, y_dev, S, W):
        from .metrics_device import (masked_aupr_fold_grid,
                                     masked_auroc_fold_grid)
        m = self.default_metric
        if m == "AuROC":
            return masked_auroc_fold_grid(y_dev, S, W)
        if m == "AuPR":
            return masked_aupr_fold_grid(y_dev, S, W)
        return None

    @staticmethod
    def _device_scores_vec(device_out):
        s = device_out.get("scores")
        if s is None:
            p = device_out.get("probability")
            if p is not None and getattr(p, "ndim", 0) == 2 and p.shape[1] == 2:
                s = p[:, 1]
        return s

    def evaluate_all_device(self, y_dev, device_out, w_dev):
        from .metrics_device import (masked_aupr, masked_auroc,
                                     masked_binary_confusion,
                                     masked_threshold_confusion)
        s = self._device_scores_vec(device_out)
        pred = device_out.get("prediction")
        if s is None or pred is None:
            return None
        import jax.numpy as jnp
        conf = masked_binary_confusion(y_dev, pred, w_dev)
        au_roc = masked_auroc(y_dev, s, w_dev)
        au_pr = masked_aupr(y_dev, s, w_dev)
        # the device path buckets with searchsorted, which needs ascending
        # thresholds; sort, then un-permute the panel back to caller order
        thr_np = np.asarray(self.thresholds, dtype=np.float64)
        order = np.argsort(thr_np, kind="stable")
        thr = masked_threshold_confusion(
            y_dev, s, w_dev, jnp.asarray(thr_np[order], jnp.float32))
        # one scalar-block d2h transfer for the whole panel
        tp, fp, tn, fn = (float(v) for v in np.asarray(conf))
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        ttp, tfp, ttn, tfn = np.asarray(thr, dtype=np.float64)[:, inv]
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        n = max(tp + fp + tn + fn, 1.0)
        m = {"TP": tp, "TN": tn, "FP": fp, "FN": fn,
             "Precision": precision, "Recall": recall,
             "F1": (2 * precision * recall / (precision + recall)
                    if precision + recall > 0 else 0.0),
             "Error": (fp + fn) / n,
             "AuROC": float(au_roc), "AuPR": float(au_pr),
             "thresholds": np.asarray(self.thresholds).tolist(),
             "precisionByThreshold": (ttp / np.maximum(ttp + tfp, 1.0)).tolist(),
             "recallByThreshold": (ttp / np.maximum(ttp + tfn, 1.0)).tolist(),
             "truePositivesByThreshold": ttp.tolist(),
             "falsePositivesByThreshold": tfp.tolist(),
             "trueNegativesByThreshold": ttn.tolist(),
             "falseNegativesByThreshold": tfn.tolist()}
        return EvaluationMetrics(m)


class OpMultiClassificationEvaluator(OpEvaluatorBase):
    """≙ OpMultiClassificationEvaluator: weighted precision/recall/F1/error +
    top-N correctness-by-threshold (calculateThresholdMetrics:153)."""

    name = "multiEval"
    default_metric = "F1"
    grid_panel_input = "predictions"

    def __init__(self, top_ns: Sequence[int] = (1, 3), n_bins: int = 10, **kw):
        super().__init__(**kw)
        self.top_ns = tuple(top_ns)
        self.n_bins = n_bins

    @staticmethod
    def _conf_panel(conf: np.ndarray) -> Dict[str, Any]:
        """Weighted precision/recall/F1/error from a [C, C] confusion matrix
        (shared by the host and device paths)."""
        C = conf.shape[0]
        support = conf.sum(axis=1)
        tp = np.diag(conf)
        pred_count = conf.sum(axis=0)
        prec_c = np.divide(tp, pred_count, out=np.zeros(C), where=pred_count > 0)
        rec_c = np.divide(tp, support, out=np.zeros(C), where=support > 0)
        f1_c = np.divide(2 * prec_c * rec_c, prec_c + rec_c,
                         out=np.zeros(C), where=(prec_c + rec_c) > 0)
        wts = support / max(support.sum(), 1.0)
        return {
            "Precision": float(wts @ prec_c), "Recall": float(wts @ rec_c),
            "F1": float(wts @ f1_c),
            "Error": 1.0 - float(tp.sum() / max(support.sum(), 1.0)),
            "confusionMatrix": conf.tolist(),
        }

    def evaluate_all(self, y, pred) -> EvaluationMetrics:
        y = np.asarray(y, dtype=np.int64)
        yhat = np.asarray(pred["prediction"], dtype=np.int64)
        C = int(max(y.max(initial=0), yhat.max(initial=0))) + 1
        conf = np.zeros((C, C), dtype=np.float64)
        np.add.at(conf, (y, yhat), 1.0)
        m: Dict[str, Any] = self._conf_panel(conf)
        prob = pred.get("probability")
        if prob is not None:
            prob = np.asarray(prob, dtype=np.float64)
            order = np.argsort(-prob, axis=1)
            maxprob = prob[np.arange(len(y)), order[:, 0]]
            bins = np.clip((maxprob * self.n_bins).astype(int), 0, self.n_bins - 1)
            topns = {}
            for n in self.top_ns:
                correct = (order[:, :n] == y[:, None]).any(axis=1)
                counts = np.zeros(self.n_bins)
                corr = np.zeros(self.n_bins)
                np.add.at(counts, bins, 1.0)
                np.add.at(corr, bins, correct.astype(np.float64))
                topns[str(n)] = {
                    "topNCorrectByBin": corr.tolist(),
                    "topNCountByBin": counts.tolist(),
                }
            m["ThresholdMetrics"] = {
                "topNs": list(self.top_ns), "nBins": self.n_bins, "byTopN": topns}
        return EvaluationMetrics(m)

    def evaluate(self, y, pred) -> float:
        # confusion-only fast path for the CV loop (skips top-N-by-bin panel)
        if self.default_metric not in ("Precision", "Recall", "F1", "Error"):
            return super().evaluate(y, pred)
        fast = {"prediction": pred["prediction"]}
        return float(self.evaluate_all(y, fast)[self.default_metric])

    def evaluate_masked(self, y_dev, device_out, w_dev,
                        defer: bool = False):
        if self.default_metric not in ("Precision", "Recall", "F1", "Error"):
            return None
        pred = device_out.get("prediction")
        if pred is None:
            return None
        import jax.numpy as jnp

        from .metrics_device import masked_multiclass_confusion
        C = int(jnp.maximum(jnp.max(y_dev), jnp.max(pred))) + 1
        conf = np.asarray(masked_multiclass_confusion(
            y_dev, pred, w_dev, n_classes=C), dtype=np.float64)
        return self._conf_panel(conf)[self.default_metric]

    def evaluate_masked_grid(self, y_dev, S, W):
        # S [N, K] carries integer PREDICTION columns (grid_panel_input)
        if self.default_metric not in ("Precision", "Recall", "F1", "Error"):
            return None
        import jax.numpy as jnp

        from .metrics_device import masked_multiclass_metric_grid
        C = int(jnp.maximum(jnp.max(y_dev), jnp.max(S))) + 1
        return masked_multiclass_metric_grid(
            y_dev, S, W, n_classes=C, metric=self.default_metric)

    def evaluate_masked_fold_grid(self, y_dev, S, W):
        # S [N, F, G] integer predictions, W [F, N] fold masks -> [F, G]
        if self.default_metric not in ("Precision", "Recall", "F1", "Error"):
            return None
        import jax.numpy as jnp

        from .metrics_device import masked_multiclass_metric_fold_grid
        C = int(jnp.maximum(jnp.max(y_dev), jnp.max(S))) + 1
        return masked_multiclass_metric_fold_grid(
            y_dev, S, W, n_classes=C, metric=self.default_metric)

    def evaluate_all_device(self, y_dev, device_out, w_dev):
        pred = device_out.get("prediction")
        if pred is None or not len(y_dev):
            return None  # host path handles the empty-input degenerate case
        import jax
        import jax.numpy as jnp

        from .metrics_device import masked_multiclass_confusion
        C = int(jnp.maximum(jnp.max(y_dev), jnp.max(pred))) + 1
        conf = np.asarray(masked_multiclass_confusion(
            y_dev, pred, w_dev, n_classes=C), dtype=np.float64)
        m: Dict[str, Any] = self._conf_panel(conf)
        prob = device_out.get("probability")
        if prob is not None and getattr(prob, "ndim", 0) == 2:
            order = jnp.argsort(-prob, axis=1)
            maxprob = jnp.max(prob, axis=1)
            bins = jnp.clip((maxprob * self.n_bins).astype(jnp.int32),
                            0, self.n_bins - 1)
            yi = y_dev.astype(jnp.int32)
            counts = jax.ops.segment_sum(w_dev, bins,
                                         num_segments=self.n_bins)
            topns = {}
            for n in self.top_ns:
                correct = (order[:, :n] == yi[:, None]).any(axis=1)
                corr = jax.ops.segment_sum(
                    w_dev * correct.astype(w_dev.dtype), bins,
                    num_segments=self.n_bins)
                topns[str(n)] = {
                    "topNCorrectByBin": np.asarray(corr, np.float64).tolist(),
                    "topNCountByBin": np.asarray(counts, np.float64).tolist(),
                }
            m["ThresholdMetrics"] = {
                "topNs": list(self.top_ns), "nBins": self.n_bins,
                "byTopN": topns}
        return EvaluationMetrics(m)


class OpRegressionEvaluator(OpEvaluatorBase):
    """≙ OpRegressionEvaluator: RMSE/MSE/R2/MAE + signed-error histogram."""

    name = "regEval"
    default_metric = "RootMeanSquaredError"
    is_larger_better = False
    grid_panel_input = "predictions"

    def __init__(self, hist_bins: int = 20, **kw):
        super().__init__(**kw)
        self.hist_bins = hist_bins

    def evaluate_all(self, y, pred) -> EvaluationMetrics:
        y = np.asarray(y, dtype=np.float64)
        yhat = np.asarray(pred["prediction"], dtype=np.float64)
        err = yhat - y
        mse = float(np.mean(err ** 2)) if len(y) else 0.0
        var = float(np.var(y)) if len(y) else 0.0
        counts, edges = np.histogram(err, bins=self.hist_bins)
        return EvaluationMetrics({
            "RootMeanSquaredError": float(np.sqrt(mse)),
            "MeanSquaredError": mse,
            "MeanAbsoluteError": float(np.mean(np.abs(err))) if len(y) else 0.0,
            "R2": 1.0 - mse / var if var > 0 else 0.0,
            "SignedPercentageErrorHistogram": {
                "counts": counts.tolist(), "bins": edges.tolist()},
        })

    def evaluate(self, y, pred) -> float:
        y = np.asarray(y, dtype=np.float64)
        yhat = np.asarray(pred["prediction"], dtype=np.float64)
        err = yhat - y
        m = self.default_metric
        if m == "RootMeanSquaredError":
            return float(np.sqrt(np.mean(err ** 2))) if len(y) else 0.0
        if m == "MeanSquaredError":
            return float(np.mean(err ** 2)) if len(y) else 0.0
        if m == "MeanAbsoluteError":
            return float(np.mean(np.abs(err))) if len(y) else 0.0
        return super().evaluate(y, pred)

    def evaluate_masked(self, y_dev, device_out, w_dev,
                        defer: bool = False):
        pred = device_out.get("prediction")
        if pred is None or self.default_metric not in (
                "RootMeanSquaredError", "MeanSquaredError", "MeanAbsoluteError"):
            return None
        from .metrics_device import masked_reg_errors
        errs = masked_reg_errors(y_dev, pred, w_dev)
        if defer:
            import jax.numpy as jnp
            return {"RootMeanSquaredError": jnp.sqrt(errs[0]),
                    "MeanSquaredError": errs[0],
                    "MeanAbsoluteError": errs[1]}[self.default_metric]
        mse, mae = (float(v) for v in np.asarray(errs))
        return {"RootMeanSquaredError": float(np.sqrt(mse)),
                "MeanSquaredError": mse,
                "MeanAbsoluteError": mae}[self.default_metric]

    def evaluate_masked_grid(self, y_dev, S, W):
        # S [N, K] carries PREDICTION columns — for linear regression the
        # margins ARE the predictions, so the fused panel is exact
        if self.default_metric not in (
                "RootMeanSquaredError", "MeanSquaredError",
                "MeanAbsoluteError"):
            return None
        from .metrics_device import masked_reg_metric_grid
        return masked_reg_metric_grid(y_dev, S, W,
                                      metric=self.default_metric)

    def evaluate_masked_fold_grid(self, y_dev, S, W):
        # S [N, F, G] predictions, W [F, N] fold masks -> [F, G]
        if self.default_metric not in (
                "RootMeanSquaredError", "MeanSquaredError",
                "MeanAbsoluteError"):
            return None
        from .metrics_device import masked_reg_metric_fold_grid
        return masked_reg_metric_fold_grid(y_dev, S, W,
                                           metric=self.default_metric)

    def evaluate_all_device(self, y_dev, device_out, w_dev):
        pred = device_out.get("prediction")
        if pred is None:
            return None
        import jax.numpy as jnp
        from .metrics_device import masked_reg_errors
        mse, mae = (float(v) for v in np.asarray(
            masked_reg_errors(y_dev, pred, w_dev)))
        wsum = jnp.maximum(jnp.sum(w_dev), 1e-12)
        ym = jnp.sum(w_dev * y_dev) / wsum
        var = float(jnp.sum(w_dev * (y_dev - ym) ** 2) / wsum)
        # residual histogram on device: static bin count, one [bins] transfer
        err = (pred - y_dev)
        lo = float(jnp.min(jnp.where(w_dev > 0, err, jnp.inf)))
        hi = float(jnp.max(jnp.where(w_dev > 0, err, -jnp.inf)))
        edges = np.linspace(lo, hi if hi > lo else lo + 1.0, self.hist_bins + 1)
        idx = jnp.clip(jnp.searchsorted(jnp.asarray(edges[1:-1]), err,
                                        side="right"), 0, self.hist_bins - 1)
        import jax
        counts = jax.ops.segment_sum(w_dev, idx, num_segments=self.hist_bins)
        return EvaluationMetrics({
            "RootMeanSquaredError": float(np.sqrt(mse)),
            "MeanSquaredError": mse,
            "MeanAbsoluteError": mae,
            "R2": 1.0 - mse / var if var > 0 else 0.0,
            "SignedPercentageErrorHistogram": {
                "counts": [int(c) for c in np.asarray(counts)],
                "bins": edges.tolist()},
        })


class OpForecastEvaluator(OpEvaluatorBase):
    """≙ OpForecastEvaluator: SMAPE / seasonal MASE."""

    name = "forecastEval"
    default_metric = "SMAPE"
    is_larger_better = False

    def __init__(self, seasonal_window: int = 1, **kw):
        super().__init__(**kw)
        self.seasonal_window = seasonal_window

    def evaluate_all(self, y, pred) -> EvaluationMetrics:
        y = np.asarray(y, dtype=np.float64)
        yhat = np.asarray(pred["prediction"], dtype=np.float64)
        denom = np.abs(y) + np.abs(yhat)
        smape = float(2.0 * np.mean(
            np.divide(np.abs(y - yhat), denom, out=np.zeros_like(denom),
                      where=denom > 0)))
        m = self.seasonal_window
        naive = np.abs(y[m:] - y[:-m]).mean() if len(y) > m else 0.0
        mase = float(np.mean(np.abs(y - yhat)) / naive) if naive > 0 else 0.0
        return EvaluationMetrics({"SMAPE": smape, "MASE": mase})


class OpBinScoreEvaluator(OpEvaluatorBase):
    """≙ OpBinScoreEvaluator: score-decile calibration (Brier score + per-bin
    average score vs conversion rate)."""

    name = "binScoreEval"
    default_metric = "BrierScore"
    is_larger_better = False

    def __init__(self, num_bins: int = 100, **kw):
        super().__init__(**kw)
        self.num_bins = num_bins

    def evaluate_all(self, y, pred) -> EvaluationMetrics:
        y = np.asarray(y, dtype=np.float64)
        scores = _scores_from_pred(pred)
        bins = np.clip((scores * self.num_bins).astype(int), 0, self.num_bins - 1)
        counts = np.zeros(self.num_bins)
        ssum = np.zeros(self.num_bins)
        ysum = np.zeros(self.num_bins)
        np.add.at(counts, bins, 1.0)
        np.add.at(ssum, bins, scores)
        np.add.at(ysum, bins, y)
        nz = counts > 0
        avg_score = np.divide(ssum, counts, out=np.zeros_like(ssum), where=nz)
        conv_rate = np.divide(ysum, counts, out=np.zeros_like(ysum), where=nz)
        return EvaluationMetrics({
            "BrierScore": float(np.mean((scores - y) ** 2)) if len(y) else 0.0,
            "binCenters": ((np.arange(self.num_bins) + 0.5) / self.num_bins).tolist(),
            "numberOfDataPoints": counts.tolist(),
            "averageScore": avg_score.tolist(),
            "averageConversionRate": conv_rate.tolist(),
        })


# --------------------------------------------------------------------------
# factory (≙ Evaluators.scala:40)
# --------------------------------------------------------------------------

class CustomEvaluator(OpEvaluatorBase):
    """User-supplied metric (≙ Evaluators.*.custom, Evaluators.scala:126):
    ``evaluate_fn(y, pred)`` receives the label array and the prediction dict
    and returns one float.  All three keys ('prediction', 'probability',
    'rawPrediction') are always PRESENT but may be None for models that don't
    produce them (e.g. regression) — the fn must handle None values, as the
    reference leaves error scenarios to the caller."""

    def __init__(self, metric_name: str, evaluate_fn, larger_better: bool = True):
        super().__init__(default_metric=metric_name,
                         is_larger_better=larger_better)
        self.name = metric_name
        self.evaluate_fn = evaluate_fn

    def evaluate_all(self, y, pred) -> EvaluationMetrics:
        # uniform contract across the CV loop and Workflow.evaluate: keys
        # always present, None when the model has no such output
        pred = dict(pred)
        for k in ("prediction", "probability", "rawPrediction"):
            pred.setdefault(k, None)
        return EvaluationMetrics(
            {self.default_metric: float(self.evaluate_fn(y, pred))})


class Evaluators:
    # user metric factory, shared by every problem-type family
    custom = CustomEvaluator

    class BinaryClassification:
        custom = CustomEvaluator

        @staticmethod
        def auPR() -> OpBinaryClassificationEvaluator:
            return OpBinaryClassificationEvaluator(default_metric="AuPR")

        @staticmethod
        def auROC() -> OpBinaryClassificationEvaluator:
            return OpBinaryClassificationEvaluator(default_metric="AuROC")

        @staticmethod
        def precision() -> OpBinaryClassificationEvaluator:
            return OpBinaryClassificationEvaluator(default_metric="Precision")

        @staticmethod
        def recall() -> OpBinaryClassificationEvaluator:
            return OpBinaryClassificationEvaluator(default_metric="Recall")

        @staticmethod
        def f1() -> OpBinaryClassificationEvaluator:
            return OpBinaryClassificationEvaluator(default_metric="F1")

        @staticmethod
        def error() -> OpBinaryClassificationEvaluator:
            return OpBinaryClassificationEvaluator(
                default_metric="Error", is_larger_better=False)

        @staticmethod
        def brierScore() -> OpBinScoreEvaluator:
            return OpBinScoreEvaluator()

    class MultiClassification:
        custom = CustomEvaluator

        @staticmethod
        def precision() -> OpMultiClassificationEvaluator:
            return OpMultiClassificationEvaluator(default_metric="Precision")

        @staticmethod
        def recall() -> OpMultiClassificationEvaluator:
            return OpMultiClassificationEvaluator(default_metric="Recall")

        @staticmethod
        def f1() -> OpMultiClassificationEvaluator:
            return OpMultiClassificationEvaluator(default_metric="F1")

        @staticmethod
        def error() -> OpMultiClassificationEvaluator:
            return OpMultiClassificationEvaluator(
                default_metric="Error", is_larger_better=False)

    class Regression:
        custom = CustomEvaluator

        @staticmethod
        def rmse() -> OpRegressionEvaluator:
            return OpRegressionEvaluator(default_metric="RootMeanSquaredError")

        @staticmethod
        def mse() -> OpRegressionEvaluator:
            return OpRegressionEvaluator(default_metric="MeanSquaredError")

        @staticmethod
        def mae() -> OpRegressionEvaluator:
            return OpRegressionEvaluator(default_metric="MeanAbsoluteError")

        @staticmethod
        def r2() -> OpRegressionEvaluator:
            return OpRegressionEvaluator(default_metric="R2", is_larger_better=True)

    class Forecast:
        @staticmethod
        def smape() -> OpForecastEvaluator:
            return OpForecastEvaluator(default_metric="SMAPE")

        @staticmethod
        def mase() -> OpForecastEvaluator:
            return OpForecastEvaluator(default_metric="MASE")
