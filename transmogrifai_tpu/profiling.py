"""Profiling / observability — the OpSparkListener equivalent (reference:
utils/src/main/scala/com/salesforce/op/utils/spark/OpSparkListener.scala:62:
per-stage executor run time, GC time, IO bytes, cumulative metrics, and
AppMetrics delivered to completion handlers).

TPU translation (SURVEY §5): per-phase wall-clock + device memory stats from
``jax.local_devices()[0].memory_stats()``, optional ``jax.profiler`` trace
capture, all emitted as structured JSON.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# -- host-link transfer accounting (≙ the listener's IO byte counters) ------
# Incremented at the transfer chokepoints (columns.to_device_f32 cache
# misses, packed token-id prefetch, fused-program wire args); PhaseTimer
# snapshots it per phase.  TRACKED transfers only — implicit jit-arg copies
# of small arrays are not counted.
_HOST_LINK_BYTES = [0]


def add_host_link_bytes(n: int) -> None:
    _HOST_LINK_BYTES[0] += int(n)


def host_link_bytes() -> int:
    return _HOST_LINK_BYTES[0]


# -- compile-vs-execute attribution (ISSUE 4) -------------------------------
# jax.monitoring streams every backend compile (and, with a persistent
# compilation cache configured, every cache hit/miss) through process-global
# listeners.  The counters below let PhaseTimer split a phase's wall into
# "seconds spent inside XLA compilation" vs everything else, and let the
# bench count NEW programs built this process (persistent-cache misses when
# the cache is on, raw backend compiles otherwise).
_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_COMPILE_LOCK = threading.Lock()
_COMPILE_INSTALL_LOCK = threading.Lock()
_COMPILE_STATS = {"compile_s": 0.0, "backend_compiles": 0,
                  "cache_hits": 0, "cache_misses": 0}
_COMPILE_LISTENERS_INSTALLED = [False]


def install_compile_listeners() -> bool:
    """Register the jax.monitoring listeners feeding ``compile_stats``.
    Idempotent and safe without jax (returns False).  Called from package
    import; also from the accessors so a bare ``import profiling`` works.
    Registration is double-checked under an install lock: jax.monitoring has
    no dedup, so two racing callers registering the same listeners would
    double-count every compile second from then on."""
    if _COMPILE_LISTENERS_INSTALLED[0]:
        return True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover — jax-less host
        return False

    def _on_duration(event: str, duration: float, **kw) -> None:
        if event == _COMPILE_DURATION_EVENT:
            with _COMPILE_LOCK:
                _COMPILE_STATS["compile_s"] += float(duration)
                _COMPILE_STATS["backend_compiles"] += 1

    def _on_event(event: str, **kw) -> None:
        if event == _CACHE_HIT_EVENT:
            with _COMPILE_LOCK:
                _COMPILE_STATS["cache_hits"] += 1
        elif event == _CACHE_MISS_EVENT:
            with _COMPILE_LOCK:
                _COMPILE_STATS["cache_misses"] += 1

    with _COMPILE_INSTALL_LOCK:
        if _COMPILE_LISTENERS_INSTALLED[0]:
            return True
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _COMPILE_LISTENERS_INSTALLED[0] = True
    return True


def compile_stats() -> Dict[str, float]:
    install_compile_listeners()
    return dict(_COMPILE_STATS)


def reset_compile_stats() -> None:
    install_compile_listeners()
    for k in _COMPILE_STATS:
        _COMPILE_STATS[k] = 0.0 if k == "compile_s" else 0


def compile_seconds() -> float:
    install_compile_listeners()
    return float(_COMPILE_STATS["compile_s"])


def new_compile_count() -> int:
    """Programs newly BUILT this process.  With a persistent compilation
    cache configured this is the miss count (a hit retrieves a prior build —
    its small backend_compile_duration is retrieval, not compilation);
    without one every backend compile is a fresh build."""
    install_compile_listeners()
    try:
        import jax
        if jax.config.jax_compilation_cache_dir:
            return int(_COMPILE_STATS["cache_misses"])
    except Exception:  # pragma: no cover
        pass
    return int(_COMPILE_STATS["backend_compiles"])


def set_compile_cache_dir(path: str, min_compile_time_secs: float = 0.0
                          ) -> bool:
    """Point jax's persistent compilation cache at ``path`` (created on
    first write by jax).  ``min_compile_time_secs=0`` caches every program —
    a warm process then reports ~0 ``new_compile_count()``.  The path is
    scoped per backend platform (same hazard as the import-time default: CPU
    AOT entries carry host machine-feature assumptions)."""
    try:
        import os

        import jax
        plat = ((os.environ.get("JAX_PLATFORMS") or "default")
                .split(",")[0].strip() or "default")
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(path, plat))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        return True
    except Exception:  # pragma: no cover — cache is best-effort
        return False


# -- selector racing accounting (ISSUE 4) -----------------------------------
# Fold-fits the successive-halving sweep did NOT run (pruned grid points ×
# remaining folds).  Reset at bench-workload boundaries.
RACING_STATS = {"cv_fits_saved": 0, "families_raced": 0, "points_pruned": 0}


def record_racing(fits_saved: int, points_pruned: int) -> None:
    RACING_STATS["cv_fits_saved"] += int(fits_saved)
    RACING_STATS["families_raced"] += 1
    RACING_STATS["points_pruned"] += int(points_pruned)


def racing_stats() -> Dict[str, int]:
    return dict(RACING_STATS)


def reset_racing_stats() -> None:
    for k in RACING_STATS:
        RACING_STATS[k] = 0


# -- XLA program cost registry (VERDICT r4 next #5) -------------------------
# When TRANSMOGRIFAI_COST_ANALYSIS=1, the dominant compiled programs record
# their XLA cost analysis (flops / bytes accessed) here, once per program
# name; bench.py turns them into achieved-FLOP/s roofline fields.
PROGRAM_COSTS: Dict[str, Dict[str, Any]] = {}

# name → jax Lowered, captured inline at near-zero cost and resolved to a
# PROGRAM_COSTS entry by flush_program_costs() OUTSIDE any timed wall
_PENDING_COSTS: Dict[str, Any] = {}


def cost_analysis_enabled() -> bool:
    return os.environ.get("TRANSMOGRIFAI_COST_ANALYSIS") == "1"


def record_program_cost(name: str, jitted_fn, args=(), kwargs=None) -> None:
    """Best-effort XLA cost analysis of ``jitted_fn`` at ``args``' shapes.
    Only the cheap ``lower()`` trace happens here (a Lowered holds shapes,
    not argument buffers); the compile()+cost_analysis() pass is deferred to
    ``flush_program_costs`` so enabling TRANSMOGRIFAI_COST_ANALYSIS=1 does
    not add analysis time inside a caller's timed wall (ADVICE r5)."""
    if (not cost_analysis_enabled() or name in PROGRAM_COSTS
            or name in _PENDING_COSTS):
        return
    try:
        _PENDING_COSTS[name] = jitted_fn.lower(*args, **(kwargs or {}))
    except Exception:  # noqa: BLE001 — diagnostics must never break a fit
        pass


def flush_program_costs() -> None:
    """Resolve pending lowerings into PROGRAM_COSTS entries.  The explicit
    compile() hits the in-process/persistent compile cache (the caller
    already executed the program), so the cost is one analysis pass, not a
    recompile.  Call after the timed region ends."""
    while _PENDING_COSTS:
        name, lowered = _PENDING_COSTS.popitem()
        if name in PROGRAM_COSTS:
            continue
        try:
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            PROGRAM_COSTS[name] = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            }
        except Exception:  # noqa: BLE001 — diagnostics only
            pass


def clear_program_costs() -> None:
    """Reset both resolved and pending cost records (workload boundaries)."""
    PROGRAM_COSTS.clear()
    _PENDING_COSTS.clear()


class LatencyHistogram:
    """Thread-safe latency sketch for the serving layer: fixed log-spaced
    bucket counters (Prometheus-style cumulative buckets) plus exact
    count/sum.  Quantiles interpolate inside the winning bucket — a bounded
    ~5% relative error, no per-observation storage, O(1) record."""

    # 100 µs → ~100 s, ×1.3 per bucket: 54 bounds
    _BOUNDS = tuple(1e-4 * (1.3 ** i) for i in range(54))

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(self._BOUNDS) + 1)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        # OpenMetrics exemplars: last {traceId, value} per bucket plus the
        # overall last — a p99 spike in Prometheus links to a concrete trace
        self._bucket_exemplars: Dict[int, Dict[str, Any]] = {}
        self._last_exemplar: Optional[Dict[str, Any]] = None

    def observe(self, seconds: float,
                trace_id: Optional[str] = None) -> None:
        """Record one observation.  Every mutation — bucket increment,
        count/sum, min/max — happens under the instance lock, so concurrent
        server threads never lose an update.  ``trace_id`` (when the request
        carried one) is remembered as the bucket's exemplar."""
        s = float(seconds)
        i = bisect.bisect_left(self._BOUNDS, s)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += s
            if self._min is None or s < self._min:
                self._min = s
            if self._max is None or s > self._max:
                self._max = s
            if trace_id:
                ex = {"traceId": trace_id, "value": s}
                self._bucket_exemplars[i] = ex
                self._last_exemplar = ex

    def exemplar(self, slowest: bool = False) -> Optional[Dict[str, Any]]:
        """The exemplar to attach to a rendered sample: the last traced
        observation, or with ``slowest=True`` the one from the highest
        occupied bucket (the trace a p99 spike points at).  None when no
        traced observation has landed yet."""
        with self._lock:
            if not self._bucket_exemplars:
                return None
            if slowest:
                return dict(self._bucket_exemplars[
                    max(self._bucket_exemplars)])
            return dict(self._last_exemplar) \
                if self._last_exemplar else None

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """q-quantile estimate.  Empty → None; q<=0 → exact min; q>=1 →
        exact max; bucket-interpolated results are clamped into [min, max],
        so a single observation returns that exact value for any q."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
            mn, mx = self._min, self._max
        if total == 0:
            return None
        if q <= 0.0:
            return mn
        if q >= 1.0:
            return mx
        target = q * total
        seen = 0.0
        est = self._BOUNDS[-1]
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lo = self._BOUNDS[i - 1] if i > 0 else 0.0
            hi = self._BOUNDS[i] if i < len(self._BOUNDS) else lo * 1.3
            if seen + c >= target:
                frac = (target - seen) / c
                est = lo + (hi - lo) * frac
                break
            seen += c
        return min(max(est, mn), mx)

    def snapshot(self) -> Dict[str, Optional[float]]:
        return {"count": self.count, "sum": round(self.sum, 6),
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


@dataclass
class PhaseMetrics:
    """≙ StageMetrics (OpSparkListener.scala)."""
    name: str
    wall_s: float
    device_bytes_in_use: Optional[int] = None
    peak_bytes_in_use: Optional[int] = None
    host_link_bytes: Optional[int] = None
    compile_s: Optional[float] = None   # XLA compile seconds inside the phase

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "wallSeconds": round(self.wall_s, 4),
                "deviceBytesInUse": self.device_bytes_in_use,
                "peakBytesInUse": self.peak_bytes_in_use,
                "hostLinkBytes": self.host_link_bytes,
                "compileSeconds": (None if self.compile_s is None
                                   else round(self.compile_s, 4))}


@dataclass
class AppMetrics:
    """≙ AppMetrics (OpSparkListener.scala:146 MetricJsonLike)."""
    app_tag: Optional[str]
    total_wall_s: float
    phases: List[PhaseMetrics] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"appTag": self.app_tag,
                "totalWallSeconds": round(self.total_wall_s, 4),
                "phases": [p.to_json() for p in self.phases]}

    def log_pretty(self) -> str:
        lines = [f"App metrics{f' [{self.app_tag}]' if self.app_tag else ''}: "
                 f"{self.total_wall_s:.2f}s total"]
        for p in self.phases:
            mem = (f", {p.peak_bytes_in_use / 2**20:.0f} MiB peak"
                   if p.peak_bytes_in_use else "")
            lines.append(f"  {p.name}: {p.wall_s:.2f}s{mem}")
        return "\n".join(lines)


def _device_memory() -> Dict[str, Optional[int]]:
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        return {"bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use")}
    except Exception:
        return {"bytes_in_use": None, "peak_bytes_in_use": None}


class PhaseTimer:
    """Collects per-phase timings; nested phases are recorded flat."""

    def __init__(self):
        self.phases: List[PhaseMetrics] = []
        self._t0 = time.time()

    @contextlib.contextmanager
    def phase(self, name: str):
        # late import: telemetry imports profiling, so the reverse edge must
        # stay out of module load.  span() is a no-op without a tracer.
        from .obsv import BOARD
        from .telemetry import span as _span
        t0 = time.time()
        link0 = host_link_bytes()
        compile0 = compile_seconds()
        # training control plane: the phase boundary is the coarsest
        # progress seam — /statusz shows it live.  A dict merge, no span.
        BOARD.publish(phase=name)
        try:
            with _span(f"phase.{name}"):
                yield
        finally:
            mem = _device_memory()
            self.phases.append(PhaseMetrics(
                name, time.time() - t0,
                device_bytes_in_use=mem["bytes_in_use"],
                peak_bytes_in_use=mem["peak_bytes_in_use"],
                host_link_bytes=host_link_bytes() - link0,
                compile_s=compile_seconds() - compile0))
            BOARD.publish(phase=f"{name}:done",
                          phaseWallS=round(time.time() - t0, 3))

    def app_metrics(self, tag: Optional[str] = None) -> AppMetrics:
        return AppMetrics(tag, time.time() - self._t0, list(self.phases))


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """Wrap a block in a jax.profiler trace (≙ the listener's event capture);
    view with tensorboard or xprof."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
