"""Profiling / observability — the OpSparkListener equivalent (reference:
utils/src/main/scala/com/salesforce/op/utils/spark/OpSparkListener.scala:62:
per-stage executor run time, GC time, IO bytes, cumulative metrics, and
AppMetrics delivered to completion handlers).

TPU translation (SURVEY §5): per-phase wall-clock + device memory stats from
``jax.local_devices()[0].memory_stats()``, optional ``jax.profiler`` trace
capture, all emitted as structured JSON.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# -- host-link transfer accounting (≙ the listener's IO byte counters) ------
# Incremented at the transfer chokepoints (columns.to_device_f32 cache
# misses, packed token-id prefetch, fused-program wire args); PhaseTimer
# snapshots it per phase.  TRACKED transfers only — implicit jit-arg copies
# of small arrays are not counted.
_HOST_LINK_BYTES = [0]


def add_host_link_bytes(n: int) -> None:
    _HOST_LINK_BYTES[0] += int(n)


def host_link_bytes() -> int:
    return _HOST_LINK_BYTES[0]


# -- XLA program cost registry (VERDICT r4 next #5) -------------------------
# When TRANSMOGRIFAI_COST_ANALYSIS=1, the dominant compiled programs record
# their XLA cost analysis (flops / bytes accessed) here, once per program
# name; bench.py turns them into achieved-FLOP/s roofline fields.
PROGRAM_COSTS: Dict[str, Dict[str, Any]] = {}

# name → jax Lowered, captured inline at near-zero cost and resolved to a
# PROGRAM_COSTS entry by flush_program_costs() OUTSIDE any timed wall
_PENDING_COSTS: Dict[str, Any] = {}


def cost_analysis_enabled() -> bool:
    return os.environ.get("TRANSMOGRIFAI_COST_ANALYSIS") == "1"


def record_program_cost(name: str, jitted_fn, args=(), kwargs=None) -> None:
    """Best-effort XLA cost analysis of ``jitted_fn`` at ``args``' shapes.
    Only the cheap ``lower()`` trace happens here (a Lowered holds shapes,
    not argument buffers); the compile()+cost_analysis() pass is deferred to
    ``flush_program_costs`` so enabling TRANSMOGRIFAI_COST_ANALYSIS=1 does
    not add analysis time inside a caller's timed wall (ADVICE r5)."""
    if (not cost_analysis_enabled() or name in PROGRAM_COSTS
            or name in _PENDING_COSTS):
        return
    try:
        _PENDING_COSTS[name] = jitted_fn.lower(*args, **(kwargs or {}))
    except Exception:  # noqa: BLE001 — diagnostics must never break a fit
        pass


def flush_program_costs() -> None:
    """Resolve pending lowerings into PROGRAM_COSTS entries.  The explicit
    compile() hits the in-process/persistent compile cache (the caller
    already executed the program), so the cost is one analysis pass, not a
    recompile.  Call after the timed region ends."""
    while _PENDING_COSTS:
        name, lowered = _PENDING_COSTS.popitem()
        if name in PROGRAM_COSTS:
            continue
        try:
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            PROGRAM_COSTS[name] = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            }
        except Exception:  # noqa: BLE001 — diagnostics only
            pass


def clear_program_costs() -> None:
    """Reset both resolved and pending cost records (workload boundaries)."""
    PROGRAM_COSTS.clear()
    _PENDING_COSTS.clear()


class LatencyHistogram:
    """Thread-safe latency sketch for the serving layer: fixed log-spaced
    bucket counters (Prometheus-style cumulative buckets) plus exact
    count/sum.  Quantiles interpolate inside the winning bucket — a bounded
    ~5% relative error, no per-observation storage, O(1) record."""

    # 100 µs → ~100 s, ×1.3 per bucket: 54 bounds
    _BOUNDS = tuple(1e-4 * (1.3 ** i) for i in range(54))

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._counts = [0] * (len(self._BOUNDS) + 1)
        self._count = 0
        self._sum = 0.0

    def observe(self, seconds: float) -> None:
        import bisect
        s = float(seconds)
        i = bisect.bisect_left(self._BOUNDS, s)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += s

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return None
        target = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lo = self._BOUNDS[i - 1] if i > 0 else 0.0
            hi = self._BOUNDS[i] if i < len(self._BOUNDS) else lo * 1.3
            if seen + c >= target:
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self._BOUNDS[-1]

    def snapshot(self) -> Dict[str, Optional[float]]:
        return {"count": self._count, "sum": round(self._sum, 6),
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


@dataclass
class PhaseMetrics:
    """≙ StageMetrics (OpSparkListener.scala)."""
    name: str
    wall_s: float
    device_bytes_in_use: Optional[int] = None
    peak_bytes_in_use: Optional[int] = None
    host_link_bytes: Optional[int] = None

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "wallSeconds": round(self.wall_s, 4),
                "deviceBytesInUse": self.device_bytes_in_use,
                "peakBytesInUse": self.peak_bytes_in_use,
                "hostLinkBytes": self.host_link_bytes}


@dataclass
class AppMetrics:
    """≙ AppMetrics (OpSparkListener.scala:146 MetricJsonLike)."""
    app_tag: Optional[str]
    total_wall_s: float
    phases: List[PhaseMetrics] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"appTag": self.app_tag,
                "totalWallSeconds": round(self.total_wall_s, 4),
                "phases": [p.to_json() for p in self.phases]}

    def log_pretty(self) -> str:
        lines = [f"App metrics{f' [{self.app_tag}]' if self.app_tag else ''}: "
                 f"{self.total_wall_s:.2f}s total"]
        for p in self.phases:
            mem = (f", {p.peak_bytes_in_use / 2**20:.0f} MiB peak"
                   if p.peak_bytes_in_use else "")
            lines.append(f"  {p.name}: {p.wall_s:.2f}s{mem}")
        return "\n".join(lines)


def _device_memory() -> Dict[str, Optional[int]]:
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        return {"bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use")}
    except Exception:
        return {"bytes_in_use": None, "peak_bytes_in_use": None}


class PhaseTimer:
    """Collects per-phase timings; nested phases are recorded flat."""

    def __init__(self):
        self.phases: List[PhaseMetrics] = []
        self._t0 = time.time()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.time()
        link0 = host_link_bytes()
        try:
            yield
        finally:
            mem = _device_memory()
            self.phases.append(PhaseMetrics(
                name, time.time() - t0,
                device_bytes_in_use=mem["bytes_in_use"],
                peak_bytes_in_use=mem["peak_bytes_in_use"],
                host_link_bytes=host_link_bytes() - link0))

    def app_metrics(self, tag: Optional[str] = None) -> AppMetrics:
        return AppMetrics(tag, time.time() - self._t0, list(self.phases))


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """Wrap a block in a jax.profiler trace (≙ the listener's event capture);
    view with tensorboard or xprof."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
