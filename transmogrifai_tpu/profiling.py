"""Profiling / observability — the OpSparkListener equivalent (reference:
utils/src/main/scala/com/salesforce/op/utils/spark/OpSparkListener.scala:62:
per-stage executor run time, GC time, IO bytes, cumulative metrics, and
AppMetrics delivered to completion handlers).

TPU translation (SURVEY §5): per-phase wall-clock + device memory stats from
``jax.local_devices()[0].memory_stats()``, optional ``jax.profiler`` trace
capture, all emitted as structured JSON.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# -- host-link transfer accounting (≙ the listener's IO byte counters) ------
# Incremented at the transfer chokepoints (columns.to_device_f32 cache
# misses, packed token-id prefetch, fused-program wire args); PhaseTimer
# snapshots it per phase.  TRACKED transfers only — implicit jit-arg copies
# of small arrays are not counted.
_HOST_LINK_BYTES = [0]


def add_host_link_bytes(n: int) -> None:
    _HOST_LINK_BYTES[0] += int(n)


def host_link_bytes() -> int:
    return _HOST_LINK_BYTES[0]


# -- XLA program cost registry (VERDICT r4 next #5) -------------------------
# When TRANSMOGRIFAI_COST_ANALYSIS=1, the dominant compiled programs record
# their XLA cost analysis (flops / bytes accessed) here, once per program
# name; bench.py turns them into achieved-FLOP/s roofline fields.
PROGRAM_COSTS: Dict[str, Dict[str, Any]] = {}


def cost_analysis_enabled() -> bool:
    return os.environ.get("TRANSMOGRIFAI_COST_ANALYSIS") == "1"


def record_program_cost(name: str, jitted_fn, args=(), kwargs=None) -> None:
    """Best-effort XLA cost analysis of ``jitted_fn`` at ``args``' shapes.
    The explicit lower().compile() hits the in-process/persistent compile
    cache, so the cost is one analysis pass, not a recompile."""
    if not cost_analysis_enabled() or name in PROGRAM_COSTS:
        return
    try:
        ca = jitted_fn.lower(*args, **(kwargs or {})).compile(
        ).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        PROGRAM_COSTS[name] = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        }
    except Exception:  # noqa: BLE001 — diagnostics must never break a fit
        pass


@dataclass
class PhaseMetrics:
    """≙ StageMetrics (OpSparkListener.scala)."""
    name: str
    wall_s: float
    device_bytes_in_use: Optional[int] = None
    peak_bytes_in_use: Optional[int] = None
    host_link_bytes: Optional[int] = None

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "wallSeconds": round(self.wall_s, 4),
                "deviceBytesInUse": self.device_bytes_in_use,
                "peakBytesInUse": self.peak_bytes_in_use,
                "hostLinkBytes": self.host_link_bytes}


@dataclass
class AppMetrics:
    """≙ AppMetrics (OpSparkListener.scala:146 MetricJsonLike)."""
    app_tag: Optional[str]
    total_wall_s: float
    phases: List[PhaseMetrics] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"appTag": self.app_tag,
                "totalWallSeconds": round(self.total_wall_s, 4),
                "phases": [p.to_json() for p in self.phases]}

    def log_pretty(self) -> str:
        lines = [f"App metrics{f' [{self.app_tag}]' if self.app_tag else ''}: "
                 f"{self.total_wall_s:.2f}s total"]
        for p in self.phases:
            mem = (f", {p.peak_bytes_in_use / 2**20:.0f} MiB peak"
                   if p.peak_bytes_in_use else "")
            lines.append(f"  {p.name}: {p.wall_s:.2f}s{mem}")
        return "\n".join(lines)


def _device_memory() -> Dict[str, Optional[int]]:
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        return {"bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use")}
    except Exception:
        return {"bytes_in_use": None, "peak_bytes_in_use": None}


class PhaseTimer:
    """Collects per-phase timings; nested phases are recorded flat."""

    def __init__(self):
        self.phases: List[PhaseMetrics] = []
        self._t0 = time.time()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.time()
        link0 = host_link_bytes()
        try:
            yield
        finally:
            mem = _device_memory()
            self.phases.append(PhaseMetrics(
                name, time.time() - t0,
                device_bytes_in_use=mem["bytes_in_use"],
                peak_bytes_in_use=mem["peak_bytes_in_use"],
                host_link_bytes=host_link_bytes() - link0))

    def app_metrics(self, tag: Optional[str] = None) -> AppMetrics:
        return AppMetrics(tag, time.time() - self._t0, list(self.phases))


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """Wrap a block in a jax.profiler trace (≙ the listener's event capture);
    view with tensorboard or xprof."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
