"""DAG computation and fit/transform scheduling — the TPU-native re-design of
FitStagesUtil (reference: core/src/main/scala/com/salesforce/op/utils/stages/
FitStagesUtil.scala:173-304).

``compute_dag`` layers stages by distance-to-result exactly like the reference's
``computeDAG``; ``fit_dag`` fits estimators layer-by-layer then applies the
layer's transformers.  Where the reference bulk-applies row closures in a single
RDD map (applyOpTransformations:96) and persists every K Spark stages to break
Catalyst (:134-165), we simply apply column transforms — device-resident
columns stay in HBM and XLA fuses the ops; no persistence hacks are needed
(SURVEY.md §2.6 P5).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .columns import ColumnBatch
from .features import Feature
from .stages.base import Estimator, PipelineStage, Transformer, TransformerModel
from .stages.generator import FeatureGeneratorStage

StageLayer = List[PipelineStage]


def compute_dag(result_features: Sequence[Feature]) -> List[StageLayer]:
    """Layer stages by max distance to any result feature, deepest first
    (≙ FitStagesUtil.computeDAG).  FeatureGeneratorStages are excluded — raw
    data generation is the reader's job."""
    dist: Dict[PipelineStage, int] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            if dist.get(stage, -1) < d:
                dist[stage] = d
    layers: Dict[int, StageLayer] = {}
    for stage, d in dist.items():
        if isinstance(stage, FeatureGeneratorStage):
            continue
        layers.setdefault(d, []).append(stage)
    out = [sorted(layers[d], key=lambda s: s.uid) for d in sorted(layers, reverse=True)]
    return [l for l in out if l]


def dag_stages(dag: List[StageLayer]) -> List[PipelineStage]:
    return [s for layer in dag for s in layer]


def fit_layer(batch: ColumnBatch, layer: StageLayer) -> Tuple[ColumnBatch, List[Transformer]]:
    """Fit all estimators of a layer, then apply every transformer of the layer
    (≙ fitAndTransformLayer, FitStagesUtil.scala:253)."""
    fitted: List[Transformer] = []
    for stage in layer:
        if isinstance(stage, Estimator):
            model = stage.fit(batch)
            fitted.append(model)
        elif isinstance(stage, Transformer):
            fitted.append(stage)
        else:
            raise TypeError(f"stage {stage} is neither Transformer nor Estimator")
    for t in fitted:
        batch = t.transform_batch(batch)
    return batch, fitted


def fit_dag(batch: ColumnBatch, dag: List[StageLayer]) -> Tuple[ColumnBatch, List[StageLayer]]:
    """Fit + transform the whole DAG (≙ fitAndTransformDAG:213).  Returns the
    transformed batch and the fitted DAG (same layering, estimators replaced by
    their models)."""
    fitted_dag: List[StageLayer] = []
    for layer in dag:
        batch, fitted = fit_layer(batch, layer)
        fitted_dag.append(list(fitted))
    return batch, fitted_dag


def apply_dag(batch: ColumnBatch, dag: List[StageLayer],
              up_to_feature: Optional[Feature] = None) -> ColumnBatch:
    """Apply an already-fitted DAG (≙ applyTransformationsDAG,
    OpWorkflowCore.scala:321)."""
    for layer in dag:
        for t in layer:
            if not isinstance(t, Transformer):
                raise TypeError(
                    f"DAG contains unfitted estimator {t}; fit the workflow first")
            batch = t.transform_batch(batch)
            if up_to_feature is not None and any(
                    f.name == up_to_feature.name for f in t.output_features):
                return batch
    return batch


def cut_dag(dag: List[StageLayer], selector) -> Tuple[List[StageLayer], List[StageLayer], List[StageLayer]]:
    """Split the DAG into (before, during, after) relative to a ModelSelector
    (≙ FitStagesUtil.cutDAG:304) for workflow-level cross-validation: 'during'
    holds the feature-engineering stages that must be refit inside each fold to
    avoid leakage; 'before' is everything upstream shared by all folds."""
    sel_layer_idx = None
    for i, layer in enumerate(dag):
        if any(s is selector for s in layer):
            sel_layer_idx = i
            break
    if sel_layer_idx is None:
        return dag, [], []
    # Estimators feeding the selector (directly or transitively after the last
    # upstream estimator barrier) must be refit per fold.  The reference cuts at
    # the last layer containing no estimators before the selector; we do the
    # same simple cut: 'during' = contiguous estimator-containing layers
    # immediately preceding the selector.
    start = sel_layer_idx
    while start > 0 and any(isinstance(s, Estimator) for s in dag[start - 1]):
        start -= 1
    before = dag[:start]
    during = dag[start:sel_layer_idx]
    after = dag[sel_layer_idx:]
    return before, during, after
