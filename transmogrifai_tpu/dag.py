"""DAG computation and fit/transform scheduling — the TPU-native re-design of
FitStagesUtil (reference: core/src/main/scala/com/salesforce/op/utils/stages/
FitStagesUtil.scala:173-304).

``compute_dag`` layers stages by distance-to-result exactly like the reference's
``computeDAG``; ``fit_dag`` fits estimators layer-by-layer then applies the
layer's transformers.  Where the reference bulk-applies row closures in a single
RDD map (applyOpTransformations:96) and persists every K Spark stages to break
Catalyst (:134-165), we simply apply column transforms — device-resident
columns stay in HBM and XLA fuses the ops; no persistence hacks are needed
(SURVEY.md §2.6 P5).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .columns import ColumnBatch
from .features import Feature
from .stages.base import Estimator, PipelineStage, Transformer, TransformerModel
from .stages.generator import FeatureGeneratorStage

StageLayer = List[PipelineStage]


def compute_dag(result_features: Sequence[Feature]) -> List[StageLayer]:
    """Layer stages by max distance to any result feature, deepest first
    (≙ FitStagesUtil.computeDAG).  FeatureGeneratorStages are excluded — raw
    data generation is the reader's job."""
    dist: Dict[PipelineStage, int] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            if dist.get(stage, -1) < d:
                dist[stage] = d
    layers: Dict[int, StageLayer] = {}
    for stage, d in dist.items():
        if isinstance(stage, FeatureGeneratorStage):
            continue
        layers.setdefault(d, []).append(stage)
    out = [sorted(layers[d], key=lambda s: s.uid) for d in sorted(layers, reverse=True)]
    return [l for l in out if l]


def dag_stages(dag: List[StageLayer]) -> List[PipelineStage]:
    return [s for layer in dag for s in layer]


def prune_batch(batch: ColumnBatch, remaining_stages, keep_names) -> ColumnBatch:
    """Release columns no remaining stage consumes (HBM liveness — the TPU
    analog of the reference's persist/unpersist discipline): a device-resident
    intermediate like a hashed text block is GBs at scale, and holding it
    alive past its last consumer is what out-of-memories a 16 GB chip."""
    needed = set(keep_names)
    for s in remaining_stages:
        needed.update(f.name for f in s.input_features)
    drop = [n for n in batch.names() if n not in needed]
    return batch.drop(drop) if drop else batch


def fit_layer(batch: ColumnBatch, layer: StageLayer) -> Tuple[ColumnBatch, List[Transformer]]:
    """Fit all estimators of a layer, then apply every transformer of the layer
    (≙ fitAndTransformLayer, FitStagesUtil.scala:253)."""
    fitted: List[Transformer] = []
    for stage in layer:
        if isinstance(stage, Estimator):
            model = stage.fit(batch)
            fitted.append(model)
        elif isinstance(stage, Transformer):
            fitted.append(stage)
        else:
            raise TypeError(f"stage {stage} is neither Transformer nor Estimator")
    for t in fitted:
        batch = t.transform_batch(batch)
    return batch, fitted


def fit_dag(batch: ColumnBatch, dag: List[StageLayer]) -> Tuple[ColumnBatch, List[StageLayer]]:
    """Fit + transform the whole DAG (≙ fitAndTransformDAG:213).  Returns the
    transformed batch and the fitted DAG (same layering, estimators replaced by
    their models)."""
    fitted_dag: List[StageLayer] = []
    for layer in dag:
        batch, fitted = fit_layer(batch, layer)
        fitted_dag.append(list(fitted))
    return batch, fitted_dag


def apply_dag(batch: ColumnBatch, dag: List[StageLayer],
              up_to_feature: Optional[Feature] = None) -> ColumnBatch:
    """Apply an already-fitted DAG (≙ applyTransformationsDAG,
    OpWorkflowCore.scala:321)."""
    for layer in dag:
        for t in layer:
            if not isinstance(t, Transformer):
                raise TypeError(
                    f"DAG contains unfitted estimator {t}; fit the workflow first")
            batch = t.transform_batch(batch)
            if up_to_feature is not None and any(
                    f.name == up_to_feature.name for f in t.output_features):
                return batch
    return batch


def cut_dag(dag: List[StageLayer], selector) -> Tuple[List[StageLayer], List[StageLayer], List[StageLayer]]:
    """Split the DAG into (before, during, after) relative to a ModelSelector
    for workflow-level cross-validation (≙ FitStagesUtil.cutDAG:304-356).

    Reference semantics: label leakage flows only through stages that consume
    BOTH a response and a non-response input (SanityChecker and friends), so
    'during' — the sub-DAG refit inside every fold — is the selector's
    ancestor DAG from the first such label-consuming layer onward
    (``firstCVTSIndex``, FitStagesUtil.scala:333-337).  Everything upstream of
    that layer ('before') is fit once on the full data, even estimators,
    exactly as the reference does; side branches feeding other result features
    also stay in 'before' (the ``nonMSDAG - CVTSDAG`` rule, :344-349)."""
    sel_layer_idx = None
    for i, layer in enumerate(dag):
        if any(s is selector for s in layer):
            sel_layer_idx = i
            break
    if sel_layer_idx is None:
        return dag, [], []

    # the selector's own ancestor DAG, deepest-first, selector layer dropped
    anc_layers = compute_dag(selector.output_features)
    if anc_layers and any(s is selector for s in anc_layers[-1]):
        anc_layers = anc_layers[:-1]

    def consumes_label_and_features(stage) -> bool:
        ins = stage.input_features
        return (any(f.is_response for f in ins)
                and any(not f.is_response for f in ins))

    first = next((i for i, layer in enumerate(anc_layers)
                  if any(consumes_label_and_features(s) for s in layer)), -1)
    during_stages = (set() if first < 0 else
                     {s for layer in anc_layers[first:] for s in layer})

    # side branches consuming a 'during' output must follow it into 'during':
    # leaving them in 'before' would run them ahead of their producer.  One
    # forward pass suffices — layers are topologically ordered.
    during_out = {f.name for s in during_stages for f in s.output_features}
    for layer in dag[:sel_layer_idx]:
        for s in layer:
            if s not in during_stages and any(
                    f.name in during_out for f in s.input_features):
                during_stages.add(s)
                during_out.update(f.name for f in s.output_features)

    before = [[s for s in layer if s not in during_stages]
              for layer in dag[:sel_layer_idx]]
    during = [[s for s in layer if s in during_stages]
              for layer in dag[:sel_layer_idx]]
    after = dag[sel_layer_idx:]
    return ([l for l in before if l], [l for l in during if l], after)
