"""Poison-data firewall: schema contracts, per-record quarantine, and
non-finite guards (reference: the data-quality half of SanityChecker /
RawFeatureFilter — PAPER.md "auto-validates them" — applied to the HOT
paths instead of the whole-batch filter pass).

The hot paths used to trust their input: one hostile record in a coalesced
serving micro-batch failed every co-batched neighbor (``records_to_batch``
does bare coercion — ``float("junk")`` throws for the whole batch), readers
raised mid-file on malformed rows, and nothing stopped NaN/Inf from flowing
onto the device or back out as a silently-poisoned score.  This module is
the firewall:

* ``RawSchema`` — the per-bundle schema contract derived from the raw
  features (name → kind, nullable, numeric range hints from the training
  batch), serialized digest-covered as ``schema.json`` in every bundle and
  enforced at train ingestion and serving assembly.
* A typed violation taxonomy — ``MissingRequiredField`` / ``TypeMismatch``
  / ``NonCoercibleValue`` / ``NonFiniteValue`` / ``UnknownField`` — under a
  ``strict | coerce | quarantine`` policy (``qualityParams`` in OpParams).
  The default ``coerce`` keeps historical behavior for inputs the old path
  accepted (observable-but-unchanged) and quarantines only records the old
  path would have crashed on.
* Per-record quarantine: a rejected record carries its violations in a
  ``RecordQualityError`` (HTTP 422 at the server) while neighbors score
  normally; at training, quarantined rows are excluded with counters and a
  ``maxQuarantineFraction`` guard that aborts with ``DataQualityError``
  rather than silently training on a fraction of the data.
* The non-finite firewall: finite-mask reductions (``jnp.isfinite`` on
  device arrays, ``np.isfinite`` on host arrays — same reduction, jit-
  compatible) at the host→device seam and on fused scoring outputs, with
  ``quality.nonfinite_inputs_total`` / ``quality.nonfinite_scores_total``
  accounting.
"""

from __future__ import annotations

import json
import math
import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import (Any, Dict, Iterable, List, Optional, Sequence, Tuple,
                    Type)

import numpy as np

from .types import (FeatureType, OPList, OPMap, OPVector,
                    feature_type_from_name, is_map_kind, is_numeric_kind,
                    is_text_kind, map_value_kind)

SCHEMA_JSON = "schema.json"
SCHEMA_FORMAT_VERSION = 1

# -- the violation taxonomy -------------------------------------------------

MISSING_REQUIRED_FIELD = "MissingRequiredField"
TYPE_MISMATCH = "TypeMismatch"
NON_COERCIBLE_VALUE = "NonCoercibleValue"
NON_FINITE_VALUE = "NonFiniteValue"
UNKNOWN_FIELD = "UnknownField"

VIOLATION_KINDS = (MISSING_REQUIRED_FIELD, TYPE_MISMATCH,
                   NON_COERCIBLE_VALUE, NON_FINITE_VALUE, UNKNOWN_FIELD)

# violations the OLD ingestion path would have crashed on (or silently
# poisoned a score with): these reject the record under EVERY policy —
# "coerce keeps old behavior" means old *working* behavior, not old crashes
FATAL_KINDS = frozenset({NON_COERCIBLE_VALUE, NON_FINITE_VALUE})

POLICIES = ("strict", "coerce", "quarantine", "off")
DEFAULT_POLICY = "coerce"


@dataclass
class Violation:
    """One typed schema violation, attributable to a field (and, for
    columnar/batch validation, a row)."""
    kind: str
    field: str
    message: str
    row: Optional[int] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"kind": self.kind, "field": self.field,
                             "message": self.message}
        if self.row is not None:
            d["row"] = int(self.row)
        return d


class RecordQualityError(ValueError):
    """A record (or identified rows of a columnar request) failed schema
    validation.  The server maps this to a structured HTTP 422 carrying the
    full violation list — the caller learns exactly what was wrong, and
    co-batched neighbors are unaffected."""

    def __init__(self, violations: Sequence[Violation],
                 policy: str = DEFAULT_POLICY):
        self.violations = list(violations)
        self.policy = policy
        head = self.violations[0] if self.violations else None
        desc = (f"{head.kind} on field {head.field!r}: {head.message}"
                if head else "schema validation failed")
        more = len(self.violations) - 1
        super().__init__(desc + (f" (+{more} more violation(s))"
                                 if more > 0 else ""))

    def to_json(self) -> List[Dict[str, Any]]:
        return [v.to_json() for v in self.violations]


class DataQualityError(RuntimeError):
    """Training aborted because the quarantined fraction exceeded
    ``maxQuarantineFraction`` — the data is too poisoned to silently train
    on what remains."""

    def __init__(self, quarantined: int, total: int, limit: float,
                 sample: Optional[Sequence[Violation]] = None):
        self.quarantined = int(quarantined)
        self.total = int(total)
        self.fraction = (float(quarantined) / total) if total else 1.0
        self.limit = float(limit)
        self.sample = list(sample or [])
        detail = "; ".join(f"{v.kind}({v.field})" for v in self.sample[:5])
        super().__init__(
            f"{quarantined}/{total} rows ({self.fraction:.1%}) quarantined "
            f"by the data-quality firewall — exceeds maxQuarantineFraction="
            f"{limit:g}" + (f"; sample: {detail}" if detail else ""))


# -- policy / run configuration ---------------------------------------------

@dataclass
class QualityConfig:
    """Resolved quality knobs for one run (``qualityParams`` in OpParams,
    ``TRANSMOGRIFAI_QUALITY*`` in the environment)."""
    policy: str = DEFAULT_POLICY
    max_quarantine_fraction: float = 0.1
    enabled: bool = True

    @staticmethod
    def resolve(params: Optional[Dict[str, Any]] = None) -> "QualityConfig":
        """Environment defaults overridden by an explicit params dict
        (camelCase keys, the OpParams convention)."""
        p = dict(params or {})
        policy = p.get("policy",
                       os.environ.get("TRANSMOGRIFAI_QUALITY_POLICY",
                                      DEFAULT_POLICY))
        if policy not in POLICIES:
            raise ValueError(f"unknown quality policy {policy!r}; expected "
                             f"one of {POLICIES}")
        frac = p.get("maxQuarantineFraction")
        if frac is None:
            frac = float(os.environ.get(
                "TRANSMOGRIFAI_MAX_QUARANTINE_FRACTION", "0.1"))
        enabled = p.get("enabled")
        if enabled is None:
            enabled = os.environ.get("TRANSMOGRIFAI_QUALITY", "1") != "0"
        if policy == "off":
            enabled = False
        return QualityConfig(policy=policy,
                             max_quarantine_fraction=float(frac),
                             enabled=bool(enabled))


# ambient config for the dynamic extent of a train/stream run, so readers —
# which have no params channel of their own — screen records with the run's
# policy (the ``use_failure_log`` pattern)
_CFG_STACK: List[QualityConfig] = []
_CFG_LOCK = threading.Lock()


def active_quality() -> Optional[QualityConfig]:
    """The innermost installed config, or None (firewall dormant — readers
    behave exactly as before)."""
    with _CFG_LOCK:
        return _CFG_STACK[-1] if _CFG_STACK else None


@contextmanager
def use_quality(cfg: QualityConfig):
    """Install ``cfg`` as the ambient quality config for the extent."""
    with _CFG_LOCK:
        _CFG_STACK.append(cfg)
    try:
        yield cfg
    finally:
        with _CFG_LOCK:
            for i in range(len(_CFG_STACK) - 1, -1, -1):
                if _CFG_STACK[i] is cfg:
                    del _CFG_STACK[i]
                    break


# -- the schema contract ----------------------------------------------------

@dataclass
class FieldSchema:
    """One raw feature's contract: kind, nullability, response-ness and an
    optional numeric (min, max) hint from the training sketches.  Range
    hints are observability (drift/debug context in ``schema.json``), not a
    rejection rule — serving-time distribution shift is drift's job."""
    name: str
    kind: Type[FeatureType]
    nullable: bool = True
    is_response: bool = False
    range: Optional[Tuple[float, float]] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "kind": self.kind.__name__,
                             "nullable": bool(self.nullable),
                             "isResponse": bool(self.is_response)}
        if self.range is not None:
            d["range"] = [float(self.range[0]), float(self.range[1])]
        return d


def _is_number(v: Any) -> bool:
    return (isinstance(v, (int, float, np.integer, np.floating))
            and not isinstance(v, bool))


def _finite(v: Any) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError, OverflowError):
        return False


class RawSchema:
    """The bundle's data contract: every raw feature's ``FieldSchema``.

    Derived from the workflow's raw features at save time (with numeric
    range hints from the retained train batch), serialized digest-covered
    as ``schema.json``, re-derived from the rebuilt features for legacy
    bundles that predate it."""

    def __init__(self, fields: Dict[str, FieldSchema]):
        self.fields = dict(fields)

    def __contains__(self, name: str) -> bool:
        return name in self.fields

    def __len__(self) -> int:
        return len(self.fields)

    # -- construction / persistence ----------------------------------------
    @staticmethod
    def derive(raw_features: Sequence, batch=None) -> "RawSchema":
        """Features → contract; with the training ``batch``, numeric range
        hints come from the same finite-only min/max the drift sketches use
        (``filters.numeric_ranges``)."""
        fields: Dict[str, FieldSchema] = {}
        for f in raw_features:
            rng = None
            if batch is not None and is_numeric_kind(f.kind) \
                    and f.name in batch:
                try:
                    from .filters import numeric_ranges
                    rng = numeric_ranges(f, batch[f.name]).get(None)
                except Exception:  # noqa: BLE001 — hints are optional
                    rng = None
            fields[f.name] = FieldSchema(
                name=f.name, kind=f.kind,
                nullable=not f.kind.non_nullable,
                is_response=bool(getattr(f, "is_response", False)),
                range=rng)
        return RawSchema(fields)

    def to_json(self) -> Dict[str, Any]:
        return {"formatVersion": SCHEMA_FORMAT_VERSION,
                "fields": [fs.to_json()
                           for fs in self.fields.values()]}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "RawSchema":
        fields: Dict[str, FieldSchema] = {}
        for fd in d.get("fields") or []:
            try:
                kind = feature_type_from_name(fd["kind"])
            except (KeyError, ValueError):
                continue    # a kind this build doesn't know: skip the field
            rng = fd.get("range")
            fields[fd["name"]] = FieldSchema(
                name=fd["name"], kind=kind,
                nullable=bool(fd.get("nullable", True)),
                is_response=bool(fd.get("isResponse", False)),
                range=tuple(rng) if rng else None)
        return RawSchema(fields)

    def save(self, bundle_dir: str) -> None:
        with open(os.path.join(bundle_dir, SCHEMA_JSON), "w") as fh:
            json.dump(self.to_json(), fh, indent=2)

    @staticmethod
    def load(bundle_dir: str) -> Optional["RawSchema"]:
        path = os.path.join(bundle_dir, SCHEMA_JSON)
        if not os.path.exists(path):
            return None
        with open(path) as fh:
            return RawSchema.from_json(json.load(fh))

    @staticmethod
    def for_model(model, bundle_path: Optional[str] = None) -> "RawSchema":
        """The schema a serving engine should enforce: the bundle's
        ``schema.json`` when present and readable, else re-derived from the
        model's raw features (legacy bundles — degrade, never fail)."""
        if bundle_path:
            try:
                sch = RawSchema.load(bundle_path)
                if sch is not None and len(sch):
                    return sch
            except Exception as e:  # noqa: BLE001 — corrupt schema.json
                from .resilience import record_failure
                record_failure("serving", "degraded", e,
                               point="serving.quality", bundle=bundle_path,
                               detail="unreadable schema.json; contract "
                                      "re-derived from raw features")
        return RawSchema.derive(model.raw_features)

    # -- record validation ---------------------------------------------------
    def validate_record(self, record: Dict[str, Any]
                        ) -> Tuple[Dict[str, Any], List[Violation]]:
        """Validate (and where possible coerce) one record against the
        contract.  Returns ``(record, violations)`` — a NEW dict only when
        a coercion changed something, so clean records pass through
        untouched (bitwise parity with the unvalidated path).  Never
        raises; policy decisions belong to ``rejects``."""
        violations: List[Violation] = []
        out = record
        changed = False

        def coerce(name: str, value: Any) -> None:
            nonlocal out, changed
            if not changed:
                out = dict(record)
                changed = True
            out[name] = value

        for name, fs in self.fields.items():
            present = name in record
            val = record.get(name)
            if isinstance(val, FeatureType):
                val = val.value
            if val is None:
                if not fs.nullable and not fs.is_response and present:
                    # an EXPLICIT null in a non-nullable predictor; an
                    # absent one is the normal unlabeled-scoring shape and
                    # takes the monoid zero silently, as it always has
                    violations.append(Violation(
                        MISSING_REQUIRED_FIELD, name,
                        f"null value for non-nullable {fs.kind.__name__}"))
                continue
            kind = fs.kind
            if is_numeric_kind(kind):
                self._check_numeric(name, kind, val, violations, coerce)
            elif is_text_kind(kind):
                if not isinstance(val, str):
                    # str(v) is what the old path did; keep it, visibly
                    violations.append(Violation(
                        TYPE_MISMATCH, name,
                        f"{type(val).__name__} where {kind.__name__} "
                        "expects a string"))
                    coerce(name, str(val))
            elif is_map_kind(kind):
                if not isinstance(val, dict):
                    violations.append(Violation(
                        NON_COERCIBLE_VALUE, name,
                        f"{type(val).__name__} where {kind.__name__} "
                        "expects an object"))
                elif is_numeric_kind(map_value_kind(kind)):
                    for k, mv in val.items():
                        if mv is None:
                            continue
                        if isinstance(mv, bool):
                            continue        # BinaryMap values
                        if not _is_number(mv):
                            violations.append(Violation(
                                NON_COERCIBLE_VALUE, f"{name}.{k}",
                                f"{type(mv).__name__} where "
                                f"{kind.__name__} expects numeric values"))
                        elif not _finite(mv):
                            violations.append(Violation(
                                NON_FINITE_VALUE, f"{name}.{k}",
                                f"non-finite value {mv!r}"))
            elif issubclass(kind, OPVector) or issubclass(kind, OPList):
                if isinstance(val, (list, tuple, np.ndarray)):
                    items = (val.tolist() if isinstance(val, np.ndarray)
                             else val)
                    if issubclass(kind, OPVector) and any(
                            _is_number(x) and not _finite(x)
                            for x in items):
                        violations.append(Violation(
                            NON_FINITE_VALUE, name,
                            "non-finite element in vector"))
                else:
                    violations.append(Violation(
                        NON_COERCIBLE_VALUE, name,
                        f"{type(val).__name__} where {kind.__name__} "
                        "expects a list"))
            # remaining kinds (sets, geolocation variants ride OPList
            # above) pass through — the old path stored them opaquely
        for name in record:
            if name not in self.fields and name != "key":
                violations.append(Violation(
                    UNKNOWN_FIELD, name,
                    "field is not in the model's raw schema"))
        return out, violations

    @staticmethod
    def _check_numeric(name, kind, val, violations, coerce) -> None:
        from .types import Binary
        if isinstance(val, bool) or _is_number(val):
            if not _finite(val):
                violations.append(Violation(
                    NON_FINITE_VALUE, name, f"non-finite value {val!r}"))
            return
        if isinstance(val, str):
            violations.append(Violation(
                TYPE_MISMATCH, name,
                f"str where {kind.__name__} expects a number"))
            if issubclass(kind, Binary):
                # the old path's bool(v) made ANY non-empty string True
                # ("false" included) — only unambiguous spellings coerce
                low = val.strip().lower()
                if low in ("true", "1"):
                    coerce(name, True)
                elif low in ("false", "0", ""):
                    coerce(name, False)
                else:
                    violations.append(Violation(
                        NON_COERCIBLE_VALUE, name,
                        f"{val[:40]!r} is not a boolean"))
                return
            try:
                parsed = float(val)
            except (TypeError, ValueError):
                violations.append(Violation(
                    NON_COERCIBLE_VALUE, name,
                    f"{val[:40]!r} does not parse as a number"))
                return
            if not math.isfinite(parsed):
                violations.append(Violation(
                    NON_FINITE_VALUE, name,
                    f"{val!r} parses to a non-finite number"))
                return
            coerce(name, parsed)
            return
        violations.append(Violation(
            NON_COERCIBLE_VALUE, name,
            f"{type(val).__name__} where {kind.__name__} expects a number"))

    @staticmethod
    def rejects(violations: Sequence[Violation], policy: str) -> bool:
        """Does ``policy`` quarantine a record with these violations?
        ``strict`` rejects any violation; ``quarantine`` tolerates only the
        purely-observational ``UnknownField``; ``coerce`` (default) rejects
        only what the old path crashed on (the FATAL kinds)."""
        if not violations or policy == "off":
            return False
        if policy == "strict":
            return True
        if policy == "quarantine":
            return any(v.kind != UNKNOWN_FIELD for v in violations)
        return any(v.kind in FATAL_KINDS for v in violations)

    def screen_record(self, record: Dict[str, Any], policy: str
                      ) -> Tuple[Dict[str, Any], List[Violation], bool]:
        """``(record, violations, rejected)`` in one call."""
        out, violations = self.validate_record(record)
        return out, violations, self.rejects(violations, policy)


# -- the non-finite firewall (host→device seam + scoring outputs) ----------

def finite_row_mask(values, mask=None):
    """Per-row all-finite reduction over a float array, respecting an
    optional presence mask (absent cells are vacuously fine — numeric
    columns store NaN at masked-off positions by design).  Runs the same
    ``isfinite``/``all`` reduction on device (``jnp``, jit-compatible) when
    handed a jax array, on host (``np``) otherwise."""
    if values.__class__.__module__.startswith("jax"):
        import jax.numpy as jnp
        ok = jnp.isfinite(values)
        if mask is not None:
            ok = ok | ~jnp.asarray(mask)
        return ok if ok.ndim == 1 else jnp.all(
            ok.reshape(ok.shape[0], -1), axis=1)
    arr = np.asarray(values)
    ok = np.isfinite(arr)
    if mask is not None:
        m = np.asarray(mask, dtype=bool)
        ok = ok | ~m.reshape(m.shape + (1,) * (ok.ndim - 1))
    return ok if ok.ndim == 1 else np.all(
        ok.reshape(ok.shape[0], -1), axis=1)


def batch_nonfinite_rows(batch, schema: Optional[RawSchema] = None
                         ) -> Dict[int, List[Violation]]:
    """Row → violations for non-finite values at PRESENT positions of the
    float columns of an assembled ``ColumnBatch`` — the host→device seam
    check (everything in these arrays is about to ship to the device)."""
    out: Dict[int, List[Violation]] = {}
    for name, col in batch.items():
        vals = getattr(col, "values", None)
        if not isinstance(vals, np.ndarray) or \
                not np.issubdtype(vals.dtype, np.floating):
            continue
        if schema is not None and name in schema.fields and \
                not is_numeric_kind(schema.fields[name].kind) and \
                not issubclass(schema.fields[name].kind, OPVector):
            continue
        ok = finite_row_mask(vals, getattr(col, "mask", None))
        for i in np.nonzero(~np.asarray(ok))[0]:
            out.setdefault(int(i), []).append(Violation(
                NON_FINITE_VALUE, name, "non-finite value in column",
                row=int(i)))
    return out


def result_nonfinite_fields(result: Dict[str, Any]) -> List[str]:
    """Field paths of non-finite floats in one scored result row (nested
    prediction dicts included) — empty means the row is clean."""
    bad: List[str] = []
    for name, v in result.items():
        if isinstance(v, dict):
            for k, sub in v.items():
                if _is_number(sub) and not _finite(sub):
                    bad.append(f"{name}.{k}")
        elif _is_number(v) and not _finite(v):
            bad.append(name)
    return bad


def mask_nonfinite_result_arrays(arrays: Dict[str, Any]
                                 ) -> Tuple[Dict[str, Any], np.ndarray]:
    """Columnar-output firewall: mask non-finite score cells as ABSENT in
    ``{name: (values, mask)}`` result arrays instead of shipping NaN to the
    caller.  Returns ``(arrays, bad_row_mask)``; arrays are modified only
    when something was non-finite."""
    bad_rows: Optional[np.ndarray] = None
    out = dict(arrays)
    n = 0
    for name, (vals, mask) in arrays.items():
        arr = np.asarray(vals)
        n = max(n, arr.shape[0] if arr.ndim else 0)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        ok = np.asarray(finite_row_mask(arr, mask))
        if ok.all():
            continue
        new_mask = (np.ones(arr.shape[0], dtype=bool) if mask is None
                    else np.asarray(mask, dtype=bool).copy())
        new_mask &= ok
        out[name] = (np.where(np.isfinite(arr), arr, 0.0)
                     if arr.ndim == 1 else arr, new_mask)
        bad_rows = ~ok if bad_rows is None else (bad_rows | ~ok)
    if bad_rows is None:
        bad_rows = np.zeros(n, dtype=bool)
    return out, bad_rows


# -- training-side quarantine ----------------------------------------------

def _quality_counters(stage: str, violations: Iterable[Violation],
                      quarantined: int = 0,
                      trace_id: Optional[str] = None,
                      registry=None) -> None:
    """Shared counter accounting: total + per-kind violation counters and
    the quarantined-rows counter, in the given registry (an engine's) or
    the process-wide one (training/readers)."""
    if registry is None:
        from .telemetry import REGISTRY
        registry = REGISTRY
    n = 0
    for v in violations:
        n += 1
        registry.counter(
            f"quality.violations_{v.kind}_total").inc(trace_id=trace_id)
    if n:
        registry.counter("quality.violations_total").inc(
            n, trace_id=trace_id)
    if quarantined:
        registry.counter("quality.rows_quarantined_total").inc(quarantined)


def screen_records(records: List[Dict[str, Any]], raw_features: Sequence,
                   cfg: Optional[QualityConfig] = None, *,
                   stage: str = "reader",
                   schema: Optional[RawSchema] = None
                   ) -> List[Dict[str, Any]]:
    """Per-record quarantine for an ingestion record list: validate every
    record against the contract, keep the survivors (coerced in place where
    the policy allows), exclude the rest with full accounting, and abort
    with ``DataQualityError`` past ``maxQuarantineFraction``.  With no
    ambient/explicit config the input is returned untouched."""
    cfg = cfg or active_quality()
    if cfg is None or not cfg.enabled or not records:
        return records
    sch = schema or RawSchema.derive(raw_features)
    kept: List[Dict[str, Any]] = []
    sample: List[Violation] = []
    quarantined = 0
    for rec in records:
        out, violations, rejected = sch.screen_record(rec, cfg.policy)
        if violations:
            _quality_counters(stage, violations)
        if rejected:
            quarantined += 1
            if len(sample) < 8:
                sample.extend(violations[:2])
            from .resilience import record_failure
            record_failure(stage, "quarantined",
                           RecordQualityError(violations, cfg.policy),
                           point=f"{stage}.quality",
                           violations=[v.to_json() for v in violations[:4]])
        else:
            kept.append(out)
    if quarantined:
        _quality_counters(stage, (), quarantined=quarantined)
        frac = quarantined / len(records)
        if frac > cfg.max_quarantine_fraction:
            raise DataQualityError(quarantined, len(records),
                                   cfg.max_quarantine_fraction,
                                   sample=sample)
    return kept


def screen_batch(batch, raw_features: Sequence,
                 cfg: Optional[QualityConfig] = None, *,
                 stage: str = "train",
                 schema: Optional[RawSchema] = None):
    """Non-finite firewall for an assembled training ``ColumnBatch``: drop
    rows carrying NaN/Inf at present positions of raw numeric columns
    before anything ships to the device, with the same accounting and
    ``maxQuarantineFraction`` guard as ``screen_records``.  Returns the
    (possibly row-filtered) batch."""
    cfg = cfg or active_quality()
    if cfg is None or not cfg.enabled or len(batch) == 0:
        return batch
    sch = schema or RawSchema.derive(raw_features)
    by_row = batch_nonfinite_rows(batch, sch)
    if not by_row:
        return batch
    from .resilience import record_failure
    from .telemetry import REGISTRY
    n = len(batch)
    bad = sorted(by_row)
    REGISTRY.counter("quality.nonfinite_inputs_total").inc(len(bad))
    sample = [v for i in bad[:4] for v in by_row[i][:2]]
    _quality_counters(stage, sample)
    _quality_counters(stage, (), quarantined=len(bad))
    record_failure(stage, "quarantined",
                   f"{len(bad)} row(s) with non-finite values excluded "
                   "before device transfer", point=f"{stage}.quality",
                   rows=bad[:16])
    if len(bad) / n > cfg.max_quarantine_fraction:
        raise DataQualityError(len(bad), n, cfg.max_quarantine_fraction,
                               sample=sample)
    keep = np.setdiff1d(np.arange(n), np.asarray(bad, dtype=int))
    return batch.take_rows(keep)
