"""OpWorkflowRunner / OpApp — run-type dispatch and CLI harness (reference:
core/src/main/scala/com/salesforce/op/OpWorkflowRunner.scala:296-365 and
OpApp.scala:130-213).

Run types: Train / Score / StreamingScore / Features / Evaluate — the same
five (OpWorkflowRunner.scala:358-365).  Profiling hooks replace
OpSparkListener: per-phase wall-clock + device memory stats collected into
``AppMetrics`` and delivered to completion callbacks.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .checkpoint import (TrainingPreempted, preemption_guard,
                         shutdown_requested, write_json_atomic)
from .params import OpParams
from .profiling import AppMetrics, PhaseTimer
from .resilience import (FailureLog, RetryPolicy, maybe_inject,
                         use_failure_log)
from .workflow import Workflow, WorkflowModel


class RunType:
    TRAIN = "train"
    SCORE = "score"
    STREAMING_SCORE = "streamingScore"
    FEATURES = "features"
    EVALUATE = "evaluate"
    SERVE = "serve"
    LIFECYCLE = "lifecycle"

    ALL = (TRAIN, SCORE, STREAMING_SCORE, FEATURES, EVALUATE, SERVE,
           LIFECYCLE)


@dataclass
class OpWorkflowRunnerResult:
    """≙ OpWorkflowRunnerResult variants."""
    run_type: str
    model_summary: Optional[Dict[str, Any]] = None
    metrics: Optional[Dict[str, Any]] = None
    scores_location: Optional[str] = None
    app_metrics: Optional[AppMetrics] = None
    failure_log: Optional[FailureLog] = None
    # streaming micro-batches that exhausted their retries:
    # [{"index", "error", "batch"}] — the batch rides along for reprocessing
    dead_letters: List[Dict[str, Any]] = field(default_factory=list)
    # the run's Tracer when telemetryParams enabled tracing (telemetry.py)
    tracer: Optional[Any] = None


class OpWorkflowRunner:
    """≙ OpWorkflowRunner.scala:296."""

    def __init__(self, workflow: Optional[Workflow] = None,
                 train_reader=None, score_reader=None,
                 evaluator=None, evaluation_feature=None,
                 features_to_compute=None,
                 retry_policy: Optional[RetryPolicy] = None,
                 failure_log: Optional[FailureLog] = None,
                 dead_letter_max: int = 256):
        # score / streaming-score / evaluate / features run types load a
        # saved model and need no workflow; only train requires one
        self.workflow = workflow
        self.train_reader = train_reader
        self.score_reader = score_reader
        self.evaluator = evaluator
        self.evaluation_feature = evaluation_feature
        self.features_to_compute = features_to_compute
        # resilience: transient streaming-batch failures retry per policy;
        # exhausted batches dead-letter instead of killing the stream.
        # The DLQ is bounded (a persistently-failing stream would otherwise
        # grow it without limit): past ``dead_letter_max`` the OLDEST entry
        # is evicted — its index stays in the failure log even though the
        # batch payload is gone
        self.retry_policy = retry_policy
        self.failure_log = failure_log
        self.dead_letter_max = max(1, int(dead_letter_max))
        self._completion_callbacks: List[Callable[[AppMetrics], None]] = []

    def add_application_completion_handler(self, fn: Callable[[AppMetrics], None]):
        """≙ addApplicationCompletionHandler (OpWorkflowRunner.scala:300)."""
        self._completion_callbacks.append(fn)

    # -- dispatch (≙ run:296-316) -----------------------------------------
    def run(self, run_type: str, params: OpParams) -> OpWorkflowRunnerResult:
        # telemetryParams: traceDir turns the whole run into a traced run —
        # every phase/selector/checkpoint span lands in one tracer, exported
        # as Chrome-trace JSON + telemetry.json when the run finishes
        import contextlib

        from .telemetry import Tracer, use_tracer
        # aotParams: the "enabled" knob is a process-wide kill switch —
        # train stops exporting executables into bundles, load stops
        # installing them (JIT path everywhere)
        ap = params.aot or {}
        if ap.get("enabled") is False:
            from .aot import set_aot_enabled
            set_aot_enabled(False)
        if ap.get("ladderMax") is not None:
            os.environ["TRANSMOGRIFAI_AOT_LADDER_MAX"] = str(ap["ladderMax"])
        # registryParams: configure the compiled-program registry (root,
        # byte budgets, kill switch).  When no root is pinned anywhere it
        # defaults next to the sweep checkpoints (see the run-type blocks),
        # so a standing host accumulates its own warm registry
        rp = params.registry or {}
        from .aot_registry import configure as configure_registry
        configure_registry(
            root=rp.get("root"),
            enabled=(bool(rp["enabled"]) if rp.get("enabled") is not None
                     else None),
            cap_bytes=rp.get("capBytes"),
            keep_min=rp.get("keepMin"),
            cache_cap_bytes=rp.get("cacheCapBytes"))
        # meshParams: the mesh decision is made per-fit from the environment
        # (parallel/mesh.py), so the per-run knobs ride the env knobs
        mp = params.mesh or {}
        if mp.get("enabled") is not None:
            os.environ["TRANSMOGRIFAI_TPU_MESH"] = \
                "1" if mp["enabled"] else "0"
        if mp.get("modelWidth") is not None:
            os.environ["TRANSMOGRIFAI_TPU_MESH_MODEL"] = str(mp["modelWidth"])
        if mp.get("chunkBytes") is not None:
            os.environ["TRANSMOGRIFAI_DEVICE_CHUNK_BYTES"] = \
                str(mp["chunkBytes"])
        if mp.get("minRows") is not None:
            os.environ["TRANSMOGRIFAI_TPU_MESH_MIN_ROWS"] = \
                str(mp["minRows"])
        # supervisorParams: same pattern — the supervisor reads the process
        # env per call, so run-scoped knobs ride the env knobs
        sup = params.supervisor or {}
        if sup.get("enabled") is not None:
            os.environ["TRANSMOGRIFAI_SUPERVISOR"] = \
                "1" if sup["enabled"] else "0"
        if sup.get("probeTimeoutS") is not None:
            os.environ["TRANSMOGRIFAI_PROBE_TIMEOUT_S"] = \
                str(sup["probeTimeoutS"])
        if sup.get("probeBackoffs") is not None:
            b = sup["probeBackoffs"]
            os.environ["TRANSMOGRIFAI_PROBE_BACKOFFS"] = \
                ",".join(str(x) for x in b) \
                if isinstance(b, (list, tuple)) else str(b)
        if sup.get("chunkDeadlineS") is not None:
            os.environ["TRANSMOGRIFAI_CHUNK_DEADLINE_S"] = \
                str(sup["chunkDeadlineS"])
        if sup.get("sweepRecoveries") is not None:
            os.environ["TRANSMOGRIFAI_SWEEP_RECOVERIES"] = \
                str(sup["sweepRecoveries"])
        if sup.get("outageDir") is not None:
            os.environ["TRANSMOGRIFAI_OUTAGE_DIR"] = str(sup["outageDir"])
        if sup.get("heartbeatS") is not None:
            os.environ["TRANSMOGRIFAI_HEARTBEAT_S"] = str(sup["heartbeatS"])
        # hostgroupParams: cross-host liveness knobs ride the env the same
        # way (hostgroup.py reads them per call, so launcher-exported values
        # and per-run overrides compose)
        hg_params = params.hostgroup or {}
        if hg_params.get("beatIntervalS") is not None:
            os.environ["TRANSMOGRIFAI_HOSTGROUP_BEAT_S"] = \
                str(hg_params["beatIntervalS"])
        if hg_params.get("livenessTimeoutS") is not None:
            os.environ["TRANSMOGRIFAI_HOSTGROUP_LIVENESS_S"] = \
                str(hg_params["livenessTimeoutS"])
        if hg_params.get("barrierTimeoutS") is not None:
            os.environ["TRANSMOGRIFAI_HOSTGROUP_BARRIER_S"] = \
                str(hg_params["barrierTimeoutS"])
        if hg_params.get("initTimeoutS") is not None:
            os.environ["TRANSMOGRIFAI_HOSTGROUP_INIT_S"] = \
                str(hg_params["initTimeoutS"])
        if hg_params.get("distributed") is not None:
            os.environ["TRANSMOGRIFAI_HOSTGROUP_DISTRIBUTED"] = \
                "1" if hg_params["distributed"] else "0"
        # memoryParams: the governor reads the env per call (preflight plan
        # per fold group, ladder per retry), so run-scoped knobs ride the
        # env knobs exactly like the supervisor's
        memp = params.memory or {}
        if memp.get("enabled") is not None:
            os.environ["TRANSMOGRIFAI_MEMORY_GOVERNOR"] = \
                "1" if memp["enabled"] else "0"
        if memp.get("deviceMemBytes") is not None:
            os.environ["TRANSMOGRIFAI_DEVICE_MEM_BYTES"] = \
                str(memp["deviceMemBytes"])
        if memp.get("headroom") is not None:
            os.environ["TRANSMOGRIFAI_MEMORY_HEADROOM"] = \
                str(memp["headroom"])
        if memp.get("oomRecoveries") is not None:
            os.environ["TRANSMOGRIFAI_OOM_RECOVERIES"] = \
                str(memp["oomRecoveries"])
        if memp.get("hostSoftBytes") is not None:
            os.environ["TRANSMOGRIFAI_HOST_MEM_SOFT_BYTES"] = \
                str(memp["hostSoftBytes"])
        if memp.get("hostHardBytes") is not None:
            os.environ["TRANSMOGRIFAI_HOST_MEM_HARD_BYTES"] = \
                str(memp["hostHardBytes"])
        if memp.get("watchdogIntervalS") is not None:
            os.environ["TRANSMOGRIFAI_RSS_WATCHDOG_S"] = \
                str(memp["watchdogIntervalS"])
        # qualityParams: the firewall resolves QualityConfig from the env
        # at each ingestion point (workflow read, reader screen, serving
        # engine), so run-scoped knobs ride the env like the blocks above
        qp = params.quality or {}
        if qp.get("policy") is not None:
            os.environ["TRANSMOGRIFAI_QUALITY_POLICY"] = str(qp["policy"])
        if qp.get("maxQuarantineFraction") is not None:
            os.environ["TRANSMOGRIFAI_MAX_QUARANTINE_FRACTION"] = \
                str(qp["maxQuarantineFraction"])
        if qp.get("enabled") is not None:
            os.environ["TRANSMOGRIFAI_QUALITY"] = \
                "1" if qp["enabled"] else "0"
        # obsParams (ISSUE 20): the training control plane — admin HTTP
        # endpoint + crash flight recorder.  Off by default; the env knob
        # composes with the per-rank port a host-group launcher exported
        obsp = params.obs or {}
        if obsp.get("port") is not None:
            os.environ["TRANSMOGRIFAI_OBS_PORT"] = str(obsp["port"])
        if obsp.get("blackboxSpans") is not None:
            os.environ["TRANSMOGRIFAI_BLACKBOX_SPANS"] = \
                str(obsp["blackboxSpans"])
        if obsp.get("blackboxPath") is not None:
            os.environ["TRANSMOGRIFAI_BLACKBOX_PATH"] = \
                str(obsp["blackboxPath"])
        tele = params.telemetry or {}
        trace_dir = tele.get("traceDir")
        enabled = bool(tele.get("enabled", trace_dir is not None))
        # telemetryParams.traceparent (or the TRANSMOGRIFAI_TRACEPARENT a
        # supervising parent exported) joins this run's spans — including a
        # lifecycle retrain — to the caller's distributed trace
        parent = None
        if enabled:
            from .telemetry import TraceContext
            tp = tele.get("traceparent")
            parent = (TraceContext.parse(str(tp)) if tp
                      else TraceContext.from_env())
        # inside a host-group rank the tracer carries the rank so per-rank
        # exports merge into one labelled multi-host timeline (trace-merge)
        from .parallel import hostgroup as _hostgroup
        hg_rank = _hostgroup.current_rank() \
            if _hostgroup.hostgroup_env_present() else None
        tracer = Tracer(run_name=f"run:{run_type}", parent=parent,
                        rank=hg_rank) if enabled else None
        ctx = use_tracer(tracer) if tracer is not None \
            else contextlib.nullcontext()
        # opt-in heartbeat supervision for the whole run: background
        # re-probes feed the device-runtime breaker + AVAILABLE/DEGRADED/
        # OUTAGE gauges while the run is in flight
        hb = None
        try:
            hb_interval = float(os.environ.get("TRANSMOGRIFAI_HEARTBEAT_S",
                                               "0"))
        except ValueError:
            hb_interval = 0.0
        if hb_interval > 0:
            from .parallel.supervisor import Heartbeat, supervisor_enabled
            if supervisor_enabled():
                hb = Heartbeat(interval_s=hb_interval).start()
        # host-side RSS watchdog (ISSUE 15): runs whenever the governor is
        # on, a watermark is configured, and a cadence is set — sheds
        # pretrace queues/transfer caches at the soft watermark, trips the
        # typed HostMemoryPressure flag at the hard one
        wd = None
        from .parallel import memory as _memory
        wd_interval = _memory.watchdog_interval_s()
        if (wd_interval > 0 and _memory.memory_governor_enabled()
                and (os.environ.get("TRANSMOGRIFAI_HOST_MEM_SOFT_BYTES")
                     or os.environ.get("TRANSMOGRIFAI_HOST_MEM_HARD_BYTES"))):
            wd = _memory.RssWatchdog(interval_s=wd_interval).start()
            _memory.install_watchdog(wd)
        # training control plane (ISSUE 20): when an obs port is configured
        # for a train/lifecycle run, start the admin endpoint (/metrics,
        # /statusz, /traces) and install the flight recorder.  Both are
        # no-ops when TRANSMOGRIFAI_OBS_PORT is unset — no socket, no
        # recorder, no new spans.
        obs_server = None
        recorder = None
        if run_type in (RunType.TRAIN, RunType.LIFECYCLE):
            from . import obsv
            if obsv.obs_enabled():
                recorder = obsv.install_recorder(obsv.FlightRecorder())
                obs_server = obsv.maybe_start_obs_server()
                obsv.BOARD.publish(runType=run_type, phase="starting",
                                   pid=os.getpid())
        hg = None
        guard = None
        # the outer guard only wraps the run types the control plane
        # covers — serve/score keep their own signal handling untouched.
        # Re-entrant with the nested train/lifecycle guards (shared flag).
        guard_ctx = (preemption_guard(run_type)
                     if run_type in (RunType.TRAIN, RunType.LIFECYCLE)
                     else contextlib.nullcontext())
        try:
            with ctx, guard_ctx as guard:
                # inside a launch_hosts rank: join the host group (start the
                # heartbeat, optionally init jax.distributed, pass the init
                # barrier) before dispatch; post this rank's done file after
                hg = _hostgroup.maybe_init_hostgroup()
                result = self._run_dispatch(run_type, params)
                if hg is not None:
                    hg.mark_done({"runType": run_type, "ok": True})
        except BaseException as e:
            # crash flight recorder: DataQualityError / MemoryExhaustedError
            # / HostLostError / anything else unhandled dumps the last ring
            # of telemetry before the error propagates
            if recorder is not None:
                from . import obsv
                obsv.dump_blackbox(reason=type(e).__name__, error=e)
            raise
        finally:
            # a graceful SIGTERM stop never reaches the except arm (the
            # guard converts it into a drained, successful result) — dump
            # the ring here so the preemption postmortem exists too
            if recorder is not None and guard is not None \
                    and guard.stop_requested:
                from . import obsv
                obsv.dump_blackbox(
                    reason="preempted",
                    error=RuntimeError(guard.reason or "graceful stop"))
            if obs_server is not None:
                obs_server.stop()
            if recorder is not None:
                from . import obsv
                obsv.install_recorder(None)
            if hg is not None:
                hg.close()
            if hb is not None:
                hb.stop()
            if wd is not None:
                _memory.install_watchdog(None)
                wd.stop()
        if tracer is not None:
            result.tracer = tracer
            if trace_dir:
                self._export_telemetry(tracer, trace_dir, run_type, result,
                                       rank=hg_rank)
        return result

    def _run_dispatch(self, run_type: str,
                      params: OpParams) -> OpWorkflowRunnerResult:
        timer = PhaseTimer()
        with timer.phase(f"run:{run_type}"):
            if run_type == RunType.TRAIN:
                result = self._train(params, timer)
            elif run_type == RunType.SCORE:
                result = self._score(params, timer)
            elif run_type == RunType.STREAMING_SCORE:
                result = self._streaming_score(params, timer)
            elif run_type == RunType.FEATURES:
                result = self._features(params, timer)
            elif run_type == RunType.EVALUATE:
                result = self._evaluate(params, timer)
            elif run_type == RunType.SERVE:
                result = self._serve(params, timer)
            elif run_type == RunType.LIFECYCLE:
                result = self._lifecycle(params, timer)
            else:
                raise ValueError(f"unknown run type {run_type!r}; "
                                 f"expected one of {RunType.ALL}")
        metrics = timer.app_metrics(tag=params.custom_tag_name)
        result.app_metrics = metrics
        for cb in self._completion_callbacks:
            cb(metrics)
        return result

    @staticmethod
    def _export_telemetry(tracer, trace_dir: str, run_type: str,
                          result: OpWorkflowRunnerResult,
                          rank: "Optional[int]" = None) -> None:
        """Write <trace_dir>/trace-<run_type>.json (Chrome trace events,
        Perfetto-loadable) and telemetry.json (summary).  Inside a
        host-group rank the filenames carry the rank so N ranks sharing one
        trace_dir never clobber each other (``trace-merge`` stitches them).
        Best-effort: a full disk must not fail a finished run."""
        from .telemetry import write_telemetry_summary
        suffix = "" if rank is None else f"-rank{rank}"
        try:
            os.makedirs(trace_dir, exist_ok=True)
            trace_path = os.path.join(
                trace_dir, f"trace-{run_type}{suffix}.json")
            tracer.export_chrome_trace(trace_path)
            write_telemetry_summary(
                os.path.join(trace_dir, f"telemetry{suffix}.json"), tracer)
            if isinstance(result.metrics, dict):
                result.metrics["traceFile"] = trace_path
        except Exception as e:  # noqa: BLE001 — diagnostics only
            from .resilience import record_failure
            record_failure("runner.telemetry", "swallowed", e,
                           point="runner.telemetry", trace_dir=trace_dir)

    # -- run types --------------------------------------------------------
    def _train(self, params: OpParams, timer: PhaseTimer) -> OpWorkflowRunnerResult:
        """≙ :163-196: train, save model + summary."""
        if self.workflow is None:
            raise ValueError(
                "run-type 'train' needs a Workflow — construct the runner "
                "with OpWorkflowRunner(workflow, ...)")
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        if params.stage_params:
            self.workflow.apply_stage_params(params)
        if params.racing:
            self.workflow.apply_racing_params(params.racing)
        # with a checkpoint location, the selector sweep persists completed
        # candidates under <location>/selector-sweep — a rerun of the same
        # command resumes instead of restarting
        resume_from = None
        if params.checkpoint_location:
            resume_from = os.path.join(params.checkpoint_location,
                                       "selector-sweep")
            # default the compiled-program registry next to the sweep state:
            # the checkpoint dir outlives /tmp, so every re-train (and every
            # pool worker / lifecycle retrain pointed at the same location)
            # installs executables instead of compiling.  configure() also
            # parks the persistent XLA compile cache under the registry root
            # (<registry>/compile-cache), so the pre-registry cache
            # defaulting below only fires when the registry is disabled
            from .aot_registry import configure as configure_registry
            from .aot_registry import registry_allowed, registry_root
            if registry_allowed() and registry_root() is None:
                configure_registry(root=os.path.join(
                    params.checkpoint_location, "registry"))
            if not os.environ.get("TRANSMOGRIFAI_COMPILE_CACHE"):
                # registry off: keep the old behavior — park the XLA
                # compile cache beside the sweep state so every re-train
                # of this app pays execution cost only
                from .profiling import set_compile_cache_dir
                set_compile_cache_dir(os.path.join(
                    params.checkpoint_location, "compile-cache"))
        try:
            with timer.phase("train"):
                model = self.workflow.train(resume_from=resume_from)
        except TrainingPreempted as e:
            # graceful preemption is an outcome, not a crash: report the
            # resume point so the orchestrator can relaunch the same command
            return OpWorkflowRunnerResult(
                RunType.TRAIN,
                metrics={"preempted": True, "reason": str(e),
                         "resumeFrom": e.resume_from},
                failure_log=e.failure_log)
        summary = None
        if params.model_location:
            with timer.phase("save"):
                model.save(params.model_location)
        with timer.phase("summary"):
            summary = model.summary()
            if params.model_location:
                with open(os.path.join(params.model_location,
                                       "model-summary.json"), "w") as fh:
                    json.dump(summary, fh, indent=2, default=str)
        return OpWorkflowRunnerResult(
            RunType.TRAIN, model_summary=summary,
            failure_log=getattr(model, "failure_log", None))

    def _load_model(self, params: OpParams) -> WorkflowModel:
        if not params.model_location:
            raise ValueError("model_location is required")
        model = WorkflowModel.load(params.model_location)
        if self.score_reader is not None:
            model.set_reader(self.score_reader)
        elif self.workflow is not None and self.workflow.reader is not None:
            # no dedicated scoring reader: score the app's data source (the
            # reference's OpApp subclasses usually pass an explicit
            # scoringReader; falling back keeps `--run-type score` working
            # out of the box for generated starter apps)
            model.set_reader(self.workflow.reader)
        return model

    def _score(self, params: OpParams, timer: PhaseTimer) -> OpWorkflowRunnerResult:
        """≙ :204-223: load model, score, optionally evaluate, write scores."""
        model = self._load_model(params)
        with timer.phase("score"):
            scored = model.score()
        metrics = None
        if self.evaluator is not None:
            with timer.phase("evaluate"):
                metrics = model.evaluate(self.evaluator)
        loc = params.write_location
        if loc:
            with timer.phase("write"):
                os.makedirs(loc, exist_ok=True)
                _write_scores(scored, os.path.join(loc, "scores.jsonl"))
        if metrics is not None and params.metrics_location:
            os.makedirs(params.metrics_location, exist_ok=True)
            with open(os.path.join(params.metrics_location, "metrics.json"),
                      "w") as fh:
                json.dump(metrics, fh, indent=2, default=str)
        return OpWorkflowRunnerResult(RunType.SCORE, metrics=metrics,
                                      scores_location=loc)

    def _streaming_score(self, params: OpParams, timer: PhaseTimer) -> OpWorkflowRunnerResult:
        """≙ :225-263: micro-batch scoring loop over a streaming reader
        (host loop feeding the compiled score fn, SURVEY §2.6 P6).

        Resilient: each batch retries per ``self.retry_policy`` (exponential
        backoff; optional per-attempt watchdog deadline so a native hang
        cannot stall the stream), and a batch that exhausts its retries is
        routed to the result's dead-letter list — the stream continues.
        Every retry and dead-letter lands in the result's ``failure_log``."""
        model = self._load_model(params)
        if self.score_reader is None or not hasattr(self.score_reader, "stream"):
            raise ValueError("streaming score requires a StreamingReader")
        if hasattr(self.score_reader, "set_raw_features"):
            self.score_reader.set_raw_features(
                [f for f in model.raw_features if not f.is_response])
        score_fn = model.score_fn()
        policy = self.retry_policy or RetryPolicy(
            max_attempts=3, base_delay_s=0.02, max_delay_s=0.5)
        flog = self.failure_log if self.failure_log is not None else FailureLog()
        dead_letters: List[Dict[str, Any]] = []
        evicted_count = 0

        def dead_letter(entry: Dict[str, Any]) -> None:
            # bounded DLQ: oldest-first eviction past dead_letter_max, so a
            # persistently failing stream cannot grow memory without limit
            nonlocal evicted_count
            dead_letters.append(entry)
            if len(dead_letters) <= self.dead_letter_max:
                return
            victim = dead_letters.pop(0)
            if evicted_count == 0:
                flog.record("streaming", "degraded",
                            f"dead-letter queue reached its bound "
                            f"({self.dead_letter_max}); evicting oldest "
                            "entries — reprocess from the failure log",
                            point="streaming.batch",
                            first_evicted_index=victim["index"])
            evicted_count += 1
            from .telemetry import REGISTRY
            REGISTRY.counter("streaming.dead_letters_evicted_total").inc()
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
        # durable stream position: scores_<j>.jsonl is written BEFORE the
        # offsets file advances to j+1, so a crash between the two re-scores
        # batch j into the same file (idempotent) instead of losing it
        offsets_path = None
        next_batch = 0
        if params.checkpoint_location:
            os.makedirs(params.checkpoint_location, exist_ok=True)
            offsets_path = os.path.join(params.checkpoint_location,
                                        "stream-offsets.json")
            if os.path.exists(offsets_path):
                try:
                    with open(offsets_path) as fh:
                        next_batch = int(json.load(fh).get("nextBatch", 0))
                except (OSError, ValueError) as e:
                    flog.record("streaming", "degraded", e,
                                point="checkpoint.load",
                                fallback="restart from batch 0")
            if next_batch:
                flog.record("streaming", "resumed",
                            f"offsets file: {next_batch} batch(es) already "
                            "scored", point="checkpoint.load",
                            next_batch=next_batch)
        n_batches = 0
        was_preempted = False
        # double-buffered pipeline (SURVEY §2.6 P6): scoring dispatches
        # asynchronously on the device, so batch i computes while the host
        # serializes batch i-1's results — the d2h pull in _write_scores is
        # the host stage of the pipeline
        pending = None  # (index, scored)

        def flush():
            nonlocal pending
            if pending is not None:
                j, prev = pending
                if loc:
                    with timer.phase(f"write_{j}"):
                        _write_scores(prev,
                                      os.path.join(loc, f"scores_{j}.jsonl"))
                if offsets_path:
                    write_json_atomic(offsets_path, {"nextBatch": j + 1})
            pending = None

        # ambient quality config: StreamingReader micro-batches assemble
        # through Reader.generate_batch, which screens records against the
        # run's policy — a poison record quarantines per-row (typed
        # violation in the failure log) instead of dead-lettering its
        # whole micro-batch after retries
        from .quality import QualityConfig, use_quality
        qcfg = QualityConfig.resolve(params.quality)
        quality_scope = (use_quality(qcfg) if qcfg.enabled
                         else contextlib.nullcontext())
        try:
            with use_failure_log(flog), preemption_guard("streaming"), \
                    quality_scope:
                for i, batch in enumerate(self.score_reader.stream()):
                    if i < next_batch:
                        continue   # already scored by a previous run
                    if shutdown_requested(key=f"batch-{i}"):
                        # graceful stop at the batch boundary: the finally
                        # below flushes the last scored batch + its offset
                        was_preempted = True
                        break

                    def attempt(b=batch, j=i):
                        maybe_inject("streaming.batch", key=j)
                        return score_fn(b)

                    try:
                        with timer.phase(f"batch_{i}"):
                            scored = policy.call(
                                attempt, stage="streaming",
                                point="streaming.batch", key=i, log=flog,
                                description=f"streaming batch {i}")
                    except Exception as e:  # noqa: BLE001 — dead-letter
                        flog.record("streaming", "dead_letter", e,
                                    point="streaming.batch", batch_index=i,
                                    attempt=policy.max_attempts)
                        dead_letter(
                            {"index": i,
                             "error": f"{type(e).__name__}: {e}",
                             "batch": batch})
                        # persist the predecessor before moving on so a
                        # later crash cannot lose it
                        flush()
                        continue
                    flush()
                    pending = (i, scored)
                    n_batches += 1
        finally:
            # a mid-stream failure must not lose the last scored batch
            flush()
        return OpWorkflowRunnerResult(
            RunType.STREAMING_SCORE, scores_location=loc,
            metrics={"batches": n_batches,
                     "skippedBatches": next_batch,
                     "preempted": was_preempted,
                     "deadLetterBatches": [d["index"] for d in dead_letters],
                     "deadLettersEvicted": evicted_count,
                     "failures": flog.summary()},
            failure_log=flog, dead_letters=dead_letters)

    def _features(self, params: OpParams, timer: PhaseTimer) -> OpWorkflowRunnerResult:
        """≙ :265: computeDataUpTo a feature and write it."""
        model = self._load_model(params)
        feature = self.features_to_compute
        with timer.phase("features"):
            batch = model.compute_data_up_to(feature)
        loc = params.write_location
        if loc:
            os.makedirs(loc, exist_ok=True)
            _write_scores(batch, os.path.join(loc, "features.jsonl"))
        return OpWorkflowRunnerResult(RunType.FEATURES, scores_location=loc)

    def _evaluate(self, params: OpParams, timer: PhaseTimer) -> OpWorkflowRunnerResult:
        """≙ :272-285."""
        model = self._load_model(params)
        with timer.phase("evaluate"):
            metrics = model.evaluate(self.evaluator, self.evaluation_feature)
        if params.metrics_location:
            os.makedirs(params.metrics_location, exist_ok=True)
            with open(os.path.join(params.metrics_location, "metrics.json"),
                      "w") as fh:
                json.dump(metrics, fh, indent=2, default=str)
        return OpWorkflowRunnerResult(RunType.EVALUATE, metrics=metrics)

    def _serve(self, params: OpParams, timer: PhaseTimer
               ) -> OpWorkflowRunnerResult:
        """Online scoring: block inside the HTTP serve loop until
        SIGTERM/SIGINT, then drain and return.  Serving knobs ride in
        ``params.serving`` (see ``OpParams``)."""
        from .serving.overload import OverloadConfig
        from .serving.server import serve_main
        sv = params.serving or {}
        model_root = sv.get("modelRoot")
        if bool(params.model_location) == bool(model_root):
            raise ValueError("run-type 'serve' needs exactly one of "
                             "--model-location (single bundle) or "
                             "servingParams.modelRoot (multi-tenant)")
        workers = int(sv.get("workers", 1))
        with timer.phase("serve"):
            if workers > 1:
                import dataclasses

                from .serving.pool import pool_serve_main
                pool_serve_main(
                    params.model_location, workers=workers,
                    host=sv.get("host", "127.0.0.1"),
                    port=int(sv.get("port", 8180)),
                    admin_port=int(sv.get("adminPort", 0)),
                    max_batch=int(sv.get("maxBatch", 64)),
                    queue_bound=int(sv.get("queueBound", 256)),
                    request_deadline_s=sv.get("requestDeadlineS", 30.0),
                    reload_poll_s=float(sv.get("reloadPollS", 10.0)),
                    overload=dataclasses.asdict(
                        OverloadConfig.from_params(sv)),
                    wire_format=sv.get("wireFormat", "auto"),
                    model_root=model_root,
                    tenant_max_active=sv.get("tenantMaxActive"),
                    tenant_memory_budget_bytes=sv.get(
                        "tenantMemoryBudgetBytes"))
                # pool workers resolve the firewall policy from the env set
                # by run() (qualityParams.policy → TRANSMOGRIFAI_QUALITY_
                # POLICY), so no kwarg threading is needed here
            else:
                serve_main(params.model_location,
                           host=sv.get("host", "127.0.0.1"),
                           port=int(sv.get("port", 8180)),
                           max_batch=int(sv.get("maxBatch", 64)),
                           linger_ms=float(sv.get("lingerMs", 2.0)),
                           queue_bound=int(sv.get("queueBound", 256)),
                           request_deadline_s=sv.get("requestDeadlineS",
                                                     30.0),
                           reload_poll_s=float(sv.get("reloadPollS", 10.0)),
                           overload=OverloadConfig.from_params(sv),
                           wire_format=sv.get("wireFormat", "auto"),
                           model_root=model_root,
                           tenant_max_active=sv.get("tenantMaxActive"),
                           tenant_memory_budget_bytes=sv.get(
                               "tenantMemoryBudgetBytes"),
                           quality_policy=(params.quality or {}).get(
                               "policy"))
        return OpWorkflowRunnerResult(RunType.SERVE)

    def _lifecycle(self, params: OpParams, timer: PhaseTimer
                   ) -> OpWorkflowRunnerResult:
        """Drift-gated retrain loop over a versioned checkpoint root.
        Knobs ride in ``params.lifecycle`` (see ``OpParams``); the live
        feed is the runner's ``score_reader``, holdout defaults to the
        train reader."""
        if self.workflow is None:
            raise ValueError("run-type 'lifecycle' needs a workflow")
        if not params.model_location:
            raise ValueError("run-type 'lifecycle' needs --model-location")
        from .lifecycle.service import lifecycle_main
        with timer.phase("lifecycle"):
            result = lifecycle_main(
                self.workflow, params.model_location,
                evaluator=self.evaluator,
                live_reader=self.score_reader,
                holdout_reader=self.train_reader or self.workflow.reader,
                config=params.lifecycle or {})
        return OpWorkflowRunnerResult(RunType.LIFECYCLE, metrics=result)


def _write_scores(batch, path: str):
    n = len(batch)
    with open(path, "w") as fh:
        for i in range(n):
            row = {}
            for name, col in batch.items():
                if isinstance(col.values, dict):
                    row[name] = {k: np.asarray(v)[i].tolist()
                                 for k, v in col.values.items()}
                else:
                    v = np.asarray(col.values)[i]
                    row[name] = v.tolist() if hasattr(v, "tolist") else v
            fh.write(json.dumps(row, default=str) + "\n")


class OpApp:
    """≙ OpApp.scala: CLI arg parsing → runner dispatch.

    Subclasses implement ``build_workflow()`` and optionally the readers.
    """

    def build_workflow(self) -> Workflow:
        raise NotImplementedError

    def make_runner(self) -> OpWorkflowRunner:
        return OpWorkflowRunner(self.build_workflow())

    def parse_args(self, argv: Optional[List[str]] = None):
        """≙ OpApp.parseArgs (scopt, OpApp.scala:130-176)."""
        p = argparse.ArgumentParser(description=type(self).__name__)
        p.add_argument("--run-type", required=True, choices=RunType.ALL)
        p.add_argument("--model-location")
        p.add_argument("--read-location")
        p.add_argument("--write-location")
        p.add_argument("--metrics-location")
        p.add_argument("--checkpoint-location",
                       help="directory for sweep checkpoints + streaming "
                            "offsets; rerunning the same command resumes")
        p.add_argument("--param-location",
                       help="json file of OpParams")
        p.add_argument("--no-racing", action="store_true",
                       help="run the full fold x grid sweep instead of "
                            "successive-halving racing")
        p.add_argument("--racing-eta", type=float,
                       help="racing reduction factor (keep top 1/eta per "
                            "family after the fold-0 screen)")
        p.add_argument("--racing-min-survivors", type=int,
                       help="never race a family below this many surviving "
                            "grid points")
        p.add_argument("--trace-dir",
                       help="trace this run and write Chrome-trace JSON + "
                            "telemetry.json into this directory")
        p.add_argument("--traceparent",
                       help="W3C traceparent header value joining this run "
                            "to the caller's distributed trace (defaults "
                            "to $TRANSMOGRIFAI_TRACEPARENT)")
        p.add_argument("--no-aot", action="store_true",
                       help="disable AOT-serialized executables: train "
                            "saves JIT-only bundles, load/serve recompiles "
                            "instead of installing shipped executables")
        p.add_argument("--registry-root",
                       help="compiled-program registry directory (default: "
                            "<checkpoint-location>/registry, or "
                            "$TRANSMOGRIFAI_AOT_REGISTRY); train publishes "
                            "executables into it, every fresh train / "
                            "worker / tenant installs from it")
        p.add_argument("--no-registry", action="store_true",
                       help="disable the compiled-program registry (no "
                            "publish, no install; pre-registry compile "
                            "behavior)")
        p.add_argument("--mesh", action="store_true",
                       help="force the mesh-sharded CV sweep on regardless "
                            "of the row-count heuristic")
        p.add_argument("--no-mesh", action="store_true",
                       help="disable mesh sharding (single-device sweep)")
        p.add_argument("--mesh-model-width", type=int,
                       help="width of the model axis carved out of the "
                            "device mesh (grid candidates shard over it)")
        p.add_argument("--mesh-chunk-bytes", type=int,
                       help="host->device streaming chunk budget in bytes "
                            "(peak host staging stays <= 2x this)")
        p.add_argument("--no-supervisor", action="store_true",
                       help="disable device-runtime supervision: no "
                            "degrade-to-surviving-mesh sweep recovery, no "
                            "heartbeat; device errors propagate unchanged")
        p.add_argument("--no-memory-governor", action="store_true",
                       help="disable memory governance: no preflight "
                            "device-memory planning, no OOM shrink-and-"
                            "retry ladder, no RSS watchdog; allocator "
                            "errors propagate unchanged")
        p.add_argument("--device-mem-bytes", type=int,
                       help="per-device memory budget the preflight "
                            "planner plans against (overrides "
                            "device.memory_stats() discovery)")
        p.add_argument("--hosts", type=int, default=1,
                       help="launch this command across N supervised local "
                            "processes (ranked host group with heartbeats, "
                            "jax.distributed init, lost-host relaunch); "
                            "1 = run in-process")
        p.add_argument("--hosts-run-dir",
                       help="host-group run directory (heartbeats, logs, "
                            "outage records); default: a temp dir")
        p.add_argument("--quality-policy",
                       choices=["strict", "coerce", "quarantine", "off"],
                       help="data-quality firewall policy: strict rejects "
                            "any schema violation, coerce (default) "
                            "repairs what it can and rejects only "
                            "non-coercible/non-finite values, quarantine "
                            "tolerates only unknown fields, off disables "
                            "the firewall")
        p.add_argument("--max-quarantine-fraction", type=float,
                       help="abort training with DataQualityError when "
                            "more than this fraction of rows is "
                            "quarantined (default 0.1)")
        p.add_argument("--no-quality", action="store_true",
                       help="disable the data-quality firewall entirely "
                            "(schema screening, quarantine accounting and "
                            "non-finite guards)")
        p.add_argument("--obs-port", type=int,
                       help="training control plane: serve GET /metrics, "
                            "/statusz and /traces on this port while the "
                            "run is in flight, and arm the crash flight "
                            "recorder (blackbox.json).  Inside a host "
                            "group the launcher keeps this port for the "
                            "merged rank panel and rank r serves on "
                            "port+1+r.  Unset/0 = off (no socket, no "
                            "recorder)")
        return p.parse_args(argv)

    def main(self, argv: Optional[List[str]] = None) -> OpWorkflowRunnerResult:
        args = self.parse_args(argv)
        params = (OpParams.load(args.param_location)
                  if args.param_location else OpParams())
        if args.model_location:
            params.model_location = args.model_location
        if args.write_location:
            params.write_location = args.write_location
        if args.metrics_location:
            params.metrics_location = args.metrics_location
        if args.checkpoint_location:
            params.checkpoint_location = args.checkpoint_location
        if args.read_location:
            from .params import ReaderParams
            params.reader_params.setdefault("default", ReaderParams()).path = \
                args.read_location
        if args.no_racing:
            params.racing["enabled"] = False
        if args.racing_eta is not None:
            params.racing["eta"] = args.racing_eta
        if args.racing_min_survivors is not None:
            params.racing["minSurvivors"] = args.racing_min_survivors
        if args.trace_dir:
            params.telemetry["traceDir"] = args.trace_dir
        if args.traceparent:
            params.telemetry["traceparent"] = args.traceparent
        if args.no_aot:
            params.aot["enabled"] = False
        if args.registry_root:
            params.registry["root"] = args.registry_root
        if args.no_registry:
            params.registry["enabled"] = False
        if args.mesh or args.no_mesh:
            params.mesh["enabled"] = bool(args.mesh and not args.no_mesh)
        if args.mesh_model_width is not None:
            params.mesh["modelWidth"] = args.mesh_model_width
        if args.mesh_chunk_bytes is not None:
            params.mesh["chunkBytes"] = args.mesh_chunk_bytes
        if args.no_supervisor:
            params.supervisor["enabled"] = False
        if args.no_memory_governor:
            params.memory["enabled"] = False
        if args.device_mem_bytes is not None:
            params.memory["deviceMemBytes"] = args.device_mem_bytes
        if args.quality_policy is not None:
            params.quality["policy"] = args.quality_policy
        if args.max_quarantine_fraction is not None:
            params.quality["maxQuarantineFraction"] = \
                args.max_quarantine_fraction
        if args.no_quality:
            params.quality["enabled"] = False
        if args.obs_port is not None:
            params.obs["port"] = args.obs_port
        from .parallel import hostgroup
        hosts = max(1, int(args.hosts or params.hostgroup.get("hosts", 1)))
        if hosts > 1 and not hostgroup.hostgroup_env_present():
            # launcher role: fan this same command out as N ranked worker
            # processes and supervise them (each rank re-enters main() with
            # the host-group env set and takes the in-process branch)
            import sys
            child = list(sys.argv) if argv is None else [sys.argv[0]] + \
                list(argv)
            hg_params = params.hostgroup or {}
            # training control plane: the launcher owns the base obs port
            # (merged rank panel); launch_hosts exports base+1+rank to each
            # child, so every rank's own endpoint is reachable too
            obs_port = (params.obs or {}).get("port")
            if obs_port:
                os.environ["TRANSMOGRIFAI_OBS_PORT"] = str(obs_port)
            res = hostgroup.launch_hosts(
                [sys.executable] + child, hosts,
                run_dir=args.hosts_run_dir or hg_params.get("runDir"),
                boot_timeout=float(hg_params.get("bootTimeoutS", 240.0)),
                grace_s=float(hg_params.get("graceS", 15.0)),
                max_relaunches=int(hg_params.get("maxRelaunches", 1)),
                liveness_timeout=hg_params.get("livenessTimeoutS"),
                beat_interval=hg_params.get("beatIntervalS"),
                distributed=bool(hg_params.get("distributed", True)))
            out = OpWorkflowRunnerResult(
                run_type=args.run_type, metrics={"hostgroup": res.to_json()})
            if not res.ok:
                raise SystemExit(1)
            return out
        runner = self.make_runner()
        try:
            return runner.run(args.run_type, params)
        except hostgroup.HostLostError:
            if hostgroup.hostgroup_env_present():
                # survivor abort: exit with the benign host-lost code so the
                # launcher relaunches the group instead of counting a failure
                raise SystemExit(hostgroup.EXIT_HOST_LOST)
            raise
