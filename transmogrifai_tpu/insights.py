"""ModelInsights — the model explainability report (reference:
core/src/main/scala/com/salesforce/op/ModelInsights.scala:74-392,
extractFromStages:440) and the ASCII ``summaryPretty`` rendering
(utils/table/Table.scala).

Walks the fitted DAG, collecting per-derived-feature contributions (raw and
descaled), label correlations / variances / Cramér's V from the SanityChecker
metadata, RawFeatureFilter feature distributions, the selected model summary +
validation results, the label profile, and a training-stage echo.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .utils.table import render_table


@dataclass
class FeatureInsights:
    feature_name: str
    feature_type: str = ""
    derived_columns: List[Dict[str, Any]] = field(default_factory=list)
    # RawFeatureFilter FeatureDistributions for this raw feature (per map
    # key when the feature is a map) — ≙ ModelInsights.scala distributions
    distributions: List[Dict[str, Any]] = field(default_factory=list)

    def max_contribution(self) -> float:
        vals = [abs(c.get("contribution") or 0.0) for c in self.derived_columns]
        return max(vals) if vals else 0.0

    def max_abs_correlation(self) -> float:
        vals = [abs(c["corr"]) for c in self.derived_columns
                if c.get("corr") is not None and np.isfinite(c["corr"])]
        return max(vals) if vals else float("nan")

    def cramers_v(self) -> float:
        vals = [c["cramersV"] for c in self.derived_columns
                if c.get("cramersV") is not None and np.isfinite(c["cramersV"])]
        return max(vals) if vals else float("nan")


@dataclass
class ModelInsights:
    """≙ ModelInsights.scala:74."""

    label: Dict[str, Any] = field(default_factory=dict)
    features: List[FeatureInsights] = field(default_factory=list)
    selected_model: Dict[str, Any] = field(default_factory=dict)
    problem_type: str = ""
    stage_info: Dict[str, Any] = field(default_factory=dict)
    training_params: Dict[str, Any] = field(default_factory=dict)

    # -- extraction (≙ extractFromStages:440) -----------------------------
    @staticmethod
    def extract(workflow_model) -> "ModelInsights":
        from .preparators.sanity_checker import SanityCheckerModel
        from .selector import SelectedModel

        ins = ModelInsights()
        sel: Optional[SelectedModel] = workflow_model.selected_model
        checker = next((s for s in workflow_model.stages
                        if isinstance(s, SanityCheckerModel)), None)

        # label profile
        resp = next((f for f in workflow_model.raw_features if f.is_response), None)
        if resp is not None:
            ins.label = {"labelName": resp.name, "rawFeatureName": [resp.name],
                         "rawFeatureType": [resp.kind.__name__]}
            if workflow_model.train_batch is not None and resp.name in workflow_model.train_batch:
                raw = workflow_model.train_batch[resp.name].values
                try:
                    y = np.asarray(raw, dtype=np.float64)
                except (TypeError, ValueError):
                    # raw string labels (indexed downstream, e.g. by a
                    # StringIndexer): profile the categorical values directly
                    y = np.asarray([("" if v is None else str(v)) for v in raw])
                vals, counts = np.unique(y, return_counts=True)
                ins.label.update({
                    "sampleSize": int(len(y)),
                    "distinctCount": int(len(vals)),
                })
                if y.dtype.kind == "f" and len(y):
                    ins.label["mean"] = float(y.mean())
                if len(vals) <= 30:
                    ins.label["distribution"] = {
                        str(v): int(c) for v, c in zip(vals, counts)}

        # per-derived-column insights from SanityChecker summary + model coefs
        contributions = _model_contributions(sel)
        by_parent: Dict[str, FeatureInsights] = {}
        if checker is not None and "summary" in checker.metadata:
            s = checker.metadata["summary"]
            names = s.get("names", [])
            corrs = s.get("correlationsWithLabel", [])
            variances = s.get("variances", [])
            cramers_by_group = (s.get("categoricalStats", {}) or {}).get(
                "cramersV", {}) or {}
            dropped = set(s.get("dropped", []))
            reasons = s.get("dropReasons", {})
            # per-column lineage comes from the checker's recorded vector
            # meta (VectorsCombiner/transmogrify always attach it).  When a
            # hand-built vector carried none, DON'T guess parents from name
            # splitting (silently wrong for names containing '_') — attribute
            # each column to itself and mark the lineage absent.
            meta = None
            if "input_vector_meta" in checker.metadata:
                from .vector_meta import VectorMeta
                meta = VectorMeta.from_json(
                    checker.metadata["input_vector_meta"])
                if len(meta.columns) != len(names):
                    raise ValueError(
                        f"vector meta covers {len(meta.columns)} columns but "
                        f"the SanityChecker summary names {len(names)}")
            else:
                ins.stage_info["lineage"] = "absent"
            kept_pos = 0
            for i, name in enumerate(names):
                col_meta = meta.columns[i] if meta is not None else None
                parent = (col_meta.parent_feature_name if col_meta is not None
                          else name)
                fi = by_parent.setdefault(parent, FeatureInsights(
                    parent,
                    col_meta.parent_feature_type if col_meta else ""))
                is_dropped = name in dropped
                contribution = None
                descaled = None
                if not is_dropped and kept_pos < len(contributions):
                    contribution = contributions[kept_pos]
                    # descaled contribution: |effect| in label units —
                    # |coef_j| · std_j for linear models, comparable across
                    # differently-scaled features (≙ the reference's
                    # descaled feature contributions, ModelInsights.scala)
                    var_i = variances[i] if i < len(variances) else None
                    if (contribution is not None and var_i is not None
                            and np.isfinite(var_i)):
                        descaled = float(contribution * np.sqrt(max(var_i, 0.0)))
                if not is_dropped:
                    kept_pos += 1
                grouping = col_meta.grouping if col_meta else None
                indicator = col_meta.indicator_value if col_meta else None
                gname = (parent if grouping is None
                         else f"{parent}({grouping})")
                cram = (cramers_by_group.get(gname)
                        if indicator is not None else None)
                fi.derived_columns.append({
                    "name": name,
                    "corr": corrs[i] if i < len(corrs) else None,
                    "variance": variances[i] if i < len(variances) else None,
                    "cramersV": cram,
                    "dropped": is_dropped,
                    "dropReasons": reasons.get(name, []),
                    "contribution": contribution,
                    "descaledContribution": descaled,
                    "indicatorValue": indicator,
                    "grouping": grouping,
                })

        # RawFeatureFilter feature distributions, joined per raw feature
        # (≙ ModelInsights surfacing RawFeatureFilterResults distributions)
        rff = getattr(workflow_model, "rff_results", None)
        if rff is not None:
            for d in rff.train_distributions:
                fi = by_parent.get(d.name)
                if fi is None:
                    fi = by_parent.setdefault(
                        d.name, FeatureInsights(d.name))
                fi.distributions.append(d.to_json())

        ins.features = sorted(by_parent.values(),
                              key=lambda f: -f.max_contribution())

        if sel is not None:
            if sel.summary is not None:
                ins.selected_model = sel.summary.to_json()
                ins.problem_type = sel.summary.problem_type
            elif "summary" in sel.metadata:  # reloaded model: summary persisted
                ins.selected_model = sel.metadata["summary"]
                ins.problem_type = ins.selected_model.get("problemType", "")

        # training echo: workflow parameters + per-stage ctor params
        # (≙ trainingParams / stageInfo in the reference's insights JSON)
        ins.training_params = dict(workflow_model.parameters or {})
        for stage in workflow_model.stages:
            ins.stage_info[stage.uid] = {
                "className": type(stage).__name__,
                "params": {k: v for k, v in stage.params.items()
                           if isinstance(v, (str, int, float, bool))
                           or v is None},
            }
        return ins

    def to_json(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "features": [{
                "featureName": f.feature_name,
                "featureType": f.feature_type,
                "derivedFeatures": f.derived_columns,
                "distributions": f.distributions,
            } for f in self.features],
            "selectedModelInfo": self.selected_model,
            "problemType": self.problem_type,
            "stageInfo": self.stage_info,
            "trainingParams": self.training_params,
        }

    def pretty(self) -> str:
        """≙ summaryPretty: ASCII tables of model evaluation + top features."""
        out = []
        sm = self.selected_model
        if sm:
            out.append(f"Selected model: {sm.get('bestModelName')} "
                       f"({sm.get('validationType')}, metric "
                       f"{sm.get('evaluationMetric')})")
            rows = []
            for r in sm.get("validationResults", [])[:20]:
                mv = r.get("metricValues", {})
                metric = next(iter(mv.values())) if mv else float("nan")
                shown = (f"{metric:.4f}"
                         if isinstance(metric, float) else metric)
                if r.get("racedOut"):
                    # fold-0 screen metric only — the point was pruned by
                    # sweep racing and never competed on full CV means
                    shown = f"{shown} (raced out @fold0)"
                rows.append([r.get("modelName"),
                             json.dumps(r.get("modelParameters", {}))[:48],
                             shown])
            out.append(render_table(
                ["Model", "Parameters", sm.get("evaluationMetric", "metric")],
                rows, title="Model Evaluation Metrics"))
        if self.features:
            rows = []
            for f in self.features[:25]:
                fill = ""
                if f.distributions:
                    fr = f.distributions[0].get("fillRate")
                    if fr is not None:
                        fill = f"{fr:.3f}"
                rows.append([
                    f.feature_name,
                    f"{f.max_contribution():.4f}",
                    ("%.4f" % f.max_abs_correlation()
                     if np.isfinite(f.max_abs_correlation()) else "-"),
                    ("%.4f" % f.cramers_v()
                     if np.isfinite(f.cramers_v()) else "-"),
                    fill or "-",
                    str(sum(1 for c in f.derived_columns if c["dropped"])),
                ])
            out.append(render_table(
                ["Top Raw Feature", "Max Contribution", "Max |Corr|",
                 "Cramér's V", "Fill Rate", "Dropped"],
                rows, title="Top Model Contributions"))
        return "\n".join(out)


def _model_contributions(sel) -> List[float]:
    """Per-kept-column contribution of the winning model: |coef| for linear
    models, accumulated impurity gain for trees (count-weighted, ≙ Spark's
    featureImportances feeding ModelInsights.scala:74-392), with split-usage
    frequency as the fallback for external models without gains."""
    if sel is None or sel.best_model is None:
        return []
    fitted = sel.best_model.fitted
    if "coef" in fitted:
        coef = np.asarray(fitted["coef"])
        if coef.ndim == 2:
            return np.abs(coef).max(axis=1).tolist()
        return np.abs(coef).tolist()
    if "feature_gain" in fitted:
        gain = np.asarray(fitted["feature_gain"], dtype=np.float64)
        tot = gain.sum()
        return (gain / tot if tot > 0 else gain).tolist()
    if "feature" in fitted:  # fallback: usage frequency per feature
        feats = np.asarray(fitted["feature"]).ravel()
        feats = feats[feats >= 0]
        if feats.size == 0:
            return []
        d = int(feats.max()) + 1
        counts = np.bincount(feats, minlength=d).astype(np.float64)
        return (counts / counts.sum()).tolist()
    if "log_prob" in fitted:  # naive bayes: spread of class log-probs
        lp = np.asarray(fitted["log_prob"])
        return np.abs(lp - lp.mean(axis=0)).max(axis=0).tolist()
    return []
