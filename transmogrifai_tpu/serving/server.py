"""Stdlib-only HTTP front end for the ScoringEngine.

Endpoints:

* ``POST /v1/score`` — body ``{...record...}`` or ``[{...}, ...]``;
  response ``{"modelVersion": v, "result": {...}}`` or
  ``{"modelVersion": v, "results": [...]}`` (a list response carries the
  version that served the FIRST record; per-item versions are in
  ``results[i]["_modelVersion"]`` only if they differ — a hot swap can land
  mid-list).  429 + ``Retry-After`` under shed load, 504 on deadline,
  503 while draining.

  With ``Content-Type: application/x-transmogrifai-columnar`` the body is
  the packed columnar format (``serving/wire.py``): per-feature contiguous
  arrays the engine scores without per-record Python.  The response is a
  columnar body of result arrays with the model version in
  ``X-Model-Version``.  A malformed columnar body is a structured 400,
  never a worker crash; JSON stays the compatibility path.
* ``GET /healthz`` — process *liveness*: always 200 while the process can
  answer HTTP, with the health state (``SERVING``/``DEGRADED``/
  ``BROWNOUT``/``DRAINING``) and transition reason in the body.  A
  draining server is still alive — do not restart it.
* ``GET /readyz`` — traffic-worthiness: 200 only when the model is
  loaded, the compiled-path breaker is not open, and the server is not
  draining; 503 + ``Retry-After`` otherwise.  Point load balancers here.
* ``GET /metrics`` — Prometheus text exposition: request/batch counters,
  queue depth, overload/breaker/health families, latency summaries with
  p50/p95/p99.

``serve_main`` wires the whole thing behind ``preemption_guard``: SIGTERM
stops the accept loop, drains in-flight batches, then exits.
"""

from __future__ import annotations

import json
import math
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..checkpoint import preemption_guard, shutdown_requested
from ..quality import VIOLATION_KINDS, RecordQualityError
from ..resilience import CircuitBreaker, WatchdogTimeout
from ..telemetry import TraceContext, span
from . import wire
from .engine import (DeadlineExceeded, EngineClosed, OverloadedError,
                     ScoringEngine)
from .overload import HEALTH_CODES, OverloadConfig

_METRIC_PREFIX = "transmogrifai_serving"

_BREAKER_CODES = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                  CircuitBreaker.OPEN: 2}


def _retry_after(seconds: float) -> str:
    """HTTP Retry-After is whole seconds; never advertise less than 1."""
    try:
        return str(max(1, int(math.ceil(float(seconds)))))
    except (TypeError, ValueError):
        return "1"


def render_metrics(engine: ScoringEngine) -> str:
    """The engine's stats in Prometheus text exposition format."""
    s = engine.stats()
    lines: List[str] = []

    def _exemplar_suffix(ex) -> str:
        """OpenMetrics exemplar: `` # {trace_id="..."} value`` appended to
        a sample line — Prometheus/Grafana link the sample to the trace."""
        if not ex:
            return ""
        return f' # {{trace_id="{ex["traceId"]}"}} {ex["value"]:.6g}'

    def counter(name: str, value, help_: str, exemplar=None) -> None:
        full = f"{_METRIC_PREFIX}_{name}"
        lines.append(f"# HELP {full} {help_}")
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {value}{_exemplar_suffix(exemplar)}")

    def gauge(name: str, value, help_: str) -> None:
        full = f"{_METRIC_PREFIX}_{name}"
        lines.append(f"# HELP {full} {help_}")
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {value}")

    c = s["counters"]
    counter("requests_total", c.get("requests_total", 0),
            "Records accepted into the scoring queue")
    counter("responses_total", c.get("responses_total", 0),
            "Records scored and returned")
    counter("errors_total", c.get("errors_total", 0),
            "Records that failed to score")
    counter("shed_total", c.get("shed_total", 0),
            "Requests shed by admission control (HTTP 429)",
            exemplar=engine.metrics.counter("shed_total").exemplar())
    counter("batches_total", c.get("batches_total", 0),
            "Coalesced micro-batches dispatched")
    counter("batch_rows_total", c.get("batch_rows_total", 0),
            "Records across all dispatched micro-batches")
    counter("fallback_batches_total", c.get("fallback_batches_total", 0),
            "Micro-batches served by the local row path")
    counter("reloads_total", c.get("reloads_total", 0),
            "Hot model reloads performed")
    counter("online_traces_total", c.get("online_traces_total", 0),
            "XLA traces triggered by traffic after warmup (should be 0)")
    counter("dead_letter_total", c.get("dead_letter_total", 0),
            "Records unservable by both the compiled and local paths")
    counter("columnar_observer_skips_total",
            c.get("columnar_observer_skips_total", 0),
            "Rows that bypassed batch observers on the columnar path "
            "(drift monitoring of columnar traffic is deferred)")
    # data-quality firewall families (quality.py): violation volume by
    # kind, quarantine volume and non-finite interceptions at both seams —
    # the violation counter carries a trace-id exemplar so one bad record
    # links straight to its request trace
    counter("quality_violations_total", c.get("quality.violations_total", 0),
            "Schema-contract violations observed across all records",
            exemplar=engine.metrics.counter(
                "quality.violations_total").exemplar())
    kind_family = f"{_METRIC_PREFIX}_quality_violations_by_kind_total"
    lines.append(f"# HELP {kind_family} Schema-contract violations by "
                 "taxonomy kind")
    lines.append(f"# TYPE {kind_family} counter")
    for kind in VIOLATION_KINDS:
        v = c.get(f"quality.violations_{kind}_total", 0)
        lines.append(f'{kind_family}{{kind="{kind}"}} {v}')
    counter("quality_quarantined_records_total",
            c.get("quality.quarantined_records_total", 0),
            "Records quarantined (HTTP 422) by the data-quality firewall",
            exemplar=engine.metrics.counter(
                "quality.quarantined_records_total").exemplar())
    counter("quality_nonfinite_inputs_total",
            c.get("quality.nonfinite_inputs_total", 0),
            "Records/rows rejected at the host-to-device seam for "
            "non-finite input values")
    counter("quality_nonfinite_scores_total",
            c.get("quality.nonfinite_scores_total", 0),
            "Scored rows intercepted because the model produced a "
            "non-finite score")
    gauge("quality_quarantine_fraction",
          round(engine.quality_quarantine_fraction, 6),
          "Quarantined records over all offered records since start")
    gauge("queue_depth", s["queue_depth"],
          "Requests currently waiting for a micro-batch")
    gauge("compiled_path_active", int(s["compiled_path_active"]),
          "1 when batches ride the fused device program")
    # process-wide telemetry from the central registry: compile, racing and
    # host-link counters surface alongside the serving families so one
    # scrape answers "what has this process compiled/pruned/transferred"
    from ..telemetry import REGISTRY
    reg = REGISTRY.snapshot()["gauges"]
    gauge("compile_seconds_total", reg.get("compile.compile_s", 0),
          "Seconds this process has spent inside XLA compilation")
    gauge("backend_compiles_total", reg.get("compile.backend_compiles", 0),
          "Backend compiles performed by this process")
    gauge("compile_cache_hits_total", reg.get("compile.cache_hits", 0),
          "Persistent compile-cache hits")
    gauge("compile_cache_misses_total", reg.get("compile.cache_misses", 0),
          "Persistent compile-cache misses")
    # AOT executable families (ISSUE 9): how many shipped executables this
    # process installed from bundles vs. how many degraded back to JIT
    reg_counters = REGISTRY.snapshot()["counters"]
    counter("aot_executables_loaded_total",
            reg_counters.get("aot.executables_loaded", 0),
            "AOT-serialized executables installed from model bundles")
    counter("aot_fallback_total", reg_counters.get("aot.fallback", 0),
            "Bundles or executables that fell back to the JIT path")
    # compiled-program registry families (ISSUE 18): fleet-wide executable
    # reuse — hits install published executables, misses compile + publish
    counter("aot_registry_hits_total",
            reg_counters.get("aot_registry.hits", 0),
            "Registry lookups that found an installable executable")
    counter("aot_registry_misses_total",
            reg_counters.get("aot_registry.misses", 0),
            "Registry lookups that fell through to the JIT path")
    counter("aot_registry_publishes_total",
            reg_counters.get("aot_registry.publishes", 0),
            "Executables this process published into the registry")
    counter("aot_registry_evictions_total",
            reg_counters.get("aot_registry.evictions", 0),
            "Registry entries evicted by the byte-budget GC")
    counter("aot_registry_shared_hits_total",
            reg_counters.get("aot_registry.shared_hits", 0),
            "Installs served from the process-wide loaded-executable "
            "table (tenants sharing one executable and its device memory)")
    from ..aot_registry import registry_bytes, registry_enabled
    if registry_enabled():
        gauge("aot_registry_bytes", registry_bytes(),
              "On-disk size of the compiled-program registry")
    gauge("racing_cv_fits_saved_total", reg.get("racing.cv_fits_saved", 0),
          "CV fold-fits skipped by selector grid racing")
    gauge("racing_points_pruned_total", reg.get("racing.points_pruned", 0),
          "Grid points pruned by selector racing")
    gauge("host_link_bytes_total", reg.get("host_link.bytes", 0),
          "Tracked host-to-device transfer bytes")
    # sparse feature family (ISSUE 7): volumes from the COO transform path
    # plus whether the ACTIVE bundle vectorizes sparse at all
    gauge("sparse_model_active", int(engine.sparse_model_active),
          "1 when the active bundle vectorizes text through the sparse "
          "COO path")
    gauge("sparse_nnz_total", reg.get("sparse.nnz_total", 0),
          "COO entries built by the sparse transform in this process")
    gauge("sparse_matrices_total", reg.get("sparse.matrices", 0),
          "Sparse matrices built by the transform in this process")
    gauge("sparse_matrix_density", reg.get("sparse.density", 0),
          "Density of the most recently built sparse matrix")
    gauge("model_staleness_seconds", round(engine.model_staleness_s, 3),
          "Seconds since the active bundle was created")
    # drift families: the attached DriftMonitor (engine.attach_drift_monitor)
    # writes drift.* gauges/counters into THIS engine's registry; per-feature
    # PSI and fill-rate deltas surface with a feature label
    eng_gauges = engine.metrics.snapshot()["gauges"]
    for metric, prefix, help_ in (
            ("drift_feature_psi", "drift.psi.",
             "Per-feature PSI of the live window vs training baselines"),
            ("drift_feature_fill_delta", "drift.fill_delta.",
             "Per-feature |fill-rate - baseline fill-rate|")):
        labeled = sorted((k[len(prefix):], v) for k, v in eng_gauges.items()
                         if k.startswith(prefix))
        if labeled:
            full = f"{_METRIC_PREFIX}_{metric}"
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} gauge")
            for feature, v in labeled:
                lines.append(f'{full}{{feature={json.dumps(feature)}}} '
                             f'{v:.6g}')
    gauge("drift_score_psi", eng_gauges.get("drift.score_psi", 0),
          "PSI of the live score distribution vs the training baseline")
    gauge("drift_rows_observed", eng_gauges.get("drift.rows_observed", 0),
          "Rows in the current drift observation window")
    counter("drift_evaluations_total", c.get("drift.evaluations_total", 0),
            "Drift evaluations performed")
    counter("drift_breaches_total", c.get("drift.breaches_total", 0),
            "Drift evaluations that breached a threshold")
    # lifecycle counters live in the process-wide registry (the controller
    # may run in another thread of this process); families always render so
    # dashboards see explicit zeros
    lc = REGISTRY.snapshot()["counters"]
    for fam, help_ in (("retrains", "Lifecycle retrains started"),
                       ("promotions", "Candidates promoted to serving"),
                       ("rejections", "Candidates that lost the holdout "
                                      "gate"),
                       ("preemptions", "Retrains preempted mid-sweep "
                                       "(resumable)"),
                       ("failed_retrains", "Retrains that errored out")):
        counter(f"lifecycle_{fam}_total",
                lc.get(f"lifecycle.{fam}_total", 0), help_)
    # overload control plane: health state machine, adaptive admission and
    # both circuit breakers — the families the chaos SLO harness asserts on
    ov = s.get("overload") or {}
    health = ov.get("health") or {}
    gauge("health_state", HEALTH_CODES.get(health.get("state"), 0),
          "Engine health: 0 SERVING / 1 DEGRADED / 2 BROWNOUT / 3 DRAINING")
    state_name = health.get("state", "SERVING")
    lines.append(f"# HELP {_METRIC_PREFIX}_health_info Current health "
                 "state and transition reason")
    lines.append(f"# TYPE {_METRIC_PREFIX}_health_info gauge")
    lines.append(f'{_METRIC_PREFIX}_health_info{{state="{state_name}",'
                 f'reason={json.dumps(health.get("reason", ""))}}} 1')
    gauge("admission_limit", ov.get("admission_limit", 0),
          "Queue slots currently granted by the adaptive AIMD limit "
          "(queue_bound is its ceiling)")
    counter("shed_limit_total", c.get("shed_limit_total", 0),
            "Requests shed because the queue passed the admission limit",
            exemplar=engine.metrics.counter("shed_limit_total").exemplar())
    counter("shed_deadline_total", c.get("shed_deadline_total", 0),
            "Requests shed because the queue wait would blow their "
            "deadline",
            exemplar=engine.metrics.counter(
                "shed_deadline_total").exemplar())
    counter("shed_memory_total", c.get("shed_memory_total", 0),
            "Requests shed because the estimated queued-batch footprint "
            "exceeded the device memory budget (batchBytesBudget)",
            exemplar=engine.metrics.counter("shed_memory_total").exemplar())
    counter("brownout_sheds_total", c.get("brownout_sheds_total", 0),
            "Batch-observer runs skipped while in BROWNOUT")
    counter("health_transitions_total", c.get("health_transitions_total", 0),
            "Health state machine transitions")
    for short, brk in (("compiled", ov.get("compiled_breaker") or {}),
                       ("reload", ov.get("reload_breaker") or {})):
        gauge(f"{short}_breaker_state",
              _BREAKER_CODES.get(brk.get("state"), 0),
              f"The {short} circuit breaker: 0 closed / 1 half-open / "
              "2 open")
        name = brk.get("name", "")
        for transition in ("open", "half_open", "closed"):
            counter(f"{short}_breaker_{transition}_transitions_total",
                    c.get(f"breaker.{name}.{transition}_total", 0),
                    f"Times the {short} breaker entered {transition}")
    counter("breaker_demoted_batches_total",
            c.get("breaker_demoted_batches_total", 0),
            "Micro-batches routed to the local fallback because the "
            "compiled-path breaker was open")
    counter("reload_breaker_skipped_total",
            c.get("reload_breaker_skipped_total", 0),
            "Hot-reload attempts skipped while the reload breaker was open")
    gauge("streaming_dead_letters_evicted_total",
          lc.get("streaming.dead_letters_evicted_total", 0),
          "Dead-lettered batches evicted from the bounded streaming DLQ "
          "in this process")
    lines.append(f"# HELP {_METRIC_PREFIX}_model_info Serving model version")
    lines.append(f"# TYPE {_METRIC_PREFIX}_model_info gauge")
    lines.append(f'{_METRIC_PREFIX}_model_info'
                 f'{{version="{s["model_version"]}"}} 1')
    for hist_name, hist, snap in (
            ("request_latency_seconds", engine.request_latency,
             s["request_latency"]),
            ("batch_latency_seconds", engine.batch_latency,
             s["batch_latency"])):
        full = f"{_METRIC_PREFIX}_{hist_name}"
        lines.append(f"# HELP {full} End-to-end latency summary")
        lines.append(f"# TYPE {full} summary")
        # the slowest-bucket exemplar rides the highest quantile: a p99
        # spike in Prometheus links straight to a concrete request trace
        slow_ex = hist.exemplar(slowest=True)
        for q in ("0.5", "0.95", "0.99"):
            key = "p" + q.replace("0.", "").ljust(2, "0")
            v = snap.get(key)
            if v is not None:
                suffix = _exemplar_suffix(slow_ex) if q == "0.99" else ""
                lines.append(f'{full}{{quantile="{q}"}} {v:.6g}{suffix}')
        lines.append(f"{full}_sum {snap['sum']:.6g}")
        lines.append(f"{full}_count {snap['count']}"
                     f"{_exemplar_suffix(hist.exemplar())}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "ScoringHTTPServer"

    # quiet by default; the engine's FailureLog is the observability channel
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _request_context(self) -> TraceContext:
        """The per-request W3C position: continue the client's trace when
        it sent a valid ``traceparent``, start a fresh one otherwise.  A
        malformed or oversized header parses to None and falls through to
        a fresh context — never an error."""
        parent = TraceContext.parse(self.headers.get("traceparent"))
        ctx = parent.child() if parent else TraceContext.new()
        self._req_ctx = ctx
        self._req_span = None
        return ctx

    def _reply(self, code: int, payload: Any,
               content_type: str = "application/json",
               extra_headers: Optional[Dict[str, str]] = None) -> None:
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode()
                if content_type == "application/json"
                else str(payload).encode())
        ctx: Optional[TraceContext] = getattr(self, "_req_ctx", None)
        if ctx is None:
            ctx = self._request_context()
        sp = getattr(self, "_req_span", None)
        if sp is not None:
            sp.attrs.setdefault("httpStatus", code)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        # EVERY response — including 400/415/429/503/504 sheds — carries
        # the request's trace position, so a client can correlate any
        # outcome with the server-side trace
        self.send_header("traceparent", ctx.to_traceparent())
        self.send_header("X-Request-Id", ctx.trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; nothing to salvage

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        self._request_context()
        if self.server.registry is not None:
            self._do_get_registry()
            return
        engine = self.server.engine
        if self.path == "/healthz":
            # liveness, not readiness: a draining process is still alive
            # (restarting it would abort the drain) — /readyz is the probe
            # that takes it out of rotation
            from ..checkpoint import bundle_version
            health = engine.overload.health.snapshot()
            status = ("draining" if self.server.draining else "ok")
            self._reply(200, {"status": status,
                              "health": health["state"],
                              "healthReason": health["reason"],
                              "modelVersion": engine.model_version,
                              "bundleVersion": bundle_version(
                                  engine.active_bundle_path),
                              "modelStalenessS": round(
                                  engine.model_staleness_s, 3),
                              "queueDepth": engine.queue_depth,
                              "qualityPolicy": engine.quality_policy,
                              "qualityQuarantineFraction": round(
                                  engine.quality_quarantine_fraction, 6)})
        elif self.path == "/readyz":
            health = engine.overload.health.snapshot()
            breaker = engine.overload.compiled_breaker
            reasons: List[str] = []
            if self.server.draining or health["state"] == "DRAINING":
                reasons.append("draining")
            if breaker.current_state() == breaker.OPEN:
                reasons.append("compiled-path breaker open")
            if not reasons:
                self._reply(200, {"ready": True,
                                  "health": health["state"],
                                  "modelVersion": engine.model_version})
            else:
                retry = (breaker.retry_after_s()
                         if "compiled-path breaker open" in reasons
                         and not self.server.draining else 30.0)
                self._reply(503, {"ready": False,
                                  "health": health["state"],
                                  "reasons": reasons},
                            extra_headers={
                                "Retry-After": _retry_after(retry)})
        elif self.path == "/metrics":
            self._reply(200, render_metrics(engine).encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def _do_get_registry(self) -> None:
        """Multi-tenant GET surfaces: ``/healthz`` lists per-tenant state,
        ``/readyz`` is ready while ANY tenant is servable, ``/metrics`` is
        the tenant-labeled merge."""
        registry = self.server.registry
        if self.path == "/healthz":
            st = registry.status()
            st["status"] = "draining" if self.server.draining else "ok"
            self._reply(200, st)
        elif self.path == "/readyz":
            st = registry.status()
            servable = st["tenantsTotal"] - st["tenantsQuarantined"]
            if self.server.draining:
                self._reply(503, {"ready": False, "reasons": ["draining"]},
                            extra_headers={"Retry-After": "30"})
            elif servable < 1:
                self._reply(503, {"ready": False,
                                  "reasons": ["no servable tenants"],
                                  "tenantsQuarantined":
                                      st["tenantsQuarantined"]},
                            extra_headers={"Retry-After": "30"})
            else:
                self._reply(200, {"ready": True,
                                  "tenantsTotal": st["tenantsTotal"],
                                  "tenantsActive": st["tenantsActive"],
                                  "tenantsQuarantined":
                                      st["tenantsQuarantined"]})
        elif self.path == "/metrics":
            self._reply(200, registry.metrics_text().encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def _tenant_from_path(self) -> Tuple[bool, Optional[str]]:
        """``(path_ok, tenant)``: ``/v1/score`` → (True, None);
        ``/v1/score/<tenant>`` → (True, tenant); anything else
        → (False, None)."""
        if self.path == "/v1/score":
            return True, None
        prefix = "/v1/score/"
        if self.path.startswith(prefix):
            from urllib.parse import unquote
            tenant = unquote(self.path[len(prefix):])
            if tenant and "/" not in tenant:
                return True, tenant
        return False, None

    def _resolve_engine(self, tenant: Optional[str]
                        ) -> Optional[ScoringEngine]:
        """Registry-mode tenant → engine, replying 404 (unknown) or 503 +
        ``Retry-After`` (quarantined) and returning None on failure.
        Single-engine mode ignores ``tenant`` and returns the engine —
        the path check in ``do_POST`` already enforced ``/v1/score``."""
        from .tenants import TenantQuarantinedError, UnknownTenantError
        registry = self.server.registry
        if registry is None:
            return self.server.engine
        if not tenant:
            self._reply(404, {
                "error": "multi-tenant server: name the model via "
                         "/v1/score/<tenant>, an X-Model-Id header, or a "
                         "modelId field", "tenants": registry.tenants()})
            return None
        try:
            return registry.engine_for(tenant)
        except UnknownTenantError as e:
            self._reply(404, {"error": str(e), "tenant": tenant})
            return None
        except TenantQuarantinedError as e:
            self._reply(503, {"error": str(e), "tenant": tenant,
                              "state": "QUARANTINED"},
                        extra_headers={"Retry-After": _retry_after(
                            e.retry_after_s)})
            return None
        except EngineClosed as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "30"})
            return None

    def do_POST(self) -> None:  # noqa: N802
        ctx = self._request_context()
        path_ok, path_tenant = self._tenant_from_path()
        registry_mode = self.server.registry is not None
        if not path_ok or (path_tenant is not None and not registry_mode):
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        tenant = path_tenant or self.headers.get("X-Model-Id")
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or
                 "").split(";")[0].strip().lower()
        timeout_s = self.server.request_deadline_s
        columnar = ctype == wire.CONTENT_TYPE
        # the request span is pinned to the request's W3C position (ctx),
        # so the engine's batch span — which links back to ctx — and any
        # supervised child this request triggers share its trace id
        attrs = {"tenant": tenant} if (registry_mode and tenant) else {}
        with span("serving.request", ctx=ctx,
                  wire="columnar" if columnar else "json",
                  **attrs) as req_sp:
            self._req_span = req_sp
            if columnar:
                engine = self._resolve_engine(tenant)
                if engine is None:
                    return
                self._post_columnar(engine, body, timeout_s, ctx)
            elif registry_mode:
                self._post_json_registry(tenant, body, timeout_s, ctx)
            else:
                self._post_json(self.server.engine, body, timeout_s, ctx)

    def _post_json_registry(self, tenant: Optional[str], body: bytes,
                            timeout_s: Optional[float],
                            ctx: TraceContext) -> None:
        """JSON scoring with tenant resolution: the path / ``X-Model-Id``
        header wins; otherwise a ``modelId`` field in the record (or in
        every record of a list — mixed ids are a 400, one request routes
        to one bulkhead).  The field is stripped before scoring."""
        try:
            payload = json.loads(body or b"null")
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"invalid JSON body: {e}"})
            return
        if tenant is None:
            if isinstance(payload, dict):
                tenant = payload.pop("modelId", None)
            elif isinstance(payload, list):
                ids = {r.pop("modelId", None) for r in payload
                       if isinstance(r, dict)}
                if len(ids) > 1:
                    self._reply(400, {
                        "error": "mixed modelId values in one list "
                                 "request; a request routes to exactly "
                                 "one tenant"})
                    return
                tenant = next(iter(ids), None)
        if tenant is not None and not isinstance(tenant, str):
            self._reply(400, {"error": "modelId must be a string"})
            return
        sp = getattr(self, "_req_span", None)
        if sp is not None and tenant:
            sp.attrs.setdefault("tenant", tenant)
        engine = self._resolve_engine(tenant)
        if engine is None:
            return
        self._score_json(engine, payload, timeout_s, ctx)

    def _post_columnar(self, engine: ScoringEngine, body: bytes,
                       timeout_s: Optional[float],
                       ctx: TraceContext) -> None:
        if self.server.wire_format == "json":
            self._reply(415, {"error": "columnar wire format is "
                                       "disabled on this server "
                                       "(wire_format=json); send JSON"})
            return
        try:
            batch = wire.decode_batch(body, engine.raw_features)
            arrays, version = engine.score_columns(batch, timeout_s,
                                                   ctx=ctx)
            out = wire.encode_result_arrays(arrays, len(batch))
            self._reply(200, out, content_type=wire.CONTENT_TYPE,
                        extra_headers={"X-Model-Version": version})
        except wire.WireFormatError as e:
            # malformed body = client bug, never a worker crash: a
            # structured 400 names exactly what failed to parse — with the
            # quality-taxonomy kind when the decoder could classify it
            detail: Dict[str, Any] = {"error": "malformed columnar body",
                                      "detail": str(e)}
            kind = getattr(e, "violation_kind", None)
            if kind:
                detail["violationKind"] = kind
            self._reply(400, detail)
        except RecordQualityError as e:
            # rows identified by the firewall: 422 with the per-row
            # violation list; other queued requests scored normally
            self._reply(422, {
                "error": "record failed data-quality validation",
                "policy": e.policy, "violations": e.to_json()})
        except OverloadedError as e:
            self._reply(429, {"error": str(e)},
                        extra_headers={"Retry-After": _retry_after(
                            getattr(e, "retry_after_s", 1.0))})
        except (DeadlineExceeded, WatchdogTimeout) as e:
            self._reply(504, {"error": str(e)})
        except EngineClosed as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "30"})
        except Exception as e:  # noqa: BLE001 — see JSON path below
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def _post_json(self, engine: ScoringEngine, body: bytes,
                   timeout_s: Optional[float], ctx: TraceContext) -> None:
        try:
            payload = json.loads(body or b"null")
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"invalid JSON body: {e}"})
            return
        self._score_json(engine, payload, timeout_s, ctx)

    def _score_json(self, engine: ScoringEngine, payload: Any,
                    timeout_s: Optional[float], ctx: TraceContext) -> None:
        try:
            if isinstance(payload, dict):
                result, version = engine.score_record(payload, timeout_s,
                                                      ctx=ctx)
                self._reply(200, {"modelVersion": version, "result": result})
            elif isinstance(payload, list):
                if not all(isinstance(r, dict) for r in payload):
                    self._reply(400, {"error": "list items must be objects"})
                    return
                pairs = engine.score_records(payload, timeout_s, ctx=ctx)
                versions = {v for _, v in pairs}
                out: Dict[str, Any] = {
                    "modelVersion": pairs[0][1] if pairs else
                    engine.model_version,
                    "results": [r for r, _ in pairs]}
                if len(versions) > 1:   # a hot swap landed mid-list
                    for (r, v), slot in zip(pairs, out["results"]):
                        slot["_modelVersion"] = v
                self._reply(200, out)
            else:
                self._reply(400, {"error": "body must be an object or a "
                                           "list of objects"})
        except RecordQualityError as e:
            # the poison record (or the offending rows of a list, tagged
            # with their index) gets its own structured 422; co-batched
            # neighbors in other requests score normally
            self._reply(422, {
                "error": "record failed data-quality validation",
                "policy": e.policy, "violations": e.to_json()})
        except OverloadedError as e:
            self._reply(429, {"error": str(e)},
                        extra_headers={"Retry-After": _retry_after(
                            getattr(e, "retry_after_s", 1.0))})
        except (DeadlineExceeded, WatchdogTimeout) as e:
            self._reply(504, {"error": str(e)})
        except EngineClosed as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "30"})
        except Exception as e:  # noqa: BLE001 — a bad record must not 500
            #                     the whole connection with a stack trace
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})


class ScoringHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer bound to a ScoringEngine."""

    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog of 5 resets connections under a
    # concurrent-client burst; serving is exactly that workload
    request_queue_size = 128

    def __init__(self, engine: Optional[ScoringEngine],
                 host: str = "127.0.0.1", port: int = 8180,
                 request_deadline_s: Optional[float] = 30.0,
                 reuse_port: bool = False, wire_format: str = "auto",
                 registry: Optional[Any] = None):
        if engine is None and registry is None:
            raise ValueError("either an engine (single bundle) or a "
                             "TenantRegistry is required")
        # bind manually so SO_REUSEPORT is set BEFORE bind: N pool workers
        # each bind the same (host, port) and the kernel load-balances
        # accepted connections across them
        super().__init__((host, port), _Handler, bind_and_activate=False)
        try:
            if reuse_port:
                self.socket.setsockopt(socket.SOL_SOCKET,
                                       socket.SO_REUSEPORT, 1)
            self.server_bind()
            self.server_activate()
        except BaseException:
            self.server_close()
            raise
        self.engine = engine
        # multi-tenant mode: a TenantRegistry routes /v1/score/<tenant>,
        # X-Model-Id and modelId-field requests to per-tenant engines; the
        # single-engine path above stays byte-for-byte when registry=None
        self.registry = registry
        self.request_deadline_s = request_deadline_s
        self.reuse_port = reuse_port
        self.wire_format = wire_format  # "auto" | "json" (columnar → 415)
        self.draining = False

    @property
    def port(self) -> int:
        return self.server_address[1]

    def drain_and_close(self, timeout_s: Optional[float] = 30.0) -> None:
        """Stop accepting, finish queued work, release the socket."""
        self.draining = True
        if self.registry is not None:
            self.registry.close(timeout_s=timeout_s)
        if self.engine is not None:
            self.engine.close(drain=True, timeout_s=timeout_s)
        self.shutdown()
        self.server_close()


def start_server(model_location: Optional[str] = None, *,
                 host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 64, linger_ms: float = 2.0,
                 queue_bound: int = 256,
                 request_deadline_s: Optional[float] = 30.0,
                 reload_poll_s: float = 0.0, warm: bool = True,
                 overload: Optional[OverloadConfig] = None,
                 reuse_port: bool = False, wire_format: str = "auto",
                 model_root: Optional[str] = None,
                 tenant_max_active: Optional[int] = None,
                 tenant_memory_budget_bytes: Optional[int] = None,
                 quality_policy: Optional[str] = None
                 ) -> Tuple[ScoringHTTPServer, threading.Thread]:
    """Build engine + server and start the accept loop in a daemon thread.
    ``port=0`` binds an ephemeral port (see ``server.port``).  Exactly one
    of ``model_location`` (single bundle, the unchanged default path) or
    ``model_root`` (a directory of per-tenant bundles → multi-tenant
    routing) is required."""
    if bool(model_location) == bool(model_root):
        raise ValueError("exactly one of model_location (single bundle) "
                         "or model_root (multi-tenant) is required")
    engine = None
    registry = None
    if model_root:
        from .tenants import TenantRegistry
        registry = TenantRegistry(
            model_root, max_batch=max_batch, queue_bound=queue_bound,
            reload_poll_s=reload_poll_s, warm=warm, overload=overload,
            max_active=tenant_max_active,
            memory_budget_bytes=tenant_memory_budget_bytes)
    else:
        # tenant engines (registry mode) resolve the policy from the
        # TRANSMOGRIFAI_QUALITY_POLICY env default on their own
        engine = ScoringEngine(model_location, max_batch=max_batch,
                               linger_ms=linger_ms, queue_bound=queue_bound,
                               reload_poll_s=reload_poll_s, warm=warm,
                               overload=overload,
                               quality_policy=quality_policy)
    server = ScoringHTTPServer(engine, host=host, port=port,
                               request_deadline_s=request_deadline_s,
                               reuse_port=reuse_port,
                               wire_format=wire_format, registry=registry)
    thread = threading.Thread(target=server.serve_forever,
                              name="scoring-http", daemon=True)
    thread.start()
    return server, thread


def serve_main(model_location: Optional[str] = None, *,
               host: str = "127.0.0.1",
               port: int = 8180, max_batch: int = 64, linger_ms: float = 2.0,
               queue_bound: int = 256,
               request_deadline_s: Optional[float] = 30.0,
               reload_poll_s: float = 10.0,
               overload: Optional[OverloadConfig] = None,
               wire_format: str = "auto",
               model_root: Optional[str] = None,
               tenant_max_active: Optional[int] = None,
               tenant_memory_budget_bytes: Optional[int] = None,
               quality_policy: Optional[str] = None) -> int:
    """Blocking entry point for the ``serve`` CLI subcommand: serve until
    SIGTERM/SIGINT, then drain in-flight batches and exit 0."""
    with preemption_guard("serve"):
        server, thread = start_server(
            model_location, host=host, port=port, max_batch=max_batch,
            linger_ms=linger_ms, queue_bound=queue_bound,
            request_deadline_s=request_deadline_s,
            reload_poll_s=reload_poll_s, overload=overload,
            wire_format=wire_format, model_root=model_root,
            tenant_max_active=tenant_max_active,
            tenant_memory_budget_bytes=tenant_memory_budget_bytes,
            quality_policy=quality_policy)
        served = (f"{len(server.registry.tenants())} tenants from "
                  f"{model_root}" if server.registry is not None
                  else server.engine.model_version)
        print(f"serving {served} on "
              f"http://{host}:{server.port} (max_batch={max_batch}, "
              f"linger_ms={linger_ms})", flush=True)
        try:
            while not shutdown_requested("serve"):
                time.sleep(0.2)
        finally:
            print("draining...", flush=True)
            server.drain_and_close()
            thread.join(timeout=5.0)
    return 0


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port for tests/smoke runs."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
