"""Online scoring — the deployable inference stack over the fitted DAG.

``engine.ScoringEngine`` coalesces concurrent single-record requests into
padded device batches (no online XLA recompile after warmup);
``server`` exposes it over stdlib HTTP with health, Prometheus metrics,
admission control, hot model reload, and SIGTERM draining.
"""

from .engine import (DeadlineExceeded, EngineClosed,  # noqa: F401
                     OverloadedError, ScoringEngine)
from .overload import (BROWNOUT, DEGRADED, DRAINING,  # noqa: F401
                       HEALTH_STATES, SERVING, HealthStateMachine,
                       OverloadConfig, OverloadController)
from .server import ScoringHTTPServer, serve_main  # noqa: F401
from .tenants import (TENANT_ACTIVE, TENANT_INACTIVE,  # noqa: F401
                      TENANT_QUARANTINED, TenantQuarantinedError,
                      TenantRegistry, UnknownTenantError)
