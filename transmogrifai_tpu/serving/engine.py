"""ScoringEngine — adaptive micro-batching over the compiled score path.

The reference's `local` module serves one record at a time through a pure
row closure (OpWorkflowModelLocal.scoreFunction); a TPU earns its keep only
when concurrent requests share one device dispatch.  The engine:

* loads a VERIFIED bundle (``checkpoint.find_latest_valid`` — corrupt
  versions are skipped via manifest digests),
* pre-warms the fused scoring program at a small ladder of padded batch
  sizes (powers of two up to ``max_batch``), so the jit cache — keyed on
  batch length — is fully populated before traffic arrives and concurrent
  load never triggers an online XLA recompile,
* runs a continuous micro-batcher thread: the moment the device frees it
  drains the request queue into the largest ladder-padded batch available
  (Clipper/vLLM-style continuous batching — no fixed linger deadline, so
  throughput never trades against an idle-latency constant; ``linger_ms``
  is accepted for compatibility and ignored),
* scores packed columnar requests (``serving/wire.py``) as pre-assembled
  ``ColumnBatch`` slices — no per-record Python on that path,
* watches the checkpoint root and atomically hot-swaps newer valid
  versions in (events through the ambient ``FailureLog``),
* sheds load (``OverloadedError`` → HTTP 429) past ``queue_bound``, bounds
  device dispatches with ``resilience.run_with_deadline``, and falls back
  to ``local.score_function`` — same outputs, row-at-a-time — for models
  or batches the compiled path can't handle.

Every response is tagged with the model version that produced it, so a
client can correlate scores across a hot swap.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..checkpoint import (bundle_version, find_latest_valid, is_bundle_dir,
                          read_manifest)
from ..columns import Column, ColumnBatch, column_from_values
from ..local import extract_raw_value, score_function
from ..quality import (NON_FINITE_VALUE, QualityConfig, RawSchema,
                       RecordQualityError, Violation, batch_nonfinite_rows,
                       mask_nonfinite_result_arrays,
                       result_nonfinite_fields)
from ..resilience import (WatchdogTimeout, maybe_inject, record_failure,
                          run_with_deadline)
from ..stages.generator import FeatureGeneratorStage
from ..telemetry import MetricsRegistry, TraceContext, span
from ..types import FeatureType, Prediction
from .overload import BROWNOUT, OverloadConfig, OverloadController


class OverloadedError(RuntimeError):
    """Admission control shed this request (HTTP 429): queue past the
    adaptive limit / ``queue_bound``, or the estimated queue wait would
    blow the request deadline.  ``retry_after_s`` is the controller's
    honest estimate of when to come back."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class EngineClosed(RuntimeError):
    """The engine is draining/closed and accepts no new requests."""


class DeadlineExceeded(RuntimeError):
    """The per-request deadline elapsed before a result was produced."""


def _padding_ladder(max_batch: int) -> List[int]:
    """Powers of two up to (and including) ``max_batch``: the full set of
    batch lengths the engine will ever hand the compiled program."""
    ladder = []
    size = 1
    while size < max_batch:
        ladder.append(size)
        size *= 2
    ladder.append(int(max_batch))
    return ladder


def records_to_batch(raw_features: Sequence, records: List[Dict[str, Any]]
                     ) -> ColumnBatch:
    """Raw records → raw ColumnBatch, with exactly the stage-0 semantics of
    ``local.score_function`` (extract_fn, monoid zero for non-nullable kinds
    absent at scoring time) so the two paths are parity-testable."""
    cols = {}
    for f in raw_features:
        gen = f.origin_stage
        if isinstance(gen, FeatureGeneratorStage):
            cols[f.name] = gen.extract_column(records)
        else:
            vals = [extract_raw_value(f, r).value for r in records]
            cols[f.name] = column_from_values(f.kind, vals)
    return ColumnBatch(cols, len(records))


class _Request:
    __slots__ = ("record", "event", "result", "error", "t_enqueue", "ctx")

    def __init__(self, record: Dict[str, Any],
                 ctx: Optional[TraceContext] = None):
        self.record = record
        self.event = threading.Event()
        self.result: Optional[Tuple[Dict[str, Any], str]] = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()
        self.ctx = ctx


class _ColumnarRequest:
    """A pre-assembled ColumnBatch riding the same queue as record
    requests.  It counts as ``len(batch)`` rows for admission and queue
    depth, and the batcher dispatches it alone (sliced into ladder-sized
    chunks) — record and columnar requests never mix in one device batch."""

    __slots__ = ("batch", "rows", "event", "result", "error", "t_enqueue",
                 "ctx")

    def __init__(self, batch: ColumnBatch,
                 ctx: Optional[TraceContext] = None):
        self.batch = batch
        self.rows = len(batch)
        self.event = threading.Event()
        self.result: Optional[Tuple[Dict[str, Any], str]] = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()
        self.ctx = ctx


class _ModelEntry:
    """One loaded model version: the model, its identity, and its row-wise
    local scorer (the fallback AND the parity oracle)."""

    def __init__(self, model, bundle_path: str):
        self.model = model
        self.bundle_path = bundle_path
        self.version = bundle_version(bundle_path)
        self.local_fn: Callable = score_function(model)
        self.result_names = [f.name for f in model.result_features]
        # staleness anchors: the bundle's manifest createdAt when it has
        # one, else when this process loaded it
        created = None
        try:
            created = (read_manifest(bundle_path) or {}).get("createdAt")
        except Exception as e:  # noqa: BLE001 — a legacy bundle has no
            #                     manifest (read_manifest → None, no raise);
            #                     reaching here means the manifest exists but
            #                     is unreadable.  Serve anyway — staleness
            #                     falls back to process load time — but say
            #                     so (PR-1 convention: silent excepts report)
            record_failure(
                "serving", "degraded", e, point="serving.manifest",
                bundle=bundle_path,
                detail="manifest unreadable; model_staleness_seconds falls "
                       "back to process load time")
        self.created_at: Optional[float] = (
            float(created) if isinstance(created, (int, float)) else None)
        self.loaded_at: float = time.time()
        # the data-quality firewall's schema contract: the bundle's
        # digest-covered schema.json, or a re-derivation from the model's
        # raw features for legacy bundles (WorkflowModel.load attaches it;
        # for_model covers models handed in directly)
        self.schema: RawSchema = (getattr(model, "raw_schema", None)
                                  or RawSchema.for_model(model, bundle_path))
        # sparse-model detection: a SmartTextVectorizer that routed text to
        # the COO path stamps metadata["sparse"]=True on its fitted stage
        # (metadata round-trips through the bundle) — /metrics exposes this
        # so operators can see which serving processes run sparse bundles
        self.sparse: bool = any(
            bool(getattr(st, "metadata", None)
                 and st.metadata.get("sparse"))
            for layer in (getattr(model, "fitted_dag", None) or [])
            for st in layer)


def _result_row(scored: ColumnBatch, names: Sequence[str], i: int
                ) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name in names:
        if name not in scored:
            continue
        v = scored[name].row_value(i)
        if isinstance(v, Prediction):
            out[name] = dict(v.value)
        elif isinstance(v, FeatureType):
            out[name] = v.value
        else:
            out[name] = v
    return out


class ScoringEngine:
    """See module docstring.  Thread-safe; one batcher thread plus an
    optional reload-watcher thread."""

    def __init__(self, model_location: str, *, max_batch: int = 64,
                 linger_ms: float = 2.0, queue_bound: int = 256,
                 batch_deadline_s: Optional[float] = 30.0,
                 reload_poll_s: float = 0.0, warm: bool = True,
                 warm_record: Optional[Dict[str, Any]] = None,
                 overload: Optional[OverloadConfig] = None,
                 tenant: Optional[str] = None,
                 quality_policy: Optional[str] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.model_location = model_location
        # multi-tenant serving (TenantRegistry): the tenant this engine is
        # a bulkhead for.  Scopes the breaker names, tags batch spans and
        # shed events — None (single-bundle) leaves every name unchanged.
        self.tenant = tenant
        self.max_batch = int(max_batch)
        # linger_ms is deprecated and ignored: the continuous batcher
        # dispatches as soon as the device frees, coalescing whatever is
        # queued at that moment (kept as a kwarg so existing callers and
        # configs keep working)
        self.linger_s = float(linger_ms) / 1000.0
        self.queue_bound = int(queue_bound)
        self.batch_deadline_s = batch_deadline_s
        self.reload_poll_s = float(reload_poll_s)
        self.ladder = _padding_ladder(self.max_batch)
        self._warm_record = dict(warm_record or {})
        # data-quality firewall policy (strict | coerce | quarantine | off);
        # env default so `op serve` picks it up without plumbing
        self.quality_policy = (quality_policy if quality_policy is not None
                               else QualityConfig.resolve(None).policy)

        self._queue: "collections.deque" = collections.deque()
        self._queued_rows = 0  # rows, not entries: a columnar request
        #                        counts its full row span (guarded by _cv)
        self._cv = threading.Condition()
        self._closed = False
        self._draining = False
        self._swap_lock = threading.Lock()   # guards self._entry
        self._score_lock = threading.Lock()  # serializes compile-sensitive
        #                                      device work (batches, warmups)
        self._compiled_ok = True

        # per-engine metrics namespace: counters/gauges/histograms reset with
        # the engine; /metrics and stats() read everything from here.  The
        # old attribute names stay as aliases into the registry.
        self.metrics = MetricsRegistry()
        self.request_latency = self.metrics.histogram("request_latency")
        self.batch_latency = self.metrics.histogram("batch_latency")
        self.metrics.gauge("queue_depth", lambda: self.queue_depth)
        self.metrics.gauge("compiled_path_active",
                           lambda: int(self._compiled_ok))

        # the overload control plane: adaptive admission, the compiled-path
        # and reload circuit breakers, and the health state machine.  It
        # shares this engine's registry so /metrics sees everything.
        self.overload = OverloadController(
            overload, queue_bound=lambda: self.queue_bound,
            max_batch=self.max_batch, registry=self.metrics,
            scope=tenant)

        # lifecycle hooks: batch observers see every successfully-scored
        # (records, results) pair; the drift monitor is one such observer.
        # Column observers are their columnar-path twins — they consume the
        # (ColumnBatch, result_arrays) pair directly, so columnar traffic
        # is observed without per-record dict materialization
        self._batch_observers: List[Callable] = []
        self._column_observers: List[Callable] = []
        self.drift_monitor = None

        self._entry = self._load_entry()
        if warm:
            self._warm(self._entry)
        # a model demoted at warmup starts DEGRADED, not SERVING
        self.overload.refresh_health(queue_depth=0, draining=False,
                                     compiled_ok=self._compiled_ok)

        self._batcher = threading.Thread(
            target=self._batch_loop, name="scoring-batcher", daemon=True)
        self._batcher.start()
        self._watcher: Optional[threading.Thread] = None
        if self.reload_poll_s > 0:
            self._watcher = threading.Thread(
                target=self._watch_loop, name="model-watcher", daemon=True)
            self._watcher.start()

    # -- model lifecycle ---------------------------------------------------
    def _load_entry(self, bundle: Optional[str] = None) -> _ModelEntry:
        from ..workflow import WorkflowModel
        path = bundle
        if path is None:
            path = (self.model_location
                    if is_bundle_dir(self.model_location)
                    else find_latest_valid(self.model_location))
        # AOT executables deserialize inside load; the span separates that
        # (ideally compile-free) cost from warmup in run timelines
        with span("serving.aot_load", bundle=os.path.basename(path)) as sp:
            model = WorkflowModel.load(path)
            if sp is not None:
                sp.attrs["aotExecutables"] = getattr(
                    model, "aot_executables", 0)
        return _ModelEntry(model, path)

    def _warm(self, entry: _ModelEntry) -> None:
        """Score a synthetic record at every ladder size so jit compiles
        every batch length the batcher will ever dispatch.  A model whose
        compiled path fails at warmup serves via the local fallback."""
        with self._score_lock:
            for size in self.ladder:
                records = [dict(self._warm_record) for _ in range(size)]
                try:
                    from ..compiled import trace_count
                    t0 = trace_count()
                    self._score_compiled(entry, records)
                    self.metrics.counter("warmup_traces_total").inc(
                        trace_count() - t0)
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    self._compiled_ok = False
                    record_failure("serving", "degraded", e,
                                   point="serving.batch",
                                   fallback="local row scoring",
                                   detail=f"warmup at batch size {size}")
                    return

    @property
    def model_version(self) -> str:
        with self._swap_lock:
            return self._entry.version

    @property
    def active_bundle_path(self) -> str:
        with self._swap_lock:
            return self._entry.bundle_path

    @property
    def model_staleness_s(self) -> float:
        """Seconds since the active bundle was created (manifest
        ``createdAt``; falls back to when this process loaded it)."""
        with self._swap_lock:
            entry = self._entry
        ref = entry.created_at if entry.created_at is not None \
            else entry.loaded_at
        return max(0.0, time.time() - ref)

    @property
    def compiled_path_active(self) -> bool:
        return self._compiled_ok

    @property
    def sparse_model_active(self) -> bool:
        """True when the active bundle vectorizes through the sparse COO
        path (any fitted stage with ``metadata["sparse"]``)."""
        with self._swap_lock:
            return self._entry.sparse

    @property
    def quality_quarantine_fraction(self) -> float:
        """Fraction of offered records the firewall quarantined (rejected
        records never reach ``requests_total``, so the denominator is
        admitted + quarantined)."""
        c = self.metrics.counters()
        q = c.get("quality.quarantined_records_total", 0)
        total = c.get("requests_total", 0) + q
        return (q / total) if total else 0.0

    # -- lifecycle hooks ---------------------------------------------------
    def add_batch_observer(self, fn: Callable) -> None:
        """Register ``fn(records, results)`` to run after each micro-batch
        (successfully-scored records only).  Observer errors are swallowed
        into the FailureLog — observability never fails a request."""
        self._batch_observers.append(fn)

    def add_column_observer(self, fn: Callable) -> None:
        """Register ``fn(batch, result_arrays)`` to run after each columnar
        request (the packed path's analog of ``add_batch_observer`` — same
        swallowed-error contract)."""
        self._column_observers.append(fn)

    def attach_drift_monitor(self, **kw):
        """Build a ``DriftMonitor`` from the active bundle's baselines,
        register it on BOTH serving paths (batch observer for JSON rows,
        column observer for packed columnar bodies), and export its gauges
        through this engine's registry (→ ``/metrics``).  Returns the
        monitor, or ``None`` (recorded as a degradation) when the bundle
        carries no ``baselines.json``."""
        from ..lifecycle.drift import DriftMonitor
        with self._swap_lock:
            entry = self._entry
        monitor = DriftMonitor.for_model(entry.model, registry=self.metrics,
                                         **kw)
        if monitor is None:
            return None
        self.drift_monitor = monitor
        self.add_batch_observer(monitor.observe_serving)
        self.add_column_observer(monitor.observe_columnar)
        return monitor

    def detach_drift_monitor(self) -> None:
        """Unregister the attached drift monitor from both observer lists
        (the tenant eviction/quarantine path: a closed engine's monitor
        must stop publishing gauges the registry would keep scraping).
        Idempotent; no-op when none is attached."""
        monitor, self.drift_monitor = self.drift_monitor, None
        if monitor is None:
            return
        self._batch_observers = [
            fn for fn in self._batch_observers
            if getattr(fn, "__self__", None) is not monitor]
        self._column_observers = [
            fn for fn in self._column_observers
            if getattr(fn, "__self__", None) is not monitor]

    def reload_now(self) -> bool:
        """Check the checkpoint root once; swap if a newer valid version
        exists.  Returns True when a swap happened (also used by tests —
        the watcher thread calls exactly this)."""
        if is_bundle_dir(self.model_location):
            return False         # fixed single bundle: nothing to watch
        try:
            latest = find_latest_valid(self.model_location)
        except Exception as e:  # noqa: BLE001 — root may be mid-write
            record_failure("serving", "skipped", e, point="serving.reload")
            return False
        with self._swap_lock:
            current = self._entry.version
        if bundle_version(latest) == current:
            return False
        breaker = self.overload.reload_breaker
        if not breaker.allow():
            # repeated corrupt/faulty candidates opened the breaker: stop
            # re-verifying and re-loading the same bundle on every watcher
            # poll; the next probe is granted after reset_timeout_s
            self.metrics.counter("reload_breaker_skipped_total").inc()
            record_failure(
                "serving", "skipped",
                f"reload breaker open; next probe in "
                f"{breaker.retry_after_s():.1f}s",
                point="serving.reload", bundle=latest)
            return False
        try:
            maybe_inject("serving.reload", key=bundle_version(latest))
            entry = self._load_entry(latest)
        except Exception as e:  # noqa: BLE001 — keep serving the old model
            breaker.record_failure(e)
            record_failure("serving", "skipped", e, point="serving.reload",
                           bundle=latest)
            return False
        # warm the NEW model's programs before it becomes visible: requests
        # never wait on a compile, and the trace accounting stays attributed
        # to warmup (the no-online-recompile invariant survives the swap)
        if self._compiled_ok:
            self._warm(entry)
        with self._swap_lock:
            old = self._entry.version
            self._entry = entry
        breaker.record_success()
        self.metrics.counter("reloads_total").inc()
        record_failure("serving", "reloaded", None, point="serving.reload",
                       previous=old, current=entry.version)
        if self.drift_monitor is not None:
            # the swapped-in model brings its own training baselines: the
            # monitor rebases onto them and starts a fresh window
            try:
                self.drift_monitor.rebase_to_model(entry.model)
            except Exception as e:  # noqa: BLE001 — monitoring must not
                #                     fail a successful swap
                record_failure("serving", "swallowed", e,
                               point="serving.reload")
        return True

    def _watch_loop(self) -> None:
        while not self._closed:
            time.sleep(self.reload_poll_s)
            if self._closed:
                return
            try:
                self.reload_now()
            except Exception as e:  # noqa: BLE001 — the watcher must survive
                record_failure("serving", "swallowed", e,
                               point="serving.reload")

    # -- the data-quality firewall (pre-queue) -----------------------------
    def _quarantine(self, violations: List[Violation],
                    ctx: Optional[TraceContext],
                    point: str = "serving.quality",
                    rows: int = 1) -> RecordQualityError:
        """Account ``rows`` quarantined records and build their typed
        error.  Runs BEFORE submit, so poison never occupies a queue slot,
        never counts against admission, and never trips the compiled-path
        breaker — co-batched neighbors are structurally unaffected."""
        trace_id = ctx.trace_id if ctx else None
        err = RecordQualityError(violations, self.quality_policy)
        self.metrics.counter("quality.quarantined_records_total").inc(
            rows, trace_id=trace_id)
        # dead-letter parity with the streaming DLQ: same counter, same
        # FailureLog action, same trace-id correlation
        self.metrics.counter("dead_letter_total").inc(rows,
                                                      trace_id=trace_id)
        record_failure("serving", "quarantined", err, point=point,
                       trace_id=trace_id,
                       violations=[v.to_json() for v in violations[:4]])
        return err

    def _screen(self, record: Dict[str, Any],
                ctx: Optional[TraceContext]) -> Dict[str, Any]:
        """Validate one record against the active bundle's schema contract.
        Returns the (possibly coerced) record — the SAME dict object when
        nothing needed coercion — or raises ``RecordQualityError``."""
        policy = self.quality_policy
        if policy == "off":
            return record
        with self._swap_lock:
            entry = self._entry
        out, violations, rejected = entry.schema.screen_record(record,
                                                               policy)
        if violations:
            trace_id = ctx.trace_id if ctx else None
            self.metrics.counter("quality.violations_total").inc(
                len(violations), trace_id=trace_id)
            for v in violations:
                self.metrics.counter(
                    f"quality.violations_{v.kind}_total").inc()
            nonfinite = sum(1 for v in violations
                            if v.kind == NON_FINITE_VALUE)
            if nonfinite:
                self.metrics.counter("quality.nonfinite_inputs_total").inc(
                    nonfinite, trace_id=trace_id)
        if rejected:
            raise self._quarantine(violations, ctx)
        return out

    # -- public scoring API ------------------------------------------------
    def score_record(self, record: Dict[str, Any],
                     timeout_s: Optional[float] = None,
                     ctx: Optional[TraceContext] = None
                     ) -> Tuple[Dict[str, Any], str]:
        """Score one record; returns ``(result, model_version)``.  Blocks
        until the coalesced batch containing it completes, the engine
        closes, or ``timeout_s`` elapses (→ ``DeadlineExceeded``).
        ``ctx`` is the request's trace position: the dispatching batch
        span links back to it and latency/shed exemplars carry its
        trace id.  Raises ``RecordQualityError`` (→ HTTP 422) before the
        record ever reaches the queue when it fails the schema contract."""
        record = self._screen(record, ctx)
        req = self._submit(record, deadline_s=timeout_s, ctx=ctx)
        if not req.event.wait(timeout_s):
            raise DeadlineExceeded(
                f"no result within {timeout_s}s (queue depth "
                f"{self.queue_depth})")
        if req.error is not None:
            raise req.error
        self.request_latency.observe(time.perf_counter() - req.t_enqueue,
                                     trace_id=ctx.trace_id if ctx else None)
        self.metrics.counter("responses_total").inc()
        assert req.result is not None
        return req.result

    def score_records(self, records: List[Dict[str, Any]],
                      timeout_s: Optional[float] = None,
                      ctx: Optional[TraceContext] = None
                      ) -> List[Tuple[Dict[str, Any], str]]:
        """Score a client-provided list: every record rides the same queue
        as single requests (admission control applies to the whole list).
        Any record failing the schema contract rejects the list up front
        with a row-tagged violation list (``RecordQualityError``) — nothing
        is partially enqueued."""
        if self.quality_policy != "off":
            screened: List[Dict[str, Any]] = []
            bad: List[Violation] = []
            for i, rec in enumerate(records):
                try:
                    screened.append(self._screen(rec, ctx))
                except RecordQualityError as e:
                    for v in e.violations:
                        v.row = i
                    bad.extend(e.violations)
            if bad:
                raise RecordQualityError(bad, self.quality_policy)
            records = screened
        with self._cv:
            self._check_admission(extra=len(records), deadline_s=timeout_s,
                                  ctx=ctx)
            reqs = [_Request(r, ctx=ctx) for r in records]
            self._queue.extend(reqs)
            self._queued_rows += len(reqs)
            self.metrics.counter("requests_total").inc(len(reqs))
            self._cv.notify()
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        out = []
        for req in reqs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not req.event.wait(remaining):
                raise DeadlineExceeded(
                    f"no result within {timeout_s}s for list request")
            if req.error is not None:
                raise req.error
            self.request_latency.observe(
                time.perf_counter() - req.t_enqueue,
                trace_id=ctx.trace_id if ctx else None)
            self.metrics.counter("responses_total").inc()
            assert req.result is not None
            out.append(req.result)
        return out

    def score_columns(self, batch: ColumnBatch,
                      timeout_s: Optional[float] = None,
                      ctx: Optional[TraceContext] = None
                      ) -> Tuple[Dict[str, Any], str]:
        """Score a pre-assembled raw ``ColumnBatch`` (the columnar wire
        path).  Returns ``(result_arrays, model_version)`` where
        ``result_arrays`` is ``{name: (values, mask)}`` per
        ``wire.result_arrays``.  Admission control sees the batch as
        ``len(batch)`` rows."""
        n = len(batch)
        if n < 1:
            raise ValueError("columnar batch must have at least one row")
        if self.quality_policy != "off":
            # host→device seam: the assembled float columns are exactly what
            # ships to the device — reject rows carrying ±inf/NaN at present
            # positions (fatal under every policy) with a per-row violation
            # list instead of letting one poison row NaN the fused program
            with self._swap_lock:
                schema = self._entry.schema
            by_row = batch_nonfinite_rows(batch, schema)
            if by_row:
                trace_id = ctx.trace_id if ctx else None
                flat = [v for vs in by_row.values() for v in vs]
                self.metrics.counter("quality.violations_total").inc(
                    len(flat), trace_id=trace_id)
                self.metrics.counter(
                    f"quality.violations_{NON_FINITE_VALUE}_total").inc(
                    len(flat))
                self.metrics.counter("quality.nonfinite_inputs_total").inc(
                    len(by_row), trace_id=trace_id)
                raise self._quarantine(flat, ctx, rows=len(by_row))
        with self._cv:
            self._check_admission(extra=n, deadline_s=timeout_s, ctx=ctx)
            req = _ColumnarRequest(batch, ctx=ctx)
            self._queue.append(req)
            self._queued_rows += n
            self.metrics.counter("requests_total").inc(n)
            self._cv.notify()
        if not req.event.wait(timeout_s):
            raise DeadlineExceeded(
                f"no result within {timeout_s}s for columnar request of "
                f"{n} rows (queue depth {self.queue_depth})")
        if req.error is not None:
            raise req.error
        self.request_latency.observe(time.perf_counter() - req.t_enqueue,
                                     trace_id=ctx.trace_id if ctx else None)
        self.metrics.counter("responses_total").inc(n)
        assert req.result is not None
        return req.result

    @property
    def queue_depth(self) -> int:
        """Queued ROWS awaiting dispatch (a columnar request counts its
        full row span, so admission and Retry-After stay honest)."""
        return self._queued_rows

    @property
    def raw_features(self) -> Sequence:
        """The active model's raw feature schema (the wire decoder keys
        columnar bodies against it)."""
        with self._swap_lock:
            return self._entry.model.raw_features

    def _check_admission(self, extra: int = 1,
                         deadline_s: Optional[float] = None,
                         ctx: Optional[TraceContext] = None) -> None:
        if self._closed or self._draining:
            raise EngineClosed("engine is shutting down")
        est_bytes = None
        if self.overload.config.batch_bytes_budget is not None:
            # device-memory admission (ISSUE 15): estimate what the queue
            # would occupy on device with this request admitted.  The entry
            # is read without the swap lock — a stale width during a reload
            # race only skews an estimate, never correctness.
            from ..parallel.memory import estimate_batch_bytes
            width = len(getattr(self._entry.model, "raw_features",
                                ()) or ()) or 1
            est_bytes = estimate_batch_bytes(self._queued_rows + extra,
                                             width)
        decision = self.overload.admit(self._queued_rows, extra,
                                       deadline_s=deadline_s,
                                       est_bytes=est_bytes)
        if decision is not None:
            trace_id = ctx.trace_id if ctx else None
            self.metrics.counter("shed_total").inc(trace_id=trace_id)
            self.metrics.counter(f"shed_{decision.kind}_total").inc(
                trace_id=trace_id)
            detail: Dict[str, Any] = {"kind": decision.kind}
            if self.tenant:
                detail["tenant"] = self.tenant
            record_failure("serving", "shed", decision.message,
                           point="serving.admit", **detail)
            self.overload.refresh_health(
                queue_depth=self._queued_rows, draining=False,
                compiled_ok=self._compiled_ok)
            raise OverloadedError(decision.message,
                                  retry_after_s=decision.retry_after_s)

    def _submit(self, record: Dict[str, Any],
                deadline_s: Optional[float] = None,
                ctx: Optional[TraceContext] = None) -> _Request:
        with self._cv:
            self._check_admission(deadline_s=deadline_s, ctx=ctx)
            req = _Request(record, ctx=ctx)
            self._queue.append(req)
            self._queued_rows += 1
            self.metrics.counter("requests_total").inc()
            self._cv.notify()
        return req

    # -- the continuous micro-batcher --------------------------------------
    def _batch_loop(self) -> None:
        """Continuous batching: the instant the previous dispatch returns,
        drain whatever is queued NOW into one ladder-padded batch (up to
        ``max_batch``) and dispatch it.  No linger deadline — a lone
        request under light load dispatches immediately, and under load
        batches fill naturally because requests accumulate while the
        device is busy."""
        while True:
            columnar: Optional[_ColumnarRequest] = None
            batch: List[_Request] = []
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(0.05)
                if not self._queue:
                    if self._closed:
                        return
                    continue
                with span("serving.assemble") as sp:
                    head = self._queue.popleft()
                    if isinstance(head, _ColumnarRequest):
                        self._queued_rows -= head.rows
                        columnar = head
                    else:
                        batch.append(head)
                        self._queued_rows -= 1
                        while (len(batch) < self.max_batch and self._queue
                               and not isinstance(self._queue[0],
                                                  _ColumnarRequest)):
                            batch.append(self._queue.popleft())
                            self._queued_rows -= 1
                    if sp is not None:
                        sp.attrs["rows"] = (columnar.rows if columnar
                                            else len(batch))
            if columnar is not None:
                self._process_columnar(columnar)
            else:
                self._process(batch)

    def _process(self, batch: List[_Request]) -> None:
        # the batch span adopts the FIRST linked request's trace (so the
        # coalesced work shows up in that request's distributed trace) and
        # records links to EVERY request it serves — one dispatch, N
        # requests, all correlated
        links = [r.ctx for r in batch if r.ctx is not None]
        bctx = links[0].child() if links else None
        attrs = {"tenant": self.tenant} if self.tenant else {}
        with span("serving.batch", ctx=bctx, links=links, rows=len(batch),
                  **attrs):
            self._process_inner(batch, links=links)

    def _process_inner(self, batch: List[_Request],
                       links: Optional[List[TraceContext]] = None) -> None:
        with self._swap_lock:
            entry = self._entry
        records = [r.record for r in batch]
        t0 = time.perf_counter()
        results: Optional[List[Dict[str, Any]]] = None
        # the breaker gates the compiled path: while open, batches go
        # straight to the local fallback (no failure paid per batch); after
        # the reset timeout it grants half-open probes that either recover
        # the compiled path or re-open it
        use_compiled = self._compiled_ok \
            and self.overload.compiled_breaker.allow()
        if self._compiled_ok and not use_compiled:
            self.metrics.counter("breaker_demoted_batches_total").inc()
        if use_compiled:
            try:
                from ..compiled import trace_count
                with self._score_lock:
                    before = trace_count()
                    maybe_inject("serving.batch",
                                 key=int(self.metrics.counter("batches_total").value))
                    with span("serving.execute",
                              ctx=links[0].child() if links else None,
                              links=links, rows=len(records)):
                        results = run_with_deadline(
                            self._score_compiled, self.batch_deadline_s,
                            entry, records,
                            description=f"serving micro-batch of "
                                        f"{len(records)}")
                    traced = trace_count() - before
                self.overload.compiled_breaker.record_success()
                if traced > 0:
                    # an online trace means this model's frontier shapes are
                    # content-dependent (e.g. text wire arrays): every batch
                    # would recompile, so demote the engine to the local path
                    self.metrics.counter("online_traces_total").inc(traced)
                    self._compiled_ok = False
                    record_failure(
                        "serving", "degraded", None, point="serving.batch",
                        fallback="local row scoring",
                        detail=f"{traced} online trace(s) after warmup")
            except WatchdogTimeout as e:
                self.overload.compiled_breaker.record_failure(e)
                record_failure("serving", "fallback", e,
                               point="serving.batch",
                               fallback="local row scoring")
                self.metrics.counter("batch_deadline_total").inc()
                results = None
            except Exception as e:  # noqa: BLE001 — per-record fallback
                self.overload.compiled_breaker.record_failure(e)
                record_failure("serving", "fallback", e,
                               point="serving.batch",
                               fallback="local row scoring")
                results = None
        if results is None:
            self.metrics.counter("fallback_batches_total").inc()
            results = []
            for req, rec in zip(batch, records):
                try:
                    results.append(entry.local_fn(rec))
                except Exception as e:  # noqa: BLE001 — isolate bad records
                    # even the row-at-a-time fallback failed: this record is
                    # unservable by either path — a serving dead letter
                    trace_id = req.ctx.trace_id if req.ctx else None
                    self.metrics.counter("dead_letter_total").inc(
                        trace_id=trace_id)
                    record_failure("serving", "dead_letter", e,
                                   point="serving.batch", trace_id=trace_id)
                    results.append(e)
        if self.quality_policy != "off":
            # output firewall: a NaN/inf score dead-letters ITS row (422 to
            # that caller) instead of returning NaN; neighbors keep their
            # finite results.  Runs before observers so drift/insight
            # windows never ingest poison scores.
            for idx, (req, res) in enumerate(zip(batch, results)):
                if isinstance(res, BaseException):
                    continue
                bad = result_nonfinite_fields(res)
                if not bad:
                    continue
                trace_id = req.ctx.trace_id if req.ctx else None
                self.metrics.counter("quality.nonfinite_scores_total").inc(
                    trace_id=trace_id)
                self.metrics.counter("quality.violations_total").inc(
                    len(bad), trace_id=trace_id)
                self.metrics.counter(
                    f"quality.violations_{NON_FINITE_VALUE}_total").inc(
                    len(bad))
                self.metrics.counter("quality.quarantined_records_total"
                                     ).inc(trace_id=trace_id)
                self.metrics.counter("dead_letter_total").inc(
                    trace_id=trace_id)
                err = RecordQualityError(
                    [Violation(NON_FINITE_VALUE, f,
                               "model produced a non-finite score")
                     for f in bad], self.quality_policy)
                record_failure("serving", "quarantined", err,
                               point="serving.quality", trace_id=trace_id,
                               fields=bad[:4])
                results[idx] = err
        self.metrics.counter("batches_total").inc()
        self.metrics.counter("batch_rows_total").inc(len(batch))
        batch_s = time.perf_counter() - t0
        self.batch_latency.observe(batch_s)
        self.overload.observe_batch(batch_s)
        health = self.overload.refresh_health(
            queue_depth=self.queue_depth,
            draining=self._draining or self._closed,
            compiled_ok=self._compiled_ok)
        if self._batch_observers and health == BROWNOUT:
            # brownout sheds optional work first: observers (drift, record
            # insights, shadow scoring) are skipped so their cycles go to
            # draining the queue — user traffic is never the first casualty
            self.metrics.counter("brownout_sheds_total").inc()
        elif self._batch_observers:
            # before the waiters wake: a client that returns and immediately
            # inspects the drift monitor sees its own batch accounted for
            ok = [(req.record, res) for req, res in zip(batch, results)
                  if not isinstance(res, BaseException)]
            if ok:
                recs = [r for r, _ in ok]
                outs = [o for _, o in ok]
                for fn in list(self._batch_observers):
                    try:
                        fn(recs, outs)
                    except Exception as e:  # noqa: BLE001 — observers are
                        #                     observability, not the hot path
                        record_failure("serving", "swallowed", e,
                                       point="serving.batch")
        for req, res in zip(batch, results):
            if isinstance(res, BaseException):
                req.error = res
                self.metrics.counter("errors_total").inc()
            else:
                req.result = (res, entry.version)
            req.event.set()

    def _score_compiled(self, entry: _ModelEntry,
                        records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """One padded device dispatch: pad to the ladder, score through the
        fused program, slice the real rows back out."""
        n = len(records)
        size = next(s for s in self.ladder if s >= n)
        padded = records + [dict(self._warm_record)
                            for _ in range(size - n)]
        batch = records_to_batch(entry.model.raw_features, padded)
        scored = entry.model.score(batch=batch)
        return [_result_row(scored, entry.result_names, i)
                for i in range(n)]

    # -- the columnar path -------------------------------------------------
    @staticmethod
    def _slice_columns(batch: ColumnBatch, lo: int, hi: int) -> ColumnBatch:
        """Contiguous row window as zero-copy array views."""
        cols = {}
        for name, c in batch.items():
            mask = None if c.mask is None else c.mask[lo:hi]
            cols[name] = Column(c.kind, c.values[lo:hi], mask=mask,
                                meta=c.meta)
        return ColumnBatch(cols, hi - lo)

    @staticmethod
    def _pad_columns(batch: ColumnBatch, size: int) -> ColumnBatch:
        """Pad to a ladder rung by repeating the last row.  Scoring is
        row-independent and the padded rows are sliced off the result, so
        the pad content only has to be type-valid — the last real row is
        by construction."""
        n = len(batch)
        if size == n:
            return batch
        pad = size - n
        cols = {}
        for name, c in batch.items():
            vals = np.concatenate([c.values,
                                   np.repeat(c.values[-1:], pad, axis=0)])
            mask = None if c.mask is None else np.concatenate(
                [c.mask, np.repeat(c.mask[-1:], pad)])
            cols[name] = Column(c.kind, vals, mask=mask, meta=c.meta)
        return ColumnBatch(cols, size)

    def _score_columns_compiled(self, entry: _ModelEntry, chunk: ColumnBatch
                                ) -> Dict[str, Any]:
        from .wire import result_arrays
        n = len(chunk)
        size = next(s for s in self.ladder if s >= n)
        scored = entry.model.score(batch=self._pad_columns(chunk, size))
        return result_arrays(scored, entry.result_names, n)

    def _local_fallback_columns(self, entry: _ModelEntry, chunk: ColumnBatch,
                                ctx: Optional[TraceContext] = None
                                ) -> Dict[str, Any]:
        """Row-at-a-time local scoring for a columnar chunk the compiled
        path could not handle.  A row that fails even here is a dead
        letter and fails the whole columnar request (arrays cannot carry a
        per-row exception)."""
        rows = []
        for i in range(len(chunk)):
            rec = {name: ft.value for name, ft in chunk.row(i).items()}
            try:
                row = entry.local_fn(rec)
            except Exception as e:  # same dead-letter accounting as the
                #                     JSON path: counter + FailureLog action,
                #                     both carrying the request's trace id
                trace_id = ctx.trace_id if ctx else None
                self.metrics.counter("dead_letter_total").inc(
                    trace_id=trace_id)
                record_failure("serving", "dead_letter", e,
                               point="serving.batch", row=i,
                               trace_id=trace_id)
                raise
            flat: Dict[str, Any] = {}
            for name, v in row.items():
                if isinstance(v, dict):
                    for k2, v2 in v.items():
                        flat[f"{name}.{k2}"] = v2
                else:
                    flat[name] = v
            rows.append(flat)
        keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        out: Dict[str, Any] = {}
        for k in keys:
            vals = [r.get(k) for r in rows]
            if any(isinstance(v, str) for v in vals):
                out[k] = (np.array(vals, dtype=object), None)
            else:
                mask = np.array([v is not None for v in vals], dtype=bool)
                arr = np.array([0.0 if v is None else float(v)
                                for v in vals], dtype=np.float64)
                out[k] = (arr, None if mask.all() else mask)
        return out

    def _process_columnar(self, req: _ColumnarRequest) -> None:
        links = [req.ctx] if req.ctx is not None else []
        attrs = {"tenant": self.tenant} if self.tenant else {}
        with span("serving.batch", ctx=links[0].child() if links else None,
                  links=links, rows=req.rows, columnar=True, **attrs):
            try:
                self._process_columnar_inner(req)
            except BaseException as e:  # noqa: BLE001 — fail the request,
                #                         never the batcher thread
                self.metrics.counter("errors_total").inc()
                req.error = e
                req.event.set()

    def _process_columnar_inner(self, req: _ColumnarRequest) -> None:
        from .wire import concat_result_arrays
        links = [req.ctx] if req.ctx is not None else []
        with self._swap_lock:
            entry = self._entry
        chunks: List[Dict[str, Any]] = []
        health = None
        for lo in range(0, req.rows, self.max_batch):
            hi = min(lo + self.max_batch, req.rows)
            chunk = self._slice_columns(req.batch, lo, hi)
            t0 = time.perf_counter()
            arrays: Optional[Dict[str, Any]] = None
            use_compiled = self._compiled_ok \
                and self.overload.compiled_breaker.allow()
            if self._compiled_ok and not use_compiled:
                self.metrics.counter("breaker_demoted_batches_total").inc()
            if use_compiled:
                try:
                    from ..compiled import trace_count
                    with self._score_lock:
                        before = trace_count()
                        maybe_inject(
                            "serving.batch",
                            key=int(self.metrics.counter(
                                "batches_total").value))
                        with span("serving.execute",
                                  ctx=(links[0].child() if links
                                       else None),
                                  links=links, rows=hi - lo,
                                  columnar=True):
                            arrays = run_with_deadline(
                                self._score_columns_compiled,
                                self.batch_deadline_s, entry, chunk,
                                description=f"serving columnar chunk of "
                                            f"{hi - lo}")
                        traced = trace_count() - before
                    self.overload.compiled_breaker.record_success()
                    if traced > 0:
                        self.metrics.counter("online_traces_total").inc(
                            traced)
                        self._compiled_ok = False
                        record_failure(
                            "serving", "degraded", None,
                            point="serving.batch",
                            fallback="local row scoring",
                            detail=f"{traced} online trace(s) after warmup"
                                   " (columnar)")
                except WatchdogTimeout as e:
                    self.overload.compiled_breaker.record_failure(e)
                    record_failure("serving", "fallback", e,
                                   point="serving.batch",
                                   fallback="local row scoring")
                    self.metrics.counter("batch_deadline_total").inc()
                    arrays = None
                except Exception as e:  # noqa: BLE001 — row fallback
                    self.overload.compiled_breaker.record_failure(e)
                    record_failure("serving", "fallback", e,
                                   point="serving.batch",
                                   fallback="local row scoring")
                    arrays = None
            if arrays is None:
                self.metrics.counter("fallback_batches_total").inc()
                arrays = self._local_fallback_columns(entry, chunk,
                                                      ctx=req.ctx)
            if self.quality_policy != "off":
                # columnar output firewall: arrays cannot carry a per-row
                # exception, so non-finite score cells are masked ABSENT
                # (the wire's null convention) and counted — the caller
                # sees null for the poisoned row, finite scores elsewhere
                arrays, bad_rows = mask_nonfinite_result_arrays(arrays)
                nbad = int(np.asarray(bad_rows).sum())
                if nbad:
                    trace_id = req.ctx.trace_id if req.ctx else None
                    self.metrics.counter(
                        "quality.nonfinite_scores_total").inc(
                        nbad, trace_id=trace_id)
                    self.metrics.counter("dead_letter_total").inc(
                        nbad, trace_id=trace_id)
                    record_failure(
                        "serving", "quarantined",
                        f"{nbad} non-finite score row(s) masked absent",
                        point="serving.quality", trace_id=trace_id,
                        rows=[int(i) + lo for i in
                              np.nonzero(np.asarray(bad_rows))[0][:8]])
            self.metrics.counter("batches_total").inc()
            self.metrics.counter("batch_rows_total").inc(hi - lo)
            batch_s = time.perf_counter() - t0
            self.batch_latency.observe(batch_s)
            self.overload.observe_batch(batch_s)
            health = self.overload.refresh_health(
                queue_depth=self.queue_depth,
                draining=self._draining or self._closed,
                compiled_ok=self._compiled_ok)
            chunks.append(arrays)
        merged = concat_result_arrays(chunks)
        if self._column_observers and health == BROWNOUT:
            # same shed rule as the JSON path: under brownout, observer
            # cycles go to draining the queue
            self.metrics.counter("brownout_sheds_total").inc()
        elif self._column_observers:
            # column observers (drift) consume the ColumnBatch + packed
            # result arrays directly — columnar traffic is observed with
            # zero per-record dict materialization
            for fn in list(self._column_observers):
                try:
                    fn(req.batch, merged)
                except Exception as e:  # noqa: BLE001 — observers are
                    #                     observability, not the hot path
                    record_failure("serving", "swallowed", e,
                                   point="serving.batch")
        elif self._batch_observers:
            # batch observers with no columnar twin still consume
            # per-record dicts; reconstructing those would put per-row
            # Python back on the hot path, so they are skipped and the
            # skipped rows counted
            self.metrics.counter("columnar_observer_skips_total").inc(
                req.rows)
        req.result = (merged, entry.version)
        req.event.set()

    # -- metrics / shutdown ------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._swap_lock:
            version = self._entry.version
            aot_execs = getattr(self._entry.model, "aot_executables", 0)
        return {"counters": self.metrics.counters(),
                "queue_depth": self.queue_depth,
                "tenant": self.tenant,
                "quality_policy": self.quality_policy,
                "quality_quarantine_fraction":
                    self.quality_quarantine_fraction,
                "model_version": version,
                "aot_executables": aot_execs,
                "compiled_path_active": self._compiled_ok,
                "overload": self.overload.snapshot(),
                "request_latency": self.request_latency.snapshot(),
                "batch_latency": self.batch_latency.snapshot()}

    def close(self, drain: bool = True,
              timeout_s: Optional[float] = 30.0) -> None:
        """Stop accepting requests; with ``drain`` the batcher finishes
        everything already queued before the thread exits (the SIGTERM
        path — ``preemption_guard`` delivers the signal, the server calls
        this)."""
        self.overload.refresh_health(queue_depth=self.queue_depth,
                                     draining=True,
                                     compiled_ok=self._compiled_ok)
        with self._cv:
            self._draining = True
            if not drain:
                for req in self._queue:
                    req.error = EngineClosed("engine closed before scoring")
                    req.event.set()
                self._queue.clear()
                self._queued_rows = 0
            self._cv.notify_all()
        if drain:
            deadline = (time.monotonic() + timeout_s
                        if timeout_s is not None else None)
            while self._queue:
                if deadline is not None and time.monotonic() > deadline:
                    break
                time.sleep(0.005)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._batcher.join(timeout=5.0)
