"""Packed columnar wire format for the scoring endpoint.

``Content-Type: application/x-transmogrifai-columnar`` — a little-endian
binary body carrying one contiguous array per feature, so the server builds
its device ``ColumnBatch`` with one ``np.frombuffer`` view per feature
instead of per-record JSON dict decode (the single-process throughput
ceiling BENCH_STANDING documented across five rounds).  JSON remains the
compatibility path; this format is opt-in per request.

Layout (all integers little-endian)::

    header   (16 bytes)
      0   4   magic               b"TMGC"
      4   2   version    u16      1
      6   2   flags      u16      reserved, must be 0
      8   4   n_rows     u32
      12  4   n_features u32
    then n_features descriptors, each:
      0   2   name_len   u16
      2   -   name       utf-8 (name_len bytes)
      +0  1   dtype      u8       1=f32  2=f64  3=i64  4=bool(u8)  5=utf8
      +1  1   col_flags  u8       bit0: a presence bitmap follows the values
      +2  4   payload_nbytes u32  bytes of the VALUES payload
    then the payload section: per feature, in descriptor order,
      - values payload, starting at the next 8-byte boundary
        (numeric: n_rows * itemsize; utf8: (n_rows+1) u32 offsets + blob),
      - if col_flags bit0: ceil(n_rows/8) presence-bitmap bytes
        (``np.packbits(..., bitorder="little")`` — bit i set = row i present).

Decode semantics mirror ``columns.numeric_column`` / ``text_column``
exactly (NaN/0/False at absent rows, empty string → None, non-nullable
kinds reject absent rows) so the columnar and JSON paths produce
bitwise-identical scores — the parity tests pin this.

Every malformed input raises :class:`WireFormatError`; the HTTP layer maps
it to a structured 400.  A worker never crashes on a bad body.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columns import Column, ColumnBatch, column_from_values
from ..stages.generator import non_nullable_empty_value
from ..types import (Binary, Date, DateTime, Integral, Prediction,
                     is_numeric_kind, is_text_kind)

CONTENT_TYPE = "application/x-transmogrifai-columnar"

MAGIC = b"TMGC"
VERSION = 1

F32, F64, I64, BOOL, UTF8 = 1, 2, 3, 4, 5
_NUMERIC_DTYPES = {F32: np.dtype("<f4"), F64: np.dtype("<f8"),
                   I64: np.dtype("<i8"), BOOL: np.dtype("u1")}
_CODE_NAMES = {F32: "f32", F64: "f64", I64: "i64", BOOL: "bool",
               UTF8: "utf8"}

_HEADER = struct.Struct("<4sHHII")
_DESC_TAIL = struct.Struct("<BBI")

# hard ceilings so a malformed header cannot make the server allocate
# unbounded memory before validation fails
MAX_ROWS = 16_000_000
MAX_FEATURES = 10_000
_MAX_NAME = 4096


class WireFormatError(ValueError):
    """The columnar body is malformed or unsupported (HTTP 400).

    ``violation_kind`` carries the data-quality taxonomy kind
    (quality.py) when the decoder could classify the problem — structural
    corruption (truncated body, bad magic) stays unclassified."""

    violation_kind: Optional[str] = None


def _typed_wire_error(message: str, kind: str) -> WireFormatError:
    err = WireFormatError(message)
    err.violation_kind = kind
    return err


def _align8(n: int) -> int:
    return (n + 7) & ~7


# --------------------------------------------------------------------------
# encode
# --------------------------------------------------------------------------

def _utf8_payload(values: Sequence) -> bytes:
    """Object array of str|None → u32 offsets (n+1) + utf-8 blob.  ``None``
    encodes as a zero-length entry; presence is the mask's job."""
    chunks: List[bytes] = []
    offsets = np.zeros(len(values) + 1, dtype="<u4")
    pos = 0
    for i, v in enumerate(values):
        b = b"" if v is None else str(v).encode("utf-8")
        chunks.append(b)
        pos += len(b)
        offsets[i + 1] = pos
    return offsets.tobytes() + b"".join(chunks)


def encode_arrays(columns: Sequence[Tuple[str, int, Any, Optional[Any]]],
                  n_rows: int) -> bytes:
    """Low-level encoder: ``columns`` is an ordered sequence of
    ``(name, dtype_code, values, mask_or_None)``.  Numeric values may be
    any array-like; they are cast to the wire dtype.  UTF8 values are a
    sequence of ``str | None``."""
    n_rows = int(n_rows)
    parts: List[bytes] = []
    descs: List[bytes] = []
    payloads: List[Tuple[bytes, Optional[bytes]]] = []
    for name, code, values, mask in columns:
        name_b = str(name).encode("utf-8")
        if code == UTF8:
            vals = list(values)
            if len(vals) != n_rows:
                raise WireFormatError(
                    f"column {name!r} has {len(vals)} rows, header says "
                    f"{n_rows}")
            payload = _utf8_payload(vals)
        elif code in _NUMERIC_DTYPES:
            arr = np.asarray(values)
            if arr.shape != (n_rows,):
                raise WireFormatError(
                    f"column {name!r} has shape {arr.shape}, want "
                    f"({n_rows},)")
            payload = np.ascontiguousarray(
                arr.astype(_NUMERIC_DTYPES[code], copy=False)).tobytes()
        else:
            raise WireFormatError(f"unknown dtype code {code} for {name!r}")
        mask_b: Optional[bytes] = None
        if mask is not None:
            m = np.asarray(mask, dtype=bool)
            if m.shape != (n_rows,):
                raise WireFormatError(
                    f"mask for {name!r} has shape {m.shape}, want "
                    f"({n_rows},)")
            mask_b = np.packbits(m, bitorder="little").tobytes()
        descs.append(struct.pack("<H", len(name_b)) + name_b
                     + _DESC_TAIL.pack(code, 1 if mask_b is not None else 0,
                                       len(payload)))
        payloads.append((payload, mask_b))
    parts.append(_HEADER.pack(MAGIC, VERSION, 0, n_rows, len(payloads)))
    parts.extend(descs)
    pos = sum(len(p) for p in parts)
    for payload, mask_b in payloads:
        pad = _align8(pos) - pos
        parts.append(b"\x00" * pad)
        pos += pad
        parts.append(payload)
        pos += len(payload)
        if mask_b is not None:
            parts.append(mask_b)
            pos += len(mask_b)
    return b"".join(parts)


def _infer_code(values: Sequence) -> int:
    present = [v for v in values if v is not None]
    if any(isinstance(v, str) for v in present):
        return UTF8
    if present and all(isinstance(v, bool) for v in present):
        return BOOL
    if present and all(isinstance(v, int) for v in present):
        return I64
    return F64


def encode_records(records: Sequence[Dict[str, Any]],
                   codes: Optional[Dict[str, int]] = None) -> bytes:
    """Client-side convenience: the JSON-records shape, packed columnar.
    Column order is first-appearance order across records; dtypes are
    inferred (str → utf8, bool → bool, int → i64, else f64) unless pinned
    via ``codes``.  Absent keys ride the presence bitmap."""
    names: List[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)
    cols = []
    for name in names:
        vals = [r.get(name) for r in records]
        code = (codes or {}).get(name) or _infer_code(vals)
        mask = np.array([v is not None for v in vals], dtype=bool)
        if code == UTF8:
            cols.append((name, UTF8, vals, mask))
        elif code == BOOL:
            arr = np.array([bool(v) if v is not None else False
                            for v in vals], dtype=np.uint8)
            cols.append((name, BOOL, arr, mask))
        elif code == I64:
            arr = np.array([int(v) if v is not None else 0 for v in vals],
                           dtype=np.int64)
            cols.append((name, I64, arr, mask))
        else:
            arr = np.array([float(v) if v is not None else 0.0
                            for v in vals], dtype=np.float64)
            cols.append((name, code, arr, mask))
    return encode_arrays(cols, len(records))


# --------------------------------------------------------------------------
# decode
# --------------------------------------------------------------------------

def decode_columns(body: bytes
                   ) -> Tuple[int, "Dict[str, Tuple[int, Any, Optional[np.ndarray]]]"]:
    """Parse a columnar body → ``(n_rows, {name: (code, values, mask)})``.

    Numeric values are read-only ``np.frombuffer`` views over ``body`` (the
    zero-copy hot path); utf8 columns decode to object arrays of
    ``str | None`` (mask-aware).  Raises :class:`WireFormatError` on any
    structural problem — never anything else."""
    try:
        return _decode_columns(body)
    except WireFormatError:
        raise
    except (struct.error, ValueError, OverflowError, IndexError,
            UnicodeDecodeError) as e:
        raise WireFormatError(f"truncated or corrupt columnar body: {e}") \
            from e


def _decode_columns(body: bytes):
    if len(body) < _HEADER.size:
        raise WireFormatError(
            f"body of {len(body)} bytes is shorter than the {_HEADER.size}"
            "-byte header")
    magic, version, flags, n_rows, n_features = _HEADER.unpack_from(body, 0)
    if magic != MAGIC:
        raise WireFormatError(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != VERSION:
        raise WireFormatError(f"unsupported wire version {version} "
                              f"(this server speaks {VERSION})")
    if flags != 0:
        raise WireFormatError(f"reserved header flags set: {flags:#x}")
    if n_rows > MAX_ROWS:
        raise WireFormatError(f"n_rows {n_rows} exceeds the {MAX_ROWS} cap")
    if n_features > MAX_FEATURES:
        raise WireFormatError(
            f"n_features {n_features} exceeds the {MAX_FEATURES} cap")
    pos = _HEADER.size
    descs: List[Tuple[str, int, int, int]] = []
    for _ in range(n_features):
        if pos + 2 > len(body):
            raise WireFormatError("descriptor table runs past the body")
        (name_len,) = struct.unpack_from("<H", body, pos)
        pos += 2
        if name_len > _MAX_NAME or pos + name_len + _DESC_TAIL.size > len(body):
            raise WireFormatError("feature name runs past the body")
        name = body[pos:pos + name_len].decode("utf-8")
        pos += name_len
        code, col_flags, nbytes = _DESC_TAIL.unpack_from(body, pos)
        pos += _DESC_TAIL.size
        if code not in (F32, F64, I64, BOOL, UTF8):
            raise WireFormatError(f"unknown dtype code {code} for {name!r}")
        if col_flags & ~1:
            raise WireFormatError(
                f"reserved column flags set for {name!r}: {col_flags:#x}")
        descs.append((name, code, col_flags, nbytes))
    mask_nbytes = (n_rows + 7) // 8
    out: Dict[str, Tuple[int, Any, Optional[np.ndarray]]] = {}
    for name, code, col_flags, nbytes in descs:
        pos = _align8(pos)
        end = pos + nbytes + (mask_nbytes if col_flags & 1 else 0)
        if end > len(body):
            raise WireFormatError(
                f"payload of {name!r} runs past the body "
                f"({end} > {len(body)})")
        if code == UTF8:
            off_nbytes = (n_rows + 1) * 4
            if nbytes < off_nbytes:
                raise WireFormatError(
                    f"utf8 column {name!r}: payload {nbytes}B cannot hold "
                    f"{n_rows + 1} u32 offsets")
            offsets = np.frombuffer(body, dtype="<u4", count=n_rows + 1,
                                    offset=pos)
            blob = body[pos + off_nbytes:pos + nbytes]
            if offsets[0] != 0 or np.any(np.diff(offsets.astype(np.int64))
                                         < 0) or offsets[-1] > len(blob):
                raise WireFormatError(
                    f"utf8 column {name!r}: offsets are not monotonically "
                    "increasing within the blob")
            values: Any = np.empty(n_rows, dtype=object)
            for i in range(n_rows):
                lo, hi = int(offsets[i]), int(offsets[i + 1])
                values[i] = (blob[lo:hi].decode("utf-8")
                             if hi > lo else None)
        else:
            dt = _NUMERIC_DTYPES[code]
            if nbytes != n_rows * dt.itemsize:
                raise WireFormatError(
                    f"column {name!r}: payload {nbytes}B != n_rows "
                    f"{n_rows} * {dt.itemsize}B ({_CODE_NAMES[code]})")
            values = np.frombuffer(body, dtype=dt, count=n_rows, offset=pos)
        mask: Optional[np.ndarray] = None
        if col_flags & 1:
            mask_buf = np.frombuffer(body, dtype=np.uint8, count=mask_nbytes,
                                     offset=pos + nbytes)
            mask = np.unpackbits(mask_buf, count=n_rows,
                                 bitorder="little").astype(bool)
        pos = end
        out[name] = (code, values, mask)
    return int(n_rows), out


def _numeric_cast(name, code, values, target: np.dtype, kind) -> np.ndarray:
    """Cast a wire array to the column storage dtype with exactly python's
    ``float()``/``int()``/``bool()`` coercion semantics (the JSON path)."""
    if code == UTF8:
        raise _typed_wire_error(
            f"column {name!r} is utf8 but feature kind {kind.__name__} "
            "is numeric", "TypeMismatch")
    if code == BOOL and np.any(values > 1):
        raise _typed_wire_error(
            f"bool column {name!r} carries bytes outside {{0, 1}}",
            "NonCoercibleValue")
    if values.dtype == target:
        return values
    with np.errstate(over="ignore"):
        # hostile i64 payloads may overflow the f64 cast to ±inf; the
        # non-finite seam guard downstream owns that verdict, not a warning
        return values.astype(target)


def decode_batch(body: bytes, raw_features: Sequence) -> ColumnBatch:
    """Columnar body → the raw ``ColumnBatch`` the engine scores, with the
    stage-0 semantics of ``records_to_batch`` (NaN/0/False at absent rows,
    monoid zero for non-nullable kinds missing from the wire, empty string
    → None) so the two request paths are bitwise parity-testable.

    Wire columns are keyed by RAW FEATURE NAME and carry already-extracted
    values — custom ``extract_fn`` hooks do not run on this path (the
    client did the extraction when it built the arrays)."""
    n_rows, cols = decode_columns(body)
    out: Dict[str, Column] = {}
    for f in raw_features:
        kind = f.kind
        wire = cols.get(f.name)
        if wire is None:
            # absent from the wire = absent from every record: nullable
            # kinds are all-None, non-nullable kinds take the monoid zero
            # (exactly extract_column over empty records)
            fill = (non_nullable_empty_value(kind)
                    if kind.non_nullable else None)
            out[f.name] = column_from_values(kind, [fill] * n_rows)
            continue
        code, values, mask = wire
        if is_text_kind(kind):
            if code != UTF8:
                raise _typed_wire_error(
                    f"column {f.name!r} is {_CODE_NAMES[code]} but feature "
                    f"kind {kind.__name__} is text", "TypeMismatch")
            vals = values
            if mask is not None and not mask.all():
                vals = values.copy()
                vals[~mask] = None
            out[f.name] = Column(kind, vals)
            continue
        if not is_numeric_kind(kind):
            raise WireFormatError(
                f"feature {f.name!r} of kind {kind.__name__} is not "
                "representable in columnar v1; use the JSON path")
        if issubclass(kind, (Date, DateTime)) or issubclass(kind, Integral):
            arr = _numeric_cast(f.name, code, values, np.dtype(np.int64),
                                kind)
            absent_fill: Any = 0
        elif issubclass(kind, Binary):
            if code != BOOL:
                raise _typed_wire_error(
                    f"column {f.name!r} is {_CODE_NAMES[code]} but "
                    f"{kind.__name__} wants bool (code {BOOL})",
                    "TypeMismatch")
            arr = _numeric_cast(f.name, code, values, np.dtype(np.bool_),
                                kind)
            absent_fill = False
        else:
            arr = _numeric_cast(f.name, code, values, np.dtype(np.float32),
                                kind)
            absent_fill = np.nan
        if kind.non_nullable:
            if mask is not None and not mask.all():
                bad = int((~mask).sum())
                raise _typed_wire_error(
                    f"{kind.__name__} column {f.name!r} has {bad} empty "
                    "values", "MissingRequiredField")
            out[f.name] = Column(kind, arr, mask=None)
            continue
        if mask is None:
            mask = np.ones(n_rows, dtype=bool)
        if not mask.all():
            arr = arr.copy()
            arr[~mask] = absent_fill
        out[f.name] = Column(kind, arr, mask=mask)
    return ColumnBatch(out, n_rows)


# --------------------------------------------------------------------------
# responses
# --------------------------------------------------------------------------

def result_arrays(scored: ColumnBatch, names: Sequence[str], n: int
                  ) -> "Dict[str, Tuple[Any, Optional[np.ndarray]]]":
    """Flatten the scored result columns to wire-encodable arrays for the
    first ``n`` (un-padded) rows.  Prediction columns flatten to
    ``<name>.prediction`` / ``<name>.probability_<j>`` /
    ``<name>.rawPrediction_<j>`` f64 columns — the same keys the JSON
    ``_result_row`` emits, dot-joined."""
    out: Dict[str, Tuple[Any, Optional[np.ndarray]]] = {}
    for name in names:
        if name not in scored:
            continue
        col = scored[name]
        if col.kind is Prediction or isinstance(col.values, dict):
            out[f"{name}.prediction"] = (
                np.asarray(col.values["prediction"])[:n].astype(np.float64),
                None)
            for base in ("probability", "rawPrediction"):
                if base in col.values:
                    block = np.asarray(col.values[base])[:n]
                    for j in range(block.shape[1]):
                        out[f"{name}.{base}_{j}"] = (
                            block[:, j].astype(np.float64), None)
        elif col.is_host_object():
            out[name] = (np.asarray(col.values)[:n], None)
        else:
            mask = (None if col.mask is None
                    else np.asarray(col.mask)[:n].astype(bool))
            out[name] = (np.asarray(col.values)[:n].astype(np.float64),
                         mask)
    return out


def concat_result_arrays(chunks: "List[Dict[str, Tuple[Any, Optional[np.ndarray]]]]"
                         ) -> "Dict[str, Tuple[Any, Optional[np.ndarray]]]":
    """Concatenate per-chunk result arrays (the batcher splits oversized
    columnar requests into ladder-sized device dispatches)."""
    if len(chunks) == 1:
        return chunks[0]
    out: Dict[str, Tuple[Any, Optional[np.ndarray]]] = {}
    for name in chunks[0]:
        vals = np.concatenate([c[name][0] for c in chunks])
        masks = [c[name][1] for c in chunks]
        mask = (None if any(m is None for m in masks)
                else np.concatenate(masks))
        out[name] = (vals, mask)
    return out


def encode_result_arrays(arrays: "Dict[str, Tuple[Any, Optional[np.ndarray]]]",
                         n_rows: int) -> bytes:
    """Result arrays → columnar response body (f64 for numerics, utf8 for
    host-object columns)."""
    cols = []
    for name, (vals, mask) in arrays.items():
        arr = np.asarray(vals)
        if arr.dtype == object:
            cols.append((name, UTF8, arr,
                         np.array([v is not None for v in arr], dtype=bool)))
        else:
            cols.append((name, F64, arr.astype(np.float64), mask))
    return encode_arrays(cols, n_rows)


def decode_response(body: bytes
                    ) -> "Dict[str, Tuple[Any, Optional[np.ndarray]]]":
    """Client-side: columnar response body → ``{name: (values, mask)}``."""
    _n, cols = decode_columns(body)
    return {name: (values, mask) for name, (code, values, mask)
            in cols.items()}
