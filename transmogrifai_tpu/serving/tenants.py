"""TenantRegistry — one serving process, many bundles, bulkheaded.

The reference's ``local`` module was designed to run many serialized
workflow models side by side in one process; this is that layer for the
TPU serving plane, built as a robustness feature (ROADMAP item 4): the
hundredth model must not be able to take down the first.

* **Layout.** ``--model-root`` is a directory whose immediate
  subdirectories are tenants; each tenant directory is a single verified
  bundle or a checkpoint root of ``ckpt-NNNNNN`` versions (exactly the
  ``--model-location`` contract, once per tenant — newest valid version
  serves, digest-checked via ``checkpoint.find_latest_valid``).
* **Bulkheads.** Every active tenant owns a full ``ScoringEngine``:
  its own queue, continuous batcher, adaptive admission limit, shed
  budget, and compiled-path + reload ``CircuitBreaker``s (scoped
  ``serving.batch@<tenant>`` / ``serving.reload@<tenant>``).  A hot
  tenant exhausts *its* admission budget and gets 429s; nothing it does
  moves another tenant's limits or breakers.
* **Quarantine.** A tenant whose bundle fails digest/ABI verification at
  activation — or whose reload breaker is OPEN (a poison candidate
  stream) — is parked ``QUARANTINED``: requests get a typed
  ``TenantQuarantinedError`` (HTTP 503 + honest ``Retry-After``), and
  re-probes follow the deterministic backoff of a
  ``resilience.RetryPolicy`` (attempt-indexed, keyed by tenant).  A
  probe that loads a now-valid bundle reactivates the tenant; other
  tenants never notice either way.
* **LRU activation under the device-memory budget (PR 15).**  Cold
  tenants activate on first request (AOT bundles deserialize shipped
  executables → zero-compile first score).  Each active entry is charged
  an ``estimate_batch_bytes(max_batch, feature_width)`` footprint
  against ``device_memory_budget()`` (or an explicit byte budget /
  ``max_active`` count cap); admitting a new tenant past the budget
  evicts the coldest active entry first, with a ``tenant.evicted``
  FailureLog action.

State machine per tenant::

    INACTIVE --activate ok--> ACTIVE --reload breaker OPEN--+
        ^  ^                     |                          |
        |  +----- evicted (LRU) -+                          v
        +-- probe ok ------------------------------- QUARANTINED
                                                  (backoff re-probe)

Thread safety: one registry lock guards the tenant table and every state
transition (activation, probe, eviction, quarantine).  Steady-state
lookups are a dict hit + timestamp; a cold activation briefly serializes
lookups, which is the price of never deadlocking across per-slot locks.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience import (CircuitBreaker, RetryPolicy, maybe_inject,
                          record_failure)
from ..telemetry import MetricsRegistry, span
from .engine import ScoringEngine
from .overload import OverloadConfig

# -- tenant states (mirrors the serving health ladder style) ----------------
TENANT_INACTIVE = "INACTIVE"        # known, not loaded (cold)
TENANT_ACTIVE = "ACTIVE"            # engine loaded and serving
TENANT_QUARANTINED = "QUARANTINED"  # bundle failed verification / reloads

TENANT_STATES = (TENANT_INACTIVE, TENANT_ACTIVE, TENANT_QUARANTINED)
TENANT_STATE_CODES = {TENANT_INACTIVE: 0, TENANT_ACTIVE: 1,
                      TENANT_QUARANTINED: 2}


class UnknownTenantError(KeyError):
    """No such tenant under the model root (HTTP 404 — a client naming a
    tenant that does not exist is a client error, not a server state)."""

    def __init__(self, tenant: str, known: List[str]):
        super().__init__(tenant)
        self.tenant = tenant
        self.known = list(known)

    def __str__(self) -> str:
        return (f"unknown tenant {self.tenant!r} "
                f"({len(self.known)} tenants registered)")


class TenantQuarantinedError(RuntimeError):
    """The tenant exists but is parked in QUARANTINED (HTTP 503 + honest
    ``Retry-After``): its bundle failed verification or its reload breaker
    tripped.  ``retry_after_s`` is when the next re-probe is due."""

    def __init__(self, tenant: str, reason: str, retry_after_s: float):
        super().__init__(f"tenant {tenant!r} is quarantined: {reason}")
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = max(1.0, float(retry_after_s))


class _TenantSlot:
    """Registry-internal record for one tenant (guarded by the registry
    lock — never hand one out)."""

    __slots__ = ("tenant", "root", "state", "engine", "entry_bytes",
                 "last_used", "requests_total", "activations", "evictions",
                 "quarantines", "probes", "reactivations",
                 "quarantine_reason", "probe_attempt", "next_probe_at")

    def __init__(self, tenant: str, root: str):
        self.tenant = tenant
        self.root = root
        self.state = TENANT_INACTIVE
        self.engine: Optional[ScoringEngine] = None
        self.entry_bytes = 0
        self.last_used = 0.0          # monotonic; 0 = never used
        self.requests_total = 0
        self.activations = 0
        self.evictions = 0
        self.quarantines = 0
        self.probes = 0
        self.reactivations = 0
        self.quarantine_reason = ""
        self.probe_attempt = 0        # backoff index while quarantined
        self.next_probe_at = 0.0      # monotonic deadline for the re-probe


class TenantRegistry:
    """See module docstring.  ``engine_for(tenant)`` is the whole hot-path
    API; everything else is lifecycle, status and metrics."""

    def __init__(self, model_root: str, *, max_batch: int = 64,
                 queue_bound: int = 256,
                 batch_deadline_s: Optional[float] = 30.0,
                 reload_poll_s: float = 0.0, warm: bool = True,
                 overload: Optional[OverloadConfig] = None,
                 max_active: Optional[int] = None,
                 memory_budget_bytes: Optional[int] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 drift: bool = False,
                 engine_factory: Optional[Callable[..., ScoringEngine]]
                 = None):
        if not os.path.isdir(model_root):
            raise FileNotFoundError(f"model root {model_root!r} is not a "
                                    "directory")
        self.model_root = model_root
        self.max_batch = int(max_batch)
        self.queue_bound = int(queue_bound)
        self.batch_deadline_s = batch_deadline_s
        self.reload_poll_s = float(reload_poll_s)
        self.warm = warm
        self.overload = overload          # shared template; controllers are
        #                                   per-engine, so budgets are not
        self.max_active = (int(max_active) if max_active else None)
        if memory_budget_bytes is not None:
            self.memory_budget: Optional[int] = int(memory_budget_bytes)
        else:
            from ..parallel.memory import device_memory_budget
            self.memory_budget = device_memory_budget()
        # quarantine re-probe backoff: deterministic in (seed, tenant,
        # attempt) — the same corrupt tenant re-probes on the same honest
        # schedule on every host, and tests can predict Retry-After
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=10 ** 9, base_delay_s=2.0, max_delay_s=300.0,
            multiplier=2.0, jitter=0.1)
        self.drift = drift
        self._engine_factory = engine_factory or self._default_factory
        self.metrics = MetricsRegistry()
        self._lock = threading.RLock()
        self._slots: Dict[str, _TenantSlot] = {}
        self._closed = False
        self.scan()

    # -- discovery ---------------------------------------------------------
    def scan(self) -> List[str]:
        """Sync the tenant table with the model root's subdirectories:
        new directories appear as INACTIVE tenants, removed ones drop
        (closing their engine).  Returns the sorted tenant names."""
        try:
            names = sorted(
                d for d in os.listdir(self.model_root)
                if not d.startswith(".")
                and os.path.isdir(os.path.join(self.model_root, d)))
        except OSError as e:
            record_failure("serving", "skipped", e, point="serving.tenants",
                           detail="model root unreadable during scan")
            with self._lock:
                return sorted(self._slots)
        with self._lock:
            for name in names:
                if name not in self._slots:
                    self._slots[name] = _TenantSlot(
                        name, os.path.join(self.model_root, name))
            for name in list(self._slots):
                if name not in names:
                    slot = self._slots.pop(name)
                    if slot.engine is not None:
                        self._close_engine(slot)
                    record_failure("serving", "tenant.removed", None,
                                   point="serving.tenants", tenant=name)
            return sorted(self._slots)

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    # -- the hot path ------------------------------------------------------
    def engine_for(self, tenant: str) -> ScoringEngine:
        """The tenant's engine, activating (or re-probing) as needed.

        Raises ``UnknownTenantError`` (404) for a tenant the root does not
        contain, ``TenantQuarantinedError`` (503 + Retry-After) for one
        parked in quarantine."""
        with self._lock:
            if self._closed:
                from .engine import EngineClosed
                raise EngineClosed("tenant registry is closed")
            slot = self._slots.get(tenant)
            if slot is None:
                # a tenant directory created after startup is one cheap
                # rescan away — no restart needed to add a tenant
                self.scan()
                slot = self._slots.get(tenant)
            if slot is None:
                raise UnknownTenantError(tenant, sorted(self._slots))
            now = time.monotonic()
            if slot.state == TENANT_QUARANTINED:
                if now < slot.next_probe_at:
                    raise TenantQuarantinedError(
                        tenant, slot.quarantine_reason,
                        slot.next_probe_at - now)
                self._probe(slot)          # raises on a failed probe
            elif slot.state == TENANT_INACTIVE:
                self._activate(slot)       # raises via quarantine on fail
            else:
                brk = slot.engine.overload.reload_breaker
                if brk.current_state() == CircuitBreaker.OPEN:
                    # a poison candidate stream opened the reload breaker:
                    # park the tenant rather than serve an entry whose
                    # refresh path is known-broken
                    self._quarantine(
                        slot, "reload breaker open "
                        f"(next bundle probe was {brk.retry_after_s():.1f}s"
                        " away)")
                    raise TenantQuarantinedError(
                        tenant, slot.quarantine_reason,
                        slot.next_probe_at - time.monotonic())
            slot.last_used = time.monotonic()
            slot.requests_total += 1
            assert slot.engine is not None
            return slot.engine

    def peek_engine(self, tenant: str) -> Optional[ScoringEngine]:
        """The tenant's engine if (and only if) it is ACTIVE — never
        activates, never raises.  For observers (drift ranking, metrics)
        that must not perturb LRU state."""
        with self._lock:
            slot = self._slots.get(tenant)
            if slot is None or slot.state != TENANT_ACTIVE:
                return None
            return slot.engine

    # -- activation / eviction ---------------------------------------------
    def _default_factory(self, slot: _TenantSlot) -> ScoringEngine:
        return ScoringEngine(
            slot.root, max_batch=self.max_batch,
            queue_bound=self.queue_bound,
            batch_deadline_s=self.batch_deadline_s,
            reload_poll_s=self.reload_poll_s, warm=self.warm,
            overload=self.overload, tenant=slot.tenant)

    def _entry_bytes(self, engine: ScoringEngine) -> int:
        from ..parallel.memory import estimate_batch_bytes
        width = len(engine.raw_features or ()) or 1
        return int(estimate_batch_bytes(self.max_batch, width))

    def _activate(self, slot: _TenantSlot) -> None:
        t0 = time.perf_counter()
        try:
            maybe_inject("tenant.activate", key=slot.tenant)
            with span("serving.tenant_activate", tenant=slot.tenant):
                engine = self._engine_factory(slot)
        except Exception as e:  # noqa: BLE001 — corrupt bundle, missing
            #                     versions, ABI mismatch: all quarantine
            self._quarantine(slot, f"activation failed: {e}", cause=e)
            raise TenantQuarantinedError(
                slot.tenant, slot.quarantine_reason,
                slot.next_probe_at - time.monotonic())
        slot.engine = engine
        slot.entry_bytes = self._entry_bytes(engine)
        slot.state = TENANT_ACTIVE
        slot.last_used = time.monotonic()
        slot.activations += 1
        slot.probe_attempt = 0
        slot.quarantine_reason = ""
        if self.drift:
            try:
                engine.attach_drift_monitor()
            except Exception as e:  # noqa: BLE001 — monitoring must not
                #                     fail an activation
                record_failure("serving", "swallowed", e,
                               point="serving.tenants", tenant=slot.tenant)
        self.metrics.counter("tenant.activations_total").inc()
        # shared_executables: size of the process-wide loaded-executable
        # table (aot_registry) — two tenants of the same family x rung
        # converge on one entry, so this grows sub-linearly in tenants
        from ..aot_registry import loaded_count
        record_failure(
            "serving", "tenant.activated", None, point="serving.tenants",
            tenant=slot.tenant, version=engine.model_version,
            activation_s=round(time.perf_counter() - t0, 3),
            entry_bytes=slot.entry_bytes,
            shared_executables=loaded_count())
        self._enforce_budget(keep=slot)

    def _active_slots(self) -> List[_TenantSlot]:
        return [s for s in self._slots.values()
                if s.state == TENANT_ACTIVE]

    def _enforce_budget(self, keep: _TenantSlot) -> None:
        """Evict coldest-first until the active set fits both the count
        cap and the byte budget.  ``keep`` (the entry just activated) is
        never the victim — the request that paid for the activation gets
        to use it."""
        while True:
            active = self._active_slots()
            over_count = (self.max_active is not None
                          and len(active) > self.max_active)
            over_bytes = (self.memory_budget is not None
                          and sum(s.entry_bytes for s in active)
                          > self.memory_budget)
            if not (over_count or over_bytes):
                return
            victims = [s for s in active if s is not keep]
            if not victims:
                return  # a single entry over budget still serves
            self._evict(min(victims, key=lambda s: s.last_used),
                        "count cap" if over_count else "memory budget")

    def _evict(self, slot: _TenantSlot, why: str) -> None:
        idle_s = (time.monotonic() - slot.last_used
                  if slot.last_used else float("inf"))
        self._close_engine(slot)
        slot.state = TENANT_INACTIVE
        slot.evictions += 1
        self.metrics.counter("tenant.evictions_total").inc()
        record_failure("serving", "tenant.evicted", None,
                       point="serving.tenants", tenant=slot.tenant,
                       reason=why, idle_s=round(idle_s, 3),
                       entry_bytes=slot.entry_bytes)

    def _close_engine(self, slot: _TenantSlot,
                      timeout_s: float = 10.0) -> None:
        engine, slot.engine = slot.engine, None
        slot.entry_bytes = 0
        if engine is None:
            return
        try:
            # an evicted/quarantined tenant's drift monitor detaches with
            # the engine — its gauges leave /metrics instead of freezing
            # at the last pre-eviction window
            engine.detach_drift_monitor()
            engine.close(drain=True, timeout_s=timeout_s)
        except Exception as e:  # noqa: BLE001 — a wedged engine must not
            #                     wedge the registry
            record_failure("serving", "swallowed", e,
                           point="serving.tenants", tenant=slot.tenant)

    # -- quarantine --------------------------------------------------------
    def _quarantine(self, slot: _TenantSlot, reason: str,
                    cause: Any = None) -> None:
        self._close_engine(slot, timeout_s=5.0)
        slot.state = TENANT_QUARANTINED
        slot.quarantine_reason = reason
        slot.probe_attempt += 1
        delay = self.retry_policy.delay_for(slot.probe_attempt,
                                            key=slot.tenant)
        slot.next_probe_at = time.monotonic() + delay
        slot.quarantines += 1
        self.metrics.counter("tenant.quarantines_total").inc()
        record_failure("serving", "tenant.quarantined", cause or reason,
                       point="serving.tenants", tenant=slot.tenant,
                       attempt=slot.probe_attempt,
                       next_probe_s=round(delay, 3))

    def _probe(self, slot: _TenantSlot) -> None:
        """One quarantine re-probe: attempt a fresh verified activation.
        Success reactivates the tenant (this request serves normally);
        failure re-parks it one backoff step later."""
        slot.probes += 1
        self.metrics.counter("tenant.probes_total").inc()
        attempt = slot.probe_attempt
        try:
            maybe_inject("tenant.probe", key=slot.tenant)
            with span("serving.tenant_probe", tenant=slot.tenant,
                      attempt=attempt):
                engine = self._engine_factory(slot)
        except Exception as e:  # noqa: BLE001 — still broken: back off
            slot.probe_attempt = attempt + 1
            delay = self.retry_policy.delay_for(slot.probe_attempt,
                                                key=slot.tenant)
            slot.next_probe_at = time.monotonic() + delay
            slot.quarantine_reason = f"probe {attempt} failed: {e}"
            record_failure("serving", "tenant.quarantined", e,
                           point="serving.tenants", tenant=slot.tenant,
                           attempt=slot.probe_attempt,
                           next_probe_s=round(delay, 3))
            raise TenantQuarantinedError(slot.tenant,
                                         slot.quarantine_reason, delay)
        slot.engine = engine
        slot.entry_bytes = self._entry_bytes(engine)
        slot.state = TENANT_ACTIVE
        slot.last_used = time.monotonic()
        slot.activations += 1
        slot.reactivations += 1
        slot.probe_attempt = 0
        slot.quarantine_reason = ""
        if self.drift:
            try:
                engine.attach_drift_monitor()
            except Exception as e:  # noqa: BLE001
                record_failure("serving", "swallowed", e,
                               point="serving.tenants", tenant=slot.tenant)
        self.metrics.counter("tenant.reactivations_total").inc()
        record_failure("serving", "tenant.reactivated", None,
                       point="serving.tenants", tenant=slot.tenant,
                       version=engine.model_version, after_probes=attempt)
        self._enforce_budget(keep=slot)

    # -- status / metrics --------------------------------------------------
    def status(self) -> Dict[str, Any]:
        """Per-tenant state for ``/healthz`` and admin surfaces."""
        with self._lock:
            now = time.monotonic()
            tenants: Dict[str, Any] = {}
            for name in sorted(self._slots):
                s = self._slots[name]
                info: Dict[str, Any] = {
                    "state": s.state,
                    "requestsTotal": s.requests_total,
                    "activations": s.activations,
                    "evictions": s.evictions,
                    "entryBytes": s.entry_bytes,
                }
                if s.engine is not None:
                    info["modelVersion"] = s.engine.model_version
                    info["queueDepth"] = s.engine.queue_depth
                    info["health"] = \
                        s.engine.overload.health.snapshot()["state"]
                if s.state == TENANT_QUARANTINED:
                    info["quarantine"] = {
                        "reason": s.quarantine_reason,
                        "attempt": s.probe_attempt,
                        "nextProbeInS": round(
                            max(0.0, s.next_probe_at - now), 3),
                    }
                tenants[name] = info
            active = self._active_slots()
            return {"modelRoot": self.model_root,
                    "tenants": tenants,
                    "tenantsTotal": len(self._slots),
                    "tenantsActive": len(active),
                    "tenantsQuarantined": sum(
                        1 for s in self._slots.values()
                        if s.state == TENANT_QUARANTINED),
                    "activeBytes": sum(s.entry_bytes for s in active),
                    "memoryBudgetBytes": self.memory_budget,
                    "maxActive": self.max_active}

    def traffic_weights(self) -> Dict[str, int]:
        """Requests routed per tenant since startup — the weight the
        lifecycle retrain ranking uses."""
        with self._lock:
            return {name: s.requests_total
                    for name, s in self._slots.items()}

    def metrics_text(self) -> str:
        """Prometheus exposition: every active tenant's full engine
        families merged with a ``tenant`` label (aggregate + per-tenant
        samples, exactly the pool's ``worker_id`` merge semantics), plus
        registry-level tenant state/activation/eviction/quarantine
        families covering ALL tenants — quarantined and cold tenants are
        visible even though they have no engine to scrape."""
        from .pool import _METRIC_PREFIX, merge_worker_metrics
        from .server import render_metrics
        with self._lock:
            for s in self._active_slots():
                # refresh each active tenant's drift gauges at scrape time
                # so tenant-labeled drift_feature_psi / drift_score_psi
                # track the live window, not the last manual evaluate()
                mon = getattr(s.engine, "drift_monitor", None)
                if mon is not None and mon.rows_observed:
                    try:
                        mon.evaluate()
                    except Exception as e:  # noqa: BLE001 — a scrape must
                        #                     never fail on monitor state
                        record_failure("serving", "swallowed", e,
                                       point="serving.tenants",
                                       tenant=s.tenant)
            texts = [(s.tenant, render_metrics(s.engine))
                     for s in self._active_slots()]
            slots = [(name, self._slots[name])
                     for name in sorted(self._slots)]
            st = self.status()
        merged = merge_worker_metrics(texts, label="tenant") if texts else ""
        p = _METRIC_PREFIX
        lines = [
            f"# HELP {p}_tenant_state Tenant state: 0 INACTIVE / 1 ACTIVE "
            "/ 2 QUARANTINED",
            f"# TYPE {p}_tenant_state gauge"]
        from .pool import _escape_label_value as esc
        for name, s in slots:
            lines.append(f'{p}_tenant_state{{tenant="{esc(name)}"}} '
                         f'{TENANT_STATE_CODES[s.state]}')
        for fam, attr, help_ in (
                ("tenant_requests_total", "requests_total",
                 "Requests routed to this tenant"),
                ("tenant_activations_total", "activations",
                 "Cold/quarantine activations of this tenant's engine"),
                ("tenant_evictions_total", "evictions",
                 "LRU evictions of this tenant under the memory budget"),
                ("tenant_quarantines_total", "quarantines",
                 "Times this tenant entered quarantine"),
                ("tenant_probes_total", "probes",
                 "Quarantine re-probes attempted for this tenant")):
            lines.append(f"# HELP {p}_{fam} {help_}")
            lines.append(f"# TYPE {p}_{fam} counter")
            lines.append(f"{p}_{fam} "
                         f"{sum(getattr(s, attr) for _, s in slots)}")
            lines.extend(
                f'{p}_{fam}{{tenant="{esc(name)}"}} {getattr(s, attr)}'
                for name, s in slots)
        for fam, key, help_ in (
                ("tenants", "tenantsTotal", "Tenants under the model root"),
                ("tenants_active", "tenantsActive",
                 "Tenants with a loaded engine"),
                ("tenants_quarantined", "tenantsQuarantined",
                 "Tenants parked in quarantine"),
                ("tenant_active_bytes", "activeBytes",
                 "Estimated device bytes charged by active entries")):
            lines.append(f"# HELP {p}_{fam} {help_}")
            lines.append(f"# TYPE {p}_{fam} gauge")
            lines.append(f"{p}_{fam} {st[key]}")
        if self.memory_budget is not None:
            lines.append(f"# HELP {p}_tenant_memory_budget_bytes Device "
                         "memory budget the active set is charged against")
            lines.append(f"# TYPE {p}_tenant_memory_budget_bytes gauge")
            lines.append(f"{p}_tenant_memory_budget_bytes "
                         f"{self.memory_budget}")
        return merged + "\n".join(lines) + "\n"

    # -- shutdown ----------------------------------------------------------
    def close(self, timeout_s: float = 30.0) -> None:
        """Drain and close every active engine; the registry refuses new
        lookups afterwards.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for slot in self._slots.values():
                self._close_engine(slot, timeout_s=timeout_s)
                if slot.state == TENANT_ACTIVE:
                    slot.state = TENANT_INACTIVE
