"""Overload control plane for the serving stack.

The engine's original admission check was one number: queue depth vs. a
static ``queue_bound``.  That answers "is the queue full" but not "will
this request get an answer it can use" — a queue under its bound can still
be minutes deep when the backend slows, and a failing compiled path or a
corrupt hot-reload candidate retried in a tight loop degrades everything
with no recovery state.  This module is the control plane that closes
those gaps, built from the ``resilience`` primitives:

* ``AdaptiveConcurrencyLimit`` (AIMD on observed batch latency) is the
  default admission signal; ``queue_bound`` remains as the fallback
  ceiling above it.
* Queue-deadline shedding: from the batch-latency EWMA the controller
  estimates how long a new request would wait in queue; one that cannot
  meet its deadline is rejected *now* with an honest ``Retry-After``
  instead of timing out after the client already gave up.
* A ``CircuitBreaker`` around compiled batch execution demotes the engine
  to the ``local.score_function`` fallback while XLA keeps failing and
  re-probes for recovery (half-open) instead of paying the failure on
  every batch.
* A second breaker around hot-reload stops a corrupt/faulty candidate
  bundle from being re-verified and re-loaded on every watcher poll.
* ``HealthStateMachine`` — ``SERVING`` / ``DEGRADED`` / ``BROWNOUT`` /
  ``DRAINING`` — makes the degradation ladder explicit.  ``BROWNOUT``
  sheds *optional* work (drift observers, record insights, shadow
  scoring) before any user traffic is turned away beyond the admission
  limit.  States and transition reasons export through ``/healthz``,
  ``/readyz``, ``/metrics`` and telemetry events.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..resilience import AdaptiveConcurrencyLimit, CircuitBreaker
from ..telemetry import event

# -- health states (the degradation ladder, mildest first) ------------------
SERVING = "SERVING"      # compiled path healthy, all optional work runs
DEGRADED = "DEGRADED"    # user traffic OK, but on the local fallback path
BROWNOUT = "BROWNOUT"    # queue pressure: optional work shed, traffic kept
DRAINING = "DRAINING"    # shutting down: no new work accepted

HEALTH_STATES = (SERVING, DEGRADED, BROWNOUT, DRAINING)
HEALTH_CODES = {SERVING: 0, DEGRADED: 1, BROWNOUT: 2, DRAINING: 3}


@dataclass
class OverloadConfig:
    """Knobs for the serving overload control plane.

    Surfaced through ``servingParams`` (camelCase keys, see
    ``from_params``) and the ``serve`` CLI flags."""

    latency_target_ms: float = 50.0     # AIMD target for batch latency
    adaptive: bool = True               # False → static queue_bound only
    min_limit: int = 4                  # AIMD floor
    queue_deadline_ms: Optional[float] = None  # extra queue-wait budget cap
    brownout_high: float = 0.75         # queue/limit ratio entering BROWNOUT
    brownout_low: float = 0.50          # ratio that exits it (hysteresis)
    breaker_window: int = 16            # compiled-path breaker window
    breaker_failures: int = 3           # consecutive failures that open it
    breaker_rate: float = 0.5           # windowed failure-rate trip wire
    breaker_min_calls: int = 8          # min window size for the rate rule
    breaker_reset_s: float = 5.0        # open → half-open delay
    half_open_probes: int = 1           # probes that must succeed to close
    reload_breaker_failures: int = 3    # reload failures that open its breaker
    reload_breaker_reset_s: float = 10.0
    # device-memory admission budget (ISSUE 15): estimated bytes the queued
    # rows would occupy on device (rows × feature width × dtype × headroom,
    # parallel/memory.estimate_batch_bytes); None = memory admission off —
    # the default, so depth/deadline-tuned deployments are unchanged
    batch_bytes_budget: Optional[int] = None

    _PARAM_KEYS = {
        "latencyTargetMs": "latency_target_ms",
        "adaptiveLimit": "adaptive",
        "minLimit": "min_limit",
        "queueDeadlineMs": "queue_deadline_ms",
        "brownoutHigh": "brownout_high",
        "brownoutLow": "brownout_low",
        "breakerWindow": "breaker_window",
        "breakerFailures": "breaker_failures",
        "breakerRate": "breaker_rate",
        "breakerMinCalls": "breaker_min_calls",
        "breakerResetS": "breaker_reset_s",
        "halfOpenProbes": "half_open_probes",
        "reloadBreakerFailures": "reload_breaker_failures",
        "reloadBreakerResetS": "reload_breaker_reset_s",
        "batchBytesBudget": "batch_bytes_budget",
    }

    @classmethod
    def from_params(cls, serving: Optional[Dict[str, Any]]
                    ) -> "OverloadConfig":
        """Build from a ``servingParams`` dict, ignoring unrelated keys
        (host, port, maxBatch, ... are consumed by the server itself)."""
        kwargs = {}
        for key, attr in cls._PARAM_KEYS.items():
            if serving and key in serving:
                kwargs[attr] = serving[key]
        return cls(**kwargs)


@dataclass
class ShedDecision:
    """Why admission refused a request, and when to come back."""

    kind: str            # "limit" (queue past the adaptive limit),
    #                      "deadline" (queue wait would blow the deadline),
    #                      or "memory" (queued rows past the byte budget)
    message: str
    retry_after_s: float


class HealthStateMachine:
    """Current engine health plus the reason it got there.

    Transitions record a telemetry event (``serving.health``) and count in
    the engine registry; the gauge ``health_state`` exports the numeric
    code (0 SERVING / 1 DEGRADED / 2 BROWNOUT / 3 DRAINING)."""

    def __init__(self, registry: Optional[Any] = None):
        self._lock = threading.Lock()
        self._state = SERVING
        self._reason = "startup"
        self._registry = registry
        if registry is not None:
            registry.gauge("health_state",
                           lambda: HEALTH_CODES[self._state])

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    @property
    def code(self) -> int:
        return HEALTH_CODES[self.state]

    def set_state(self, to: str, reason: str) -> bool:
        """Move to ``to``; returns True when this was an actual transition.
        DRAINING is terminal — nothing transitions out of it."""
        if to not in HEALTH_CODES:
            raise ValueError(f"unknown health state {to!r}")
        with self._lock:
            if self._state == to or self._state == DRAINING:
                return False
            frm, self._state = self._state, to
            self._reason = reason
        event("serving.health", from_state=frm, to_state=to, reason=reason)
        if self._registry is not None:
            self._registry.counter("health_transitions_total").inc()
            self._registry.counter(f"health.{to}_total").inc()
        return True

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state, "reason": self._reason,
                    "code": HEALTH_CODES[self._state]}


class OverloadController:
    """One controller per engine: admission, breakers, health.

    The engine owns the queue and the locks; this object owns the *policy*
    — every method is a pure decision or a bookkeeping update, safe to call
    from request threads and the batcher thread concurrently."""

    def __init__(self, config: Optional[OverloadConfig] = None, *,
                 queue_bound: Any, max_batch: int,
                 registry: Optional[Any] = None,
                 scope: Optional[str] = None):
        self.config = config or OverloadConfig()
        # multi-tenant bulkheads: a scoped controller suffixes its breaker
        # names with ``@<scope>`` so per-tenant breaker state/counters stay
        # distinguishable after the tenant-labeled metrics merge.  None
        # (the single-bundle default) keeps the PR-8 names bit-for-bit.
        self.scope = scope
        suffix = f"@{scope}" if scope else ""
        # int for a fixed ceiling, or a callable for a live one (the engine
        # passes ``lambda: self.queue_bound`` so runtime retuning is seen)
        if callable(queue_bound):
            self._queue_bound_fn = queue_bound
        else:
            self._queue_bound_fn = lambda bound=int(queue_bound): bound
        self.max_batch = max(1, int(max_batch))
        cfg = self.config
        self.limit: Optional[AdaptiveConcurrencyLimit] = None
        if cfg.adaptive:
            self.limit = AdaptiveConcurrencyLimit(
                target_latency_s=cfg.latency_target_ms / 1000.0,
                max_limit=self.queue_bound, min_limit=cfg.min_limit)
        self.compiled_breaker = CircuitBreaker(
            f"serving.batch{suffix}", window=cfg.breaker_window,
            failure_threshold=cfg.breaker_failures,
            failure_rate=cfg.breaker_rate,
            min_calls=cfg.breaker_min_calls,
            reset_timeout_s=cfg.breaker_reset_s,
            half_open_probes=cfg.half_open_probes, registry=registry)
        self.reload_breaker = CircuitBreaker(
            f"serving.reload{suffix}",
            failure_threshold=cfg.reload_breaker_failures,
            # reload attempts are sparse (one per watcher poll): consecutive
            # failures are the only meaningful trip wire
            window=max(4, cfg.reload_breaker_failures),
            failure_rate=1.1, min_calls=10 ** 9,
            reset_timeout_s=cfg.reload_breaker_reset_s,
            half_open_probes=1, registry=registry)
        self.health = HealthStateMachine(registry=registry)
        self._registry = registry
        self._lock = threading.Lock()
        self._ewma_batch_s: Optional[float] = None
        self._brownout_latched = False
        if registry is not None:
            registry.gauge("admission_limit", self.admission_limit)

    # -- admission ---------------------------------------------------------
    @property
    def queue_bound(self) -> int:
        return int(self._queue_bound_fn())

    def admission_limit(self) -> int:
        """Queue slots currently granted: the adaptive limit when enabled,
        else the static ``queue_bound`` (always the hard ceiling)."""
        if self.limit is None:
            return self.queue_bound
        return min(self.limit.limit, self.queue_bound)

    def ewma_batch_latency_s(self) -> float:
        with self._lock:
            return self._ewma_batch_s or 0.0

    def estimate_wait_s(self, queue_depth: int) -> float:
        """Expected queue wait for a request arriving at ``queue_depth``:
        batches ahead of it times the smoothed batch latency.  The
        continuous batcher dispatches the instant the device frees, so
        there is no linger constant in this estimate — batch latency is
        the whole story.  Zero until the first batch lands (no signal)."""
        with self._lock:
            ewma = self._ewma_batch_s
        if ewma is None:
            return 0.0
        batches_ahead = math.ceil((queue_depth + 1) / self.max_batch)
        return batches_ahead * ewma

    def admit(self, queue_depth: int, extra: int = 1,
              deadline_s: Optional[float] = None,
              est_bytes: Optional[int] = None
              ) -> Optional[ShedDecision]:
        """Decide whether ``extra`` records may join a queue currently
        ``queue_depth`` deep.  None = admitted; a ``ShedDecision``
        otherwise (the engine translates it into ``OverloadedError``).

        ``est_bytes`` — the engine's device-memory estimate for the queue
        WITH this request admitted — is checked against
        ``batch_bytes_budget`` when both are set: a batch that would blow
        the device budget sheds honestly at the door instead of OOM-ing
        the scoring program mid-flight."""
        budget_bytes = self.config.batch_bytes_budget
        if (budget_bytes is not None and est_bytes is not None
                and est_bytes > budget_bytes):
            wait = self.estimate_wait_s(queue_depth)
            return ShedDecision(
                kind="memory",
                message=(f"estimated queued-batch footprint {est_bytes} "
                         f"bytes exceeds the {budget_bytes}-byte device "
                         "memory budget (batchBytesBudget)"),
                retry_after_s=max(1.0, wait))
        limit = self.admission_limit()
        if queue_depth + extra > limit:
            wait = self.estimate_wait_s(queue_depth)
            return ShedDecision(
                kind="limit",
                message=(f"queue depth {queue_depth} + {extra} exceeds "
                         f"admission limit {limit} "
                         f"(queue_bound={self.queue_bound})"),
                retry_after_s=max(1.0, wait))
        budget = deadline_s
        cfg_deadline = self.config.queue_deadline_ms
        if cfg_deadline is not None:
            cfg_deadline_s = cfg_deadline / 1000.0
            budget = (cfg_deadline_s if budget is None
                      else min(budget, cfg_deadline_s))
        if budget is not None:
            wait = self.estimate_wait_s(queue_depth + extra - 1)
            if wait > budget:
                return ShedDecision(
                    kind="deadline",
                    message=(f"estimated queue wait {wait:.3f}s exceeds "
                             f"the {budget:g}s deadline; rejecting now "
                             "rather than timing out in queue"),
                    retry_after_s=max(1.0, wait - budget))
        return None

    # -- feedback from the batcher -----------------------------------------
    def observe_batch(self, latency_s: float) -> None:
        """Feed one completed batch's latency: updates the AIMD limit and
        the EWMA the deadline shedder uses."""
        with self._lock:
            if self._ewma_batch_s is None:
                self._ewma_batch_s = float(latency_s)
            else:
                self._ewma_batch_s += 0.3 * (latency_s - self._ewma_batch_s)
        if self.limit is not None:
            self.limit.observe(latency_s)

    # -- health ------------------------------------------------------------
    def refresh_health(self, *, queue_depth: int, draining: bool,
                       compiled_ok: bool) -> str:
        """Recompute the health state from current signals.  Priority:
        DRAINING > BROWNOUT > DEGRADED > SERVING; brownout enters at
        ``brownout_high`` queue utilization and exits at ``brownout_low``
        (hysteresis, so the state doesn't flap batch-to-batch)."""
        if draining:
            self.health.set_state(DRAINING, "engine close requested")
            return self.health.state
        limit = max(1, self.admission_limit())
        util = queue_depth / limit
        with self._lock:
            if util >= self.config.brownout_high:
                self._brownout_latched = True
            elif util <= self.config.brownout_low:
                self._brownout_latched = False
            browned = self._brownout_latched
        if browned:
            self.health.set_state(
                BROWNOUT, f"queue utilization {util:.0%} of limit {limit}")
            return self.health.state
        breaker_state = self.compiled_breaker.current_state()
        if not compiled_ok or breaker_state != CircuitBreaker.CLOSED:
            why = ("compiled-path breaker " + breaker_state
                   if compiled_ok else "compiled path demoted at warmup "
                   "or by online traces")
            self.health.set_state(DEGRADED, why)
            return self.health.state
        self.health.set_state(SERVING, "all signals nominal")
        # the machine may refuse (DRAINING is terminal): report what it IS
        return self.health.state

    def snapshot(self) -> Dict[str, Any]:
        return {"health": self.health.snapshot(),
                "scope": self.scope,
                "admission_limit": self.admission_limit(),
                "queue_bound": self.queue_bound,
                "adaptive": (self.limit.snapshot()
                             if self.limit is not None else None),
                "ewma_batch_latency_s": self.ewma_batch_latency_s(),
                "compiled_breaker": self.compiled_breaker.snapshot(),
                "reload_breaker": self.reload_breaker.snapshot()}
