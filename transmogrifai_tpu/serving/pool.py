"""SO_REUSEPORT worker pool: N scoring processes behind one port.

The single-process server tops out on the GIL, not the device (ROADMAP
item 1: warm score flat at ~57k rows/s across five bench rounds).  AOT
bundles (PR 9) made horizontal scale cheap — a fresh worker deserializes
the shipped executables and scores with zero compiles — so the pool is
the straightforward unix answer:

* every worker binds the SAME ``(host, port)`` with ``SO_REUSEPORT``; the
  kernel load-balances accepted connections across them (no userspace
  proxy on the hot path),
* each worker is a full single-process server (engine + continuous
  batcher + overload control plane), sharing nothing but the verified
  bundle path — admission and breaker state stay correct per-worker,
* each worker also binds a private ephemeral ADMIN port (same handler:
  ``/healthz`` ``/readyz`` ``/metrics``) that the parent probes and
  scrapes — traffic and control never contend for a socket,
* the parent supervisor health-checks workers, restarts crashed ones
  (SIGTERM → grace → SIGKILL escalation on stop, the
  ``parallel/supervisor.run_supervised`` conventions), and serves
  aggregated ``/metrics`` on its own admin port: counters sum across
  workers, gauges max-merge, and per-worker samples carry a
  ``worker_id`` label while family names stay unchanged.

Crash/failover story: when a worker dies, its pending accept backlog is
lost but every OTHER worker's listening socket keeps accepting — clients
see at worst a connection reset on in-flight requests to the dead worker,
never a 5xx from survivors (the chaos harness kills a worker mid-storm
and asserts exactly this).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

_METRIC_PREFIX = "transmogrifai_serving"


# --------------------------------------------------------------------------
# metrics aggregation
# --------------------------------------------------------------------------

def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def _escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash first, then quote and
    newline — a worker_id (or any label) containing ``"`` or ``\\``
    survives the text round-trip instead of corrupting the exposition."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _with_worker_label(labels: str, worker_id: str,
                       label: str = "worker_id") -> str:
    """``{a="b"}`` or ``""`` → same labels plus ``label`` (default
    ``worker_id``; the tenant registry merges with ``tenant``)."""
    tag = f'{label}="{_escape_label_value(worker_id)}"'
    if not labels:
        return "{" + tag + "}"
    inner = labels[1:-1].strip()
    return "{" + (f"{tag},{inner}" if inner else tag) + "}"


def _find_label_close(line: str, brace: int) -> int:
    """Index of the ``}`` closing the label set opened at ``brace``,
    honouring quoted values with ``\\"``/``\\\\`` escapes (a value may
    contain ``}``); -1 when unterminated."""
    i = brace + 1
    in_quote = False
    while i < len(line):
        ch = line[i]
        if in_quote:
            if ch == "\\":
                i += 2
                continue
            if ch == '"':
                in_quote = False
        elif ch == '"':
            in_quote = True
        elif ch == "}":
            return i
        i += 1
    return -1


def _parse_exposition(text: str):
    """Prometheus text exposition → ordered ``{family: {"type", "help",
    "samples": [(sample_name, labels, value, exemplar)]}}``.  Summary
    ``_sum`` / ``_count`` samples resolve to their base family.
    ``exemplar`` is the verbatim OpenMetrics suffix (`` # {...} v``) or
    ``""`` — the merge re-emits it so trace links survive aggregation."""
    families: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []

    def fam(name: str) -> Dict[str, Any]:
        if name not in families:
            families[name] = {"type": "untyped", "help": "", "samples": []}
            order.append(name)
        return families[name]

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_ = rest.partition(" ")
            fam(name)["help"] = help_
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, type_ = rest.partition(" ")
            fam(name)["type"] = type_.strip()
            continue
        if line.startswith("#"):
            continue
        # split off an OpenMetrics exemplar (`value # {labels} exval`)
        # BEFORE locating the label braces: the exemplar carries its own
        # brace pair that a naive rfind("}") would mistake for the end of
        # the sample's label set
        exemplar = ""
        ex_at = line.find(" # {")
        if ex_at >= 0:
            exemplar = line[ex_at + 1:]
            line = line[:ex_at].rstrip()
        brace = line.find("{")
        if brace >= 0:
            close = _find_label_close(line, brace)
            if close < 0:
                continue  # malformed sample: skip, don't fail the scrape
            sample_name = line[:brace]
            labels = line[brace:close + 1]
            value_s = line[close + 1:].strip()
        else:
            sample_name, _, value_s = line.partition(" ")
            labels = ""
        try:
            value = float(value_s)
        except ValueError:
            continue
        base = sample_name
        for suffix in ("_sum", "_count"):
            if base.endswith(suffix) and base[:-len(suffix)] in families \
                    and families[base[:-len(suffix)]]["type"] == "summary":
                base = base[:-len(suffix)]
                break
        fam(base)["samples"].append((sample_name, labels, value, exemplar))
    return families, order


def merge_worker_metrics(worker_texts: List[Tuple[str, str]],
                         label: str = "worker_id") -> str:
    """Merge per-worker ``/metrics`` payloads into one exposition.

    ``worker_texts`` is ``[(worker_id, exposition_text), ...]``; ``label``
    names the per-source label (``worker_id`` for pool workers, ``tenant``
    for the tenant registry — pool-level aggregation preserves inner
    labels, so worker-level ``tenant`` labels survive a second merge).
    Per family (names unchanged, so existing dashboards keep working):

    * **counters**: one aggregate sample per label-set (sum across
      workers) plus one sample per worker with a ``worker_id`` label,
    * **gauges**: aggregate = max across workers (right for states,
      limits and depth-style gauges; a sum would fabricate a state), plus
      per-worker labeled samples,
    * **summaries**: ``_sum``/``_count`` sum across workers; quantile
      samples can't be merged without the raw streams, so they appear
      per-worker only (with ``worker_id`` + ``quantile`` labels).

    Family order follows the first worker, then families only later
    workers expose."""
    parsed = [(wid, *_parse_exposition(text)) for wid, text in worker_texts]
    order: List[str] = []
    for _wid, _families, worker_order in parsed:
        for name in worker_order:
            if name not in order:
                order.append(name)
    lines: List[str] = []
    for name in order:
        type_ = "untyped"
        help_ = ""
        for _wid, families, _o in parsed:
            f = families.get(name)
            if f is not None:
                type_ = f["type"] if f["type"] != "untyped" else type_
                help_ = f["help"] or help_
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {type_}")
        # aggregate per (sample_name, labels) across workers; exemplars
        # can't be summed, so the aggregate sample carries the last
        # non-empty one seen (a trace link survives the merge)
        agg: Dict[Tuple[str, str], float] = {}
        agg_ex: Dict[Tuple[str, str], str] = {}
        agg_order: List[Tuple[str, str]] = []
        per_worker: List[str] = []
        for wid, families, _o in parsed:
            f = families.get(name)
            if f is None:
                continue
            for sample_name, labels, value, exemplar in f["samples"]:
                is_quantile = type_ == "summary" and not (
                    sample_name.endswith("_sum")
                    or sample_name.endswith("_count"))
                ex_suffix = f" {exemplar}" if exemplar else ""
                per_worker.append(
                    f"{sample_name}"
                    f"{_with_worker_label(labels, wid, label=label)} "
                    f"{_fmt(value)}{ex_suffix}")
                if is_quantile:
                    continue  # no cross-worker quantile merge
                key = (sample_name, labels)
                if key not in agg:
                    agg[key] = 0.0
                    agg_order.append(key)
                if type_ == "gauge":
                    agg[key] = max(agg[key], value)
                else:
                    agg[key] += value
                if exemplar:
                    agg_ex[key] = exemplar
        for sample_name, labels in agg_order:
            key = (sample_name, labels)
            ex = agg_ex.get(key, "")
            lines.append(f"{sample_name}{labels} {_fmt(agg[key])}"
                         f"{' ' + ex if ex else ''}")
        lines.extend(per_worker)
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# worker process entry
# --------------------------------------------------------------------------

def _atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def worker_main(config_path: str) -> int:
    """One pool worker: full engine + continuous batcher, a
    ``SO_REUSEPORT`` traffic server on the shared port and a private admin
    server on an ephemeral port, draining cleanly on SIGTERM."""
    import contextlib

    from ..checkpoint import preemption_guard, shutdown_requested
    from ..telemetry import TraceContext, Tracer, use_tracer
    from .overload import OverloadConfig
    from .server import ScoringHTTPServer
    from .engine import ScoringEngine

    with open(config_path) as f:
        cfg = json.load(f)
    worker_id = str(cfg["workerId"])
    overload = (OverloadConfig(**cfg["overload"])
                if cfg.get("overload") else None)
    # distributed tracing (opt-in via traceDir): the worker records every
    # request/batch span into its own tracer, seeded from the parent's
    # TRANSMOGRIFAI_TRACEPARENT when the pool exported one, and writes
    # trace-worker-<id>.json on drain — `trace-merge` (and the pool's
    # /traces endpoint) assemble the per-worker files into one timeline
    trace_dir = cfg.get("traceDir")
    tracer = None
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        tracer = Tracer(run_name=f"serve-worker-{worker_id}",
                        parent=TraceContext.from_env(),
                        worker_id=worker_id)
    with preemption_guard("serve-worker"), \
            (use_tracer(tracer) if tracer is not None
             else contextlib.nullcontext()):
        engine = None
        registry = None
        if cfg.get("modelRoot"):
            # multi-tenant worker: every worker loads the full registry —
            # tenants activate lazily per worker, so a worker only pays
            # for the tenants the kernel actually routes to it
            from .tenants import TenantRegistry
            registry = TenantRegistry(
                cfg["modelRoot"],
                max_batch=int(cfg.get("maxBatch", 64)),
                queue_bound=int(cfg.get("queueBound", 256)),
                reload_poll_s=float(cfg.get("reloadPollS", 0.0)),
                overload=overload,
                max_active=cfg.get("tenantMaxActive"),
                memory_budget_bytes=cfg.get("tenantMemoryBudgetBytes"))
            served = f"{len(registry.tenants())} tenants"
        else:
            engine = ScoringEngine(
                cfg["modelLocation"],
                max_batch=int(cfg.get("maxBatch", 64)),
                queue_bound=int(cfg.get("queueBound", 256)),
                reload_poll_s=float(cfg.get("reloadPollS", 0.0)),
                overload=overload)
            served = engine.model_version
        traffic = ScoringHTTPServer(
            engine, host=cfg["host"], port=int(cfg["port"]),
            request_deadline_s=cfg.get("requestDeadlineS", 30.0),
            reuse_port=True, wire_format=cfg.get("wireFormat", "auto"),
            registry=registry)
        admin = ScoringHTTPServer(
            engine, host=cfg["host"], port=0,
            request_deadline_s=cfg.get("requestDeadlineS", 30.0),
            wire_format=cfg.get("wireFormat", "auto"),
            registry=registry)
        for srv, tag in ((traffic, "traffic"), (admin, "admin")):
            threading.Thread(target=srv.serve_forever,
                             name=f"worker-{worker_id}-{tag}",
                             daemon=True).start()
        _atomic_write_json(
            os.path.join(cfg["runDir"], f"worker-{worker_id}.ready.json"),
            {"workerId": worker_id, "pid": os.getpid(),
             "port": traffic.port, "adminPort": admin.port})
        print(f"worker {worker_id} serving {served} on "
              f":{traffic.port} (admin :{admin.port})", flush=True)
        try:
            while not shutdown_requested("serve-worker"):
                time.sleep(0.1)
        finally:
            traffic.draining = True
            admin.draining = True
            if registry is not None:
                registry.close(timeout_s=30.0)
            else:
                engine.close(drain=True, timeout_s=30.0)
            traffic.shutdown()
            traffic.server_close()
            admin.shutdown()
            admin.server_close()
            if tracer is not None:
                try:
                    tracer.export_chrome_trace(os.path.join(
                        trace_dir, f"trace-worker-{worker_id}.json"))
                except OSError:
                    pass  # trace export must not fail the drain
    return 0


# --------------------------------------------------------------------------
# the pool supervisor
# --------------------------------------------------------------------------

class _WorkerSlot:
    def __init__(self, worker_id: int, config_path: str, log_path: str):
        self.worker_id = worker_id
        self.config_path = config_path
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.ready: Optional[Dict[str, Any]] = None
        self.probe_failures = 0
        self.restarts = 0

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ServingPool:
    """Spawn, supervise and aggregate N ``SO_REUSEPORT`` workers.

    The parent holds no engine and serves no traffic: it writes one
    config file per worker, spawns them as ``python -m
    transmogrifai_tpu.serving.pool --worker <config>`` (each in its own
    session, stdout+stderr to a per-worker log), restarts any that die or
    fail ``health_probes_fatal`` consecutive admin ``/healthz`` probes,
    and exposes pool status + merged metrics."""

    def __init__(self, model_location: Optional[str], *, workers: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch: int = 64, queue_bound: int = 256,
                 request_deadline_s: Optional[float] = 30.0,
                 reload_poll_s: float = 0.0,
                 overload: Optional[Dict[str, Any]] = None,
                 wire_format: str = "auto",
                 run_dir: Optional[str] = None,
                 health_poll_s: float = 1.0,
                 health_probes_fatal: int = 3,
                 worker_boot_timeout_s: float = 180.0,
                 max_restarts: int = 20,
                 trace_dir: Optional[str] = None,
                 model_root: Optional[str] = None,
                 tenant_max_active: Optional[int] = None,
                 tenant_memory_budget_bytes: Optional[int] = None):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if bool(model_location) == bool(model_root):
            raise ValueError("exactly one of model_location (single "
                             "bundle) or model_root (multi-tenant) is "
                             "required")
        self.model_location = model_location
        self.model_root = model_root
        self.workers = int(workers)
        self.host = host
        # all workers share ONE concrete port: resolve the ephemeral
        # request up front so every bind targets the same number
        self.port = int(port) or free_port(host)
        self.health_poll_s = float(health_poll_s)
        self.health_probes_fatal = int(health_probes_fatal)
        self.worker_boot_timeout_s = float(worker_boot_timeout_s)
        self.max_restarts = int(max_restarts)
        self.run_dir = run_dir or tempfile.mkdtemp(
            prefix="transmogrifai-pool-")
        os.makedirs(self.run_dir, exist_ok=True)
        self.trace_dir = trace_dir
        if self.trace_dir:
            os.makedirs(self.trace_dir, exist_ok=True)
        self._stopping = False
        self._lock = threading.Lock()
        self._restarts_total = 0
        self._worker_cfg = {
            "modelLocation": model_location, "host": host,
            "port": self.port, "maxBatch": int(max_batch),
            "queueBound": int(queue_bound),
            "requestDeadlineS": request_deadline_s,
            "reloadPollS": float(reload_poll_s),
            "overload": dict(overload) if overload else None,
            "wireFormat": wire_format, "runDir": self.run_dir,
            "traceDir": self.trace_dir,
            "modelRoot": model_root,
            "tenantMaxActive": tenant_max_active,
            "tenantMemoryBudgetBytes": tenant_memory_budget_bytes}
        self.slots = [self._make_slot(i) for i in range(self.workers)]
        self._supervisor: Optional[threading.Thread] = None

    # -- spawning ----------------------------------------------------------
    def _make_slot(self, worker_id: int) -> _WorkerSlot:
        config_path = os.path.join(self.run_dir,
                                   f"worker-{worker_id}.json")
        _atomic_write_json(config_path,
                           dict(self._worker_cfg, workerId=worker_id))
        return _WorkerSlot(worker_id, config_path,
                           os.path.join(self.run_dir,
                                        f"worker-{worker_id}.log"))

    def _spawn(self, slot: _WorkerSlot) -> None:
        ready_path = os.path.join(self.run_dir,
                                  f"worker-{slot.worker_id}.ready.json")
        if os.path.exists(ready_path):
            os.unlink(ready_path)
        slot.ready = None
        slot.probe_failures = 0
        env = dict(os.environ)
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        # every worker installs from the pool's compiled-program registry:
        # N-worker boot then costs at most the ONE compile the first
        # publisher paid, not N re-derivations (aot_registry.py)
        from ..aot_registry import managed_compile_cache, registry_root
        reg = registry_root()
        if reg:
            env.setdefault("TRANSMOGRIFAI_AOT_REGISTRY", reg)
        cache = managed_compile_cache()
        if cache:
            env.setdefault("TRANSMOGRIFAI_COMPILE_CACHE", cache)
        # seed the worker's root span from the pool's ambient trace so
        # worker-side spans land on the same trace_id as the spawner
        from ..telemetry import TRACEPARENT_ENV, current_trace_context
        ctx = current_trace_context()
        if ctx is not None:
            env[TRACEPARENT_ENV] = ctx.child().to_traceparent()
        log = open(slot.log_path, "ab")
        try:
            # own session: SIGTERM/SIGKILL hit exactly this worker, and a
            # dying parent shell doesn't take the pool down with it
            # (run_supervised conventions)
            slot.proc = subprocess.Popen(
                [sys.executable, "-m", "transmogrifai_tpu.serving.pool",
                 "--worker", slot.config_path],
                stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True)
        finally:
            log.close()

    def _wait_ready(self, slot: _WorkerSlot, deadline: float) -> None:
        ready_path = os.path.join(self.run_dir,
                                  f"worker-{slot.worker_id}.ready.json")
        while time.monotonic() < deadline:
            if os.path.exists(ready_path):
                try:
                    with open(ready_path) as f:
                        slot.ready = json.load(f)
                    return
                except (OSError, ValueError):
                    pass  # mid-rename; retry
            if slot.proc is not None and slot.proc.poll() is not None:
                raise RuntimeError(
                    f"worker {slot.worker_id} exited rc="
                    f"{slot.proc.returncode} before ready "
                    f"(log: {slot.log_path}):\n{self._log_tail(slot)}")
            time.sleep(0.05)
        raise RuntimeError(
            f"worker {slot.worker_id} not ready within "
            f"{self.worker_boot_timeout_s}s (log: {slot.log_path}):\n"
            f"{self._log_tail(slot)}")

    def _log_tail(self, slot: _WorkerSlot, nbytes: int = 2000) -> str:
        try:
            with open(slot.log_path, "rb") as f:
                f.seek(max(0, os.path.getsize(slot.log_path) - nbytes))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no log>"

    def start(self) -> "ServingPool":
        """Spawn every worker, wait until all are ready, start the
        supervisor thread.  Raises (after killing stragglers) if any
        worker fails to boot."""
        deadline = time.monotonic() + self.worker_boot_timeout_s
        try:
            for slot in self.slots:
                self._spawn(slot)
            for slot in self.slots:
                self._wait_ready(slot, deadline)
        except BaseException:
            self.stop(grace_s=2.0)
            raise
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name="pool-supervisor",
            daemon=True)
        self._supervisor.start()
        return self

    # -- supervision -------------------------------------------------------
    def _probe(self, slot: _WorkerSlot) -> bool:
        if not slot.ready:
            return False
        url = (f"http://{self.host}:{slot.ready['adminPort']}/healthz")
        try:
            with urllib.request.urlopen(url, timeout=2.0) as resp:
                return resp.status == 200
        except (urllib.error.URLError, OSError, TimeoutError):
            return False

    def _restart(self, slot: _WorkerSlot, reason: str) -> None:
        from ..resilience import record_failure
        with self._lock:
            if self._stopping:
                return
            if self._restarts_total >= self.max_restarts:
                record_failure("serving", "degraded",
                               f"worker {slot.worker_id} down ({reason}) "
                               "but restart budget exhausted",
                               point="serving.pool")
                return
            self._restarts_total += 1
            slot.restarts += 1
        record_failure("serving", "recovered",
                       f"restarting worker {slot.worker_id}: {reason}",
                       point="serving.pool")
        if slot.proc is not None and slot.proc.poll() is None:
            try:
                slot.proc.kill()
            except OSError:
                pass
        if slot.proc is not None:
            try:
                slot.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass
        self._spawn(slot)
        with self._lock:
            aborted = self._stopping
        if aborted:
            # stop() ran between the budget check and the spawn: the new
            # worker is ours to reap — terminate it now rather than orphan
            # a process stop() never saw
            if slot.proc is not None:
                try:
                    slot.proc.terminate()
                    slot.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        slot.proc.kill()
                        slot.proc.wait(timeout=5.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
            return
        try:
            self._wait_ready(
                slot, time.monotonic() + self.worker_boot_timeout_s)
        except RuntimeError as e:
            record_failure("serving", "degraded", e, point="serving.pool")

    def _supervise_loop(self) -> None:
        while not self._stopping:
            time.sleep(self.health_poll_s)
            if self._stopping:
                return
            for slot in self.slots:
                if self._stopping:
                    return
                if not slot.alive:
                    rc = slot.proc.returncode if slot.proc else None
                    self._restart(slot, f"process exited rc={rc}")
                    continue
                if self._probe(slot):
                    slot.probe_failures = 0
                elif slot.ready:
                    slot.probe_failures += 1
                    if slot.probe_failures >= self.health_probes_fatal:
                        self._restart(
                            slot,
                            f"{slot.probe_failures} consecutive health "
                            "probe failures")

    # -- status / metrics --------------------------------------------------
    def status(self) -> Dict[str, Any]:
        st = {"port": self.port, "workers": self.workers,
              "alive": sum(1 for s in self.slots if s.alive),
              "restartsTotal": self._restarts_total,
              "runDir": self.run_dir,
              "workerList": [
                  {"workerId": s.worker_id, "alive": s.alive,
                   "pid": (s.ready or {}).get("pid"),
                   "adminPort": (s.ready or {}).get("adminPort"),
                   "restarts": s.restarts} for s in self.slots]}
        if self.model_root:
            st["modelRoot"] = self.model_root
            st["tenants"] = self.tenant_states()
        return st

    def tenant_states(self) -> Dict[str, Any]:
        """Per-tenant state across the pool, scraped (best effort) from
        each worker's admin ``/healthz``.  A tenant's pool-level state is
        the worst any worker reports (QUARANTINED > ACTIVE > INACTIVE):
        activation is lazy per worker, so a tenant can be cold on one
        worker and quarantined on another — the operator wants the bad
        news."""
        rank = {"INACTIVE": 0, "ACTIVE": 1, "QUARANTINED": 2}
        merged: Dict[str, Any] = {}
        for slot in self.slots:
            if not (slot.alive and slot.ready):
                continue
            url = (f"http://{self.host}:{slot.ready['adminPort']}/healthz")
            try:
                with urllib.request.urlopen(url, timeout=2.0) as resp:
                    payload = json.loads(resp.read().decode())
            except (urllib.error.URLError, OSError, TimeoutError,
                    ValueError):
                continue
            for tenant, info in (payload.get("tenants") or {}).items():
                seen = merged.get(tenant)
                if seen is None or (rank.get(info.get("state"), 0)
                                    > rank.get(seen.get("state"), 0)):
                    merged[tenant] = info
        return merged

    def scrape_worker(self, slot: _WorkerSlot) -> Optional[str]:
        if not (slot.alive and slot.ready):
            return None
        url = f"http://{self.host}:{slot.ready['adminPort']}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                return resp.read().decode()
        except (urllib.error.URLError, OSError, TimeoutError):
            return None

    def metrics(self) -> str:
        """Merged per-worker metrics plus the pool's own families."""
        texts = []
        for slot in self.slots:
            text = self.scrape_worker(slot)
            if text is not None:
                texts.append((str(slot.worker_id), text))
        merged = merge_worker_metrics(texts) if texts else ""
        p = _METRIC_PREFIX
        lines = [
            f"# HELP {p}_pool_workers Configured pool size",
            f"# TYPE {p}_pool_workers gauge",
            f"{p}_pool_workers {self.workers}",
            f"# HELP {p}_pool_workers_alive Workers currently running",
            f"# TYPE {p}_pool_workers_alive gauge",
            f"{p}_pool_workers_alive "
            f"{sum(1 for s in self.slots if s.alive)}",
            f"# HELP {p}_pool_worker_restarts_total Worker restarts "
            "performed by the supervisor",
            f"# TYPE {p}_pool_worker_restarts_total counter",
            f"{p}_pool_worker_restarts_total {self._restarts_total}",
            f"# HELP {p}_pool_worker_up Per-worker liveness",
            f"# TYPE {p}_pool_worker_up gauge"]
        lines.extend(
            f'{p}_pool_worker_up{{worker_id="{s.worker_id}"}} '
            f'{1 if s.alive else 0}' for s in self.slots)
        return merged + "\n".join(lines) + "\n"

    # -- shutdown ----------------------------------------------------------
    def stop(self, grace_s: float = 30.0) -> None:
        """SIGTERM every worker (graceful drain), escalate to SIGKILL
        after ``grace_s``, reap everything (run_supervised conventions:
        children are always reaped, never orphaned)."""
        with self._lock:
            self._stopping = True
        for slot in self.slots:
            if slot.alive:
                try:
                    slot.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + grace_s
        for slot in self.slots:
            if slot.proc is None:
                continue
            remaining = max(0.1, deadline - time.monotonic())
            try:
                slot.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    slot.proc.kill()
                except OSError:
                    pass
                try:
                    slot.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)


# --------------------------------------------------------------------------
# parent admin server + CLI entry
# --------------------------------------------------------------------------

def _make_admin_server(pool: ServingPool, host: str, port: int):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _AdminHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_GET(self):  # noqa: N802
            if self.path == "/metrics":
                self._reply(200, pool.metrics().encode(),
                            "text/plain; version=0.0.4")
            elif self.path in ("/healthz", "/workers"):
                st = pool.status()
                code = 200 if st["alive"] == st["workers"] else 503
                if self.path == "/healthz":
                    code = 200 if st["alive"] > 0 else 503
                self._reply(code, json.dumps(st).encode(),
                            "application/json")
            elif self.path == "/traces":
                traces = []
                if pool.trace_dir and os.path.isdir(pool.trace_dir):
                    for name in sorted(os.listdir(pool.trace_dir)):
                        if not (name.startswith("trace-")
                                and name.endswith(".json")):
                            continue
                        p = os.path.join(pool.trace_dir, name)
                        try:
                            st_ = os.stat(p)
                        except OSError:
                            continue
                        traces.append({"name": name, "sizeBytes": st_.st_size,
                                       "mtimeS": st_.st_mtime})
                self._reply(200, json.dumps(
                    {"traceDir": pool.trace_dir,
                     "traces": traces}).encode(), "application/json")
            else:
                self._reply(404, json.dumps(
                    {"error": f"unknown path {self.path}"}).encode(),
                    "application/json")

    class _AdminServer(ThreadingHTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    return _AdminServer((host, port), _AdminHandler)


def pool_serve_main(model_location: Optional[str], *, workers: int,
                    host: str = "127.0.0.1", port: int = 8180,
                    admin_port: int = 0, max_batch: int = 64,
                    queue_bound: int = 256,
                    request_deadline_s: Optional[float] = 30.0,
                    reload_poll_s: float = 10.0,
                    overload: Optional[Dict[str, Any]] = None,
                    wire_format: str = "auto",
                    trace_dir: Optional[str] = None,
                    model_root: Optional[str] = None,
                    tenant_max_active: Optional[int] = None,
                    tenant_memory_budget_bytes: Optional[int] = None
                    ) -> int:
    """Blocking entry point for ``serve --workers N``: run the pool until
    SIGTERM/SIGINT, then drain every worker and exit 0."""
    from ..checkpoint import preemption_guard, shutdown_requested
    with preemption_guard("serve-pool"):
        pool = ServingPool(
            model_location, workers=workers, host=host, port=port,
            max_batch=max_batch, queue_bound=queue_bound,
            request_deadline_s=request_deadline_s,
            reload_poll_s=reload_poll_s, overload=overload,
            wire_format=wire_format, trace_dir=trace_dir,
            model_root=model_root, tenant_max_active=tenant_max_active,
            tenant_memory_budget_bytes=tenant_memory_budget_bytes).start()
        admin = _make_admin_server(pool, host, admin_port)
        threading.Thread(target=admin.serve_forever, name="pool-admin",
                         daemon=True).start()
        print(f"serving pool on http://{host}:{pool.port} "
              f"(workers={workers}, max_batch={max_batch}, "
              f"admin=http://{host}:{admin.server_address[1]})", flush=True)
        try:
            while not shutdown_requested("serve-pool"):
                time.sleep(0.2)
        finally:
            print("draining pool...", flush=True)
            pool.stop()
            admin.shutdown()
            admin.server_close()
    return 0


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port every worker can then SO_REUSEPORT-bind.  The
    probe socket sets SO_REUSEPORT too, so the number stays biddable."""
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="pool worker entry (internal; use `transmogrifai "
                    "serve --workers N` instead)")
    parser.add_argument("--worker", metavar="CONFIG_JSON",
                        help="run one pool worker from a config file")
    args = parser.parse_args(argv)
    if args.worker:
        return worker_main(args.worker)
    parser.error("--worker CONFIG_JSON is required")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
