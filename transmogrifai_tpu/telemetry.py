"""Unified telemetry — structured trace spans + a central metrics registry.

The runtime story used to be scattered: compile stats, racing counters,
host-link bytes, serving latency histograms and the FailureLog each lived in
their own ad-hoc global with no shared run context.  This module gives every
run one measurement substrate, in the style of Dapper/OpenTelemetry span
trees and Chrome ``chrome://tracing`` timelines:

* ``Tracer`` — thread-safe producer of nested spans.  ``tracer.span(name,
  **attrs)`` is a context manager recording monotonic wall times, a span id,
  the parent span id, a status (``ok``/``error``) and attributes.  Parenting
  is per-thread (each thread nests its own spans); a worker thread with no
  open span of its own parents to the innermost open span of the thread that
  installed the tracer — so the validator's thread-pool candidate fits nest
  under the orchestrating ``selector.sweep`` span.
* ``use_tracer(tracer)`` — the ambient run context, mirroring
  ``resilience.use_failure_log``: deep code calls the module-level
  ``span(...)`` / ``event(...)`` helpers, which no-op (near-zero cost) when
  no tracer is installed.
* ``MetricsRegistry`` — named ``Counter``s, ``Gauge``s and
  ``LatencyHistogram``s behind one namespace.  The process-default
  ``REGISTRY`` absorbs and re-exports today's scattered sources
  (``profiling.compile_stats``, ``profiling.racing_stats``,
  ``profiling.host_link_bytes``) as read-through gauges, so one
  ``snapshot()`` answers "what did this process compile/prune/transfer".
* Exports — ``tracer.export_chrome_trace(path)`` writes Perfetto-loadable
  Chrome trace-event JSON; ``telemetry_summary()`` builds the
  ``telemetry.json`` bundled next to saved models and into bench aux;
  ``render_trace_summary()`` prints the top-N slowest-spans table behind the
  ``transmogrifai_tpu trace-summary`` subcommand.

Span ids correlate with the failure layer: ``resilience.FailureLog.record``
stamps the recording thread's active span id into each event's detail, and
``FaultInjector`` remembers the span each injected fault fired inside — a
chaos-test failure points at the exact span.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

from .profiling import (LatencyHistogram, compile_stats, host_link_bytes,
                        racing_stats)

__all__ = [
    "Span", "Tracer", "TraceContext", "TRACEPARENT_ENV", "use_tracer",
    "active_tracer", "span", "event", "current_span_id",
    "current_trace_context", "Counter", "Gauge", "MetricsRegistry",
    "REGISTRY", "LatencyHistogram", "telemetry_summary",
    "write_telemetry_summary", "render_trace_summary", "load_trace",
    "merge_traces",
]


# --------------------------------------------------------------------------
# W3C trace context
# --------------------------------------------------------------------------

#: Env var carrying the parent ``traceparent`` into supervised children
#: (probe subprocesses, chaos children, pool workers, lifecycle retrains).
TRACEPARENT_ENV = "TRANSMOGRIFAI_TRACEPARENT"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

#: Hard cap on accepted header length — anything longer is dropped without
#: even running the regex (oversized headers must never cost a 500).
_TRACEPARENT_MAX_LEN = 64


@dataclass(frozen=True)
class TraceContext:
    """A W3C trace-context position: the 128-bit ``trace_id`` every span in
    one distributed request shares, plus the 64-bit ``span_id`` of the
    current position in the tree (both lowercase hex).  Frozen — deriving a
    child position returns a new instance."""

    trace_id: str
    span_id: str
    flags: int = 1          # 01 = sampled; we always record

    @staticmethod
    def new() -> "TraceContext":
        """A fresh root context (random 128-bit trace / 64-bit span id)."""
        return TraceContext(trace_id=os.urandom(16).hex(),
                            span_id=os.urandom(8).hex())

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — the position handed to a callee."""
        return TraceContext(trace_id=self.trace_id,
                            span_id=os.urandom(8).hex(),
                            flags=self.flags)

    def to_traceparent(self) -> str:
        """Serialize as a W3C ``traceparent`` header value."""
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"

    @staticmethod
    def parse(header: Optional[str]) -> Optional["TraceContext"]:
        """Strict W3C parse.  Malformed, oversized, wrong-version or
        all-zero-id headers return None — callers fall back to a fresh
        context; a bad header must never break a request."""
        if not header or not isinstance(header, str):
            return None
        header = header.strip()
        if len(header) > _TRACEPARENT_MAX_LEN:
            return None
        # no .lower(): the W3C grammar is lowercase-only, and uppercase hex
        # is specified as invalid rather than normalizable
        m = _TRACEPARENT_RE.match(header)
        if m is None:
            return None
        trace_id, span_id, flags = m.group(1), m.group(2), m.group(3)
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return TraceContext(trace_id=trace_id, span_id=span_id,
                            flags=int(flags, 16))

    @staticmethod
    def from_env() -> Optional["TraceContext"]:
        """Parse the context a parent process exported for us, if any."""
        return TraceContext.parse(os.environ.get(TRACEPARENT_ENV))


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

@dataclass
class Span:
    """One timed unit of work in the trace tree."""

    name: str
    span_id: str
    parent_id: Optional[str]
    start_s: float              # monotonic, relative to the tracer's epoch
    end_s: Optional[float] = None
    status: str = "ok"          # "ok" | "error"
    attrs: Dict[str, Any] = field(default_factory=dict)
    thread: int = 0
    start_wall_s: float = 0.0   # absolute wall clock at span start
    trace_id: str = ""          # W3C 128-bit trace id (hex)
    w3c_id: str = ""            # W3C 64-bit span id (hex)
    links: List[Dict[str, str]] = field(default_factory=list)

    @property
    def duration_s(self) -> float:
        return (self.end_s if self.end_s is not None else self.start_s) \
            - self.start_s

    def context(self) -> TraceContext:
        """This span's position as a propagatable TraceContext."""
        return TraceContext(trace_id=self.trace_id, span_id=self.w3c_id)

    def to_json(self) -> Dict[str, Any]:
        out = {"name": self.name, "spanId": self.span_id,
               "parentId": self.parent_id,
               "startS": round(self.start_s, 6),
               "durationS": round(self.duration_s, 6),
               "status": self.status, "attrs": dict(self.attrs),
               "thread": self.thread,
               "startWallS": round(self.start_wall_s, 3),
               "traceId": self.trace_id, "w3cSpanId": self.w3c_id}
        if self.links:
            out["links"] = [dict(l) for l in self.links]
        return out


def _proc_label(run_name: str, worker_id, rank) -> str:
    """Perfetto process-lane label: run name plus whichever identities
    apply — serving-pool worker id and/or host-group rank."""
    label = run_name
    if worker_id is not None:
        label += f" [worker {worker_id}]"
    if rank is not None:
        label += f" [rank {rank}]"
    return label


class Tracer:
    """Thread-safe span collector.  See module docstring for the parenting
    rule; all mutation happens under one lock, so concurrent serving/
    validator threads can record freely."""

    #: Default span ring-buffer bound: a serving process records forever,
    #: so the completed-span store must not grow without bound.
    DEFAULT_MAX_SPANS = 65536

    def __init__(self, run_name: str = "run", *,
                 max_spans: Optional[int] = None,
                 parent: Optional[TraceContext] = None,
                 worker_id: Optional[str] = None,
                 rank: Optional[int] = None):
        self.run_name = run_name
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # completed spans, finish order; bounded ring (oldest dropped first)
        self._spans: "collections.deque[Span]" = collections.deque()
        self._stacks: Dict[int, List[Span]] = {}   # open spans per thread
        self._install_thread: Optional[int] = None
        self.t0_mono = time.monotonic()
        self.t0_wall = time.time()
        if max_spans is None:
            try:
                max_spans = int(os.environ.get(
                    "TRANSMOGRIFAI_TRACE_MAX_SPANS", self.DEFAULT_MAX_SPANS))
            except ValueError:
                max_spans = self.DEFAULT_MAX_SPANS
        self.max_spans = max(1, max_spans)
        self._dropped = 0
        self._drop_noted = False
        self.parent_ctx = parent
        self.worker_id = worker_id
        # host-group rank (multi-process training); like worker_id it rides
        # the exports so merge_traces can label one lane per host
        self.rank = rank
        # every span this tracer records shares one trace id unless an
        # explicit per-request ctx overrides it
        self.trace_id = parent.trace_id if parent else os.urandom(16).hex()
        self._root_w3c = parent.span_id if parent else os.urandom(8).hex()

    def root_context(self) -> TraceContext:
        """The tracer-level context new work inherits when no request
        context is active (the parent ctx we were seeded with, else the
        tracer's own root position)."""
        if self.parent_ctx is not None:
            return self.parent_ctx
        return TraceContext(trace_id=self.trace_id, span_id=self._root_w3c)

    @property
    def spans_dropped(self) -> int:
        with self._lock:
            return self._dropped

    def _record_locked(self, sp: Span) -> int:
        """Append a completed span, evicting the oldest past the bound.
        Caller holds ``self._lock``; returns how many spans were evicted
        (the drop NOTE must be emitted after the lock is released —
        ``record_failure`` re-enters this tracer via ``current_span_id``)."""
        self._spans.append(sp)
        dropped = 0
        while len(self._spans) > self.max_spans:
            self._spans.popleft()
            dropped += 1
        self._dropped += dropped
        return dropped

    def _note_drops(self, dropped: int) -> None:
        """Post-lock bookkeeping for evicted spans: bump the global drop
        counter and, on the FIRST drop this tracer sees, record a degraded
        note so operators learn the trace is now a ring, not a log."""
        if dropped <= 0:
            return
        REGISTRY.counter("telemetry.spans_dropped_total").inc(dropped)
        with self._lock:
            first = not self._drop_noted
            self._drop_noted = True
        if first:
            try:
                # lazy import — telemetry must stay import-light here
                from .resilience import record_failure
                record_failure(
                    "telemetry", "degraded", "span ring buffer full",
                    point="tracer.max_spans", run_name=self.run_name,
                    max_spans=self.max_spans)
            except Exception:  # noqa: BLE001 — never fail a span close
                pass

    # -- parenting ---------------------------------------------------------
    def _parent(self, tid: int) -> Optional[Span]:
        stack = self._stacks.get(tid)
        if stack:
            return stack[-1]
        if self._install_thread is not None:
            root = self._stacks.get(self._install_thread)
            if root:
                return root[-1]
        return None

    def current_span(self) -> Optional[Span]:
        """The calling thread's innermost open span (falling back to the
        install thread's — the span a worker's work is logically inside)."""
        with self._lock:
            return self._parent(threading.get_ident())

    def current_span_id(self) -> Optional[str]:
        s = self.current_span()
        return s.span_id if s is not None else None

    # -- recording ---------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, *, ctx: Optional[TraceContext] = None,
             links: Optional[List[TraceContext]] = None, **attrs):
        """Record one span.  ``ctx`` pins the span to an explicit W3C trace
        position (request-scoped tracing across processes); ``links`` record
        causally-related-but-not-parent contexts (a batch span links every
        request it coalesced).  Without ``ctx`` the span rides the tracer's
        own trace id with a fresh 64-bit position."""
        tid = threading.get_ident()
        now = time.monotonic() - self.t0_mono
        with self._lock:
            parent = self._parent(tid)
            sp = Span(name=name, span_id=f"s{next(self._ids)}",
                      parent_id=parent.span_id if parent else None,
                      start_s=now, attrs=dict(attrs), thread=tid,
                      start_wall_s=time.time(),
                      trace_id=ctx.trace_id if ctx else self.trace_id,
                      w3c_id=ctx.span_id if ctx else os.urandom(8).hex(),
                      links=[{"traceId": l.trace_id, "spanId": l.span_id}
                             for l in (links or [])])
            self._stacks.setdefault(tid, []).append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.status = "error"
            sp.attrs.setdefault("error", f"{type(e).__name__}: {e}")
            raise
        finally:
            sp.end_s = time.monotonic() - self.t0_mono
            with self._lock:
                stack = self._stacks.get(tid, [])
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] is sp:      # robust to interleaved exits
                        del stack[i]
                        break
                dropped = self._record_locked(sp)
            self._note_drops(dropped)

    def event(self, name: str, *, ctx: Optional[TraceContext] = None,
              **attrs) -> Span:
        """A zero-duration marker span (e.g. a racing prune decision)."""
        now = time.monotonic() - self.t0_mono
        tid = threading.get_ident()
        with self._lock:
            parent = self._parent(tid)
            sp = Span(name=name, span_id=f"s{next(self._ids)}",
                      parent_id=parent.span_id if parent else None,
                      start_s=now, end_s=now, attrs=dict(attrs), thread=tid,
                      start_wall_s=time.time(),
                      trace_id=ctx.trace_id if ctx else self.trace_id,
                      w3c_id=ctx.span_id if ctx else os.urandom(8).hex())
            dropped = self._record_locked(sp)
        self._note_drops(dropped)
        return sp

    @property
    def spans(self) -> List[Span]:
        """Completed spans (finish order); open spans are not included."""
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- export ------------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {"runName": self.run_name, "t0WallS": round(self.t0_wall, 3),
                "traceId": self.trace_id, "pid": os.getpid(),
                "workerId": self.worker_id, "rank": self.rank,
                "spansDropped": self.spans_dropped,
                "spans": [s.to_json() for s in self.spans]}

    def export_chrome_trace(self, path: str) -> str:
        """Write the trace in Chrome trace-event JSON ("X" complete events,
        microsecond timestamps) — loadable in Perfetto / chrome://tracing.
        Span ids and parent ids ride in ``args`` so the span tree survives
        the round trip (``load_trace`` reads them back).  Alongside the span
        events the export carries ``process_name`` metadata and a
        ``clock_sync`` event anchored at ``t0_wall`` — two independently
        exported traces align on a shared wall-clock timeline in Perfetto
        even without ``merge_traces``."""
        pid = os.getpid()
        proc_label = _proc_label(self.run_name, self.worker_id, self.rank)
        events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": proc_label}},
            # wall-clock anchor: issue_ts is the absolute wall time (µs) at
            # the tracer epoch (ts=0), so cross-process merges re-align by
            # shifting each file's events onto one wall timeline
            {"name": "clock_sync", "ph": "c", "pid": pid, "tid": 0,
             "ts": 0.0,
             "args": {"sync_id": self.trace_id,
                      "issue_ts": round(self.t0_wall * 1e6, 1)}},
        ]
        for s in self.spans:
            args = {"spanId": s.span_id, "parentId": s.parent_id,
                    "status": s.status, "traceId": s.trace_id,
                    "w3cSpanId": s.w3c_id, **s.attrs}
            if s.links:
                args["links"] = [dict(l) for l in s.links]
            events.append({
                "name": s.name, "cat": s.name.split(".", 1)[0], "ph": "X",
                "ts": round(s.start_s * 1e6, 1),
                "dur": round(max(s.duration_s, 0.0) * 1e6, 1),
                "pid": pid, "tid": s.thread, "args": args})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"runName": self.run_name,
                             "t0WallS": round(self.t0_wall, 3),
                             "traceId": self.trace_id, "pid": pid,
                             "workerId": self.worker_id, "rank": self.rank,
                             "spansDropped": self.spans_dropped}}
        with open(path, "w") as fh:
            json.dump(doc, fh, default=str)
        return path

    def slowest(self, top_n: int = 10) -> List[Span]:
        return sorted(self.spans, key=lambda s: -s.duration_s)[:top_n]


# --------------------------------------------------------------------------
# ambient tracer (mirrors resilience.use_failure_log)
# --------------------------------------------------------------------------

# Process-global stack, NOT thread-local: the validator's candidate fits run
# on a thread pool and must record into the tracer their orchestrating
# train() installed.  Concurrent *independent* traced runs in one process
# should pass explicit tracers instead.
_TRACER_STACK: List[Tracer] = []
_TRACER_LOCK = threading.Lock()


def active_tracer() -> Optional[Tracer]:
    """The innermost installed tracer, or None (spans become no-ops)."""
    with _TRACER_LOCK:
        return _TRACER_STACK[-1] if _TRACER_STACK else None


@contextlib.contextmanager
def use_tracer(tracer: Tracer):
    """Install ``tracer`` as the ambient tracer for the dynamic extent."""
    with _TRACER_LOCK:
        _TRACER_STACK.append(tracer)
        if tracer._install_thread is None:
            tracer._install_thread = threading.get_ident()
    try:
        yield tracer
    finally:
        with _TRACER_LOCK:
            for i in range(len(_TRACER_STACK) - 1, -1, -1):
                if _TRACER_STACK[i] is tracer:
                    del _TRACER_STACK[i]
                    break


@contextlib.contextmanager
def span(name: str, *, ctx: Optional[TraceContext] = None,
         links: Optional[List[TraceContext]] = None, **attrs):
    """Record a span on the ambient tracer; a no-op (one attribute check)
    when tracing is off — instrumentation sites pay nothing by default."""
    tracer = active_tracer()
    if tracer is None:
        yield None
        return
    with tracer.span(name, ctx=ctx, links=links, **attrs) as sp:
        yield sp


def event(name: str, *, ctx: Optional[TraceContext] = None,
          **attrs) -> Optional[Span]:
    """Record a zero-duration marker on the ambient tracer (None when off)."""
    tracer = active_tracer()
    if tracer is None:
        return None
    return tracer.event(name, ctx=ctx, **attrs)


def current_span_id() -> Optional[str]:
    """The calling thread's active span id on the ambient tracer, or None.
    ``resilience.FailureLog`` uses this to correlate failures with spans."""
    tracer = active_tracer()
    if tracer is None:
        return None
    return tracer.current_span_id()


def current_trace_context() -> Optional[TraceContext]:
    """The W3C position to propagate to a callee or child process right
    now: the innermost open span's context on the ambient tracer (falling
    back to the tracer root), else the context a parent process exported
    via ``TRANSMOGRIFAI_TRACEPARENT``, else None."""
    tracer = active_tracer()
    if tracer is not None:
        sp = tracer.current_span()
        if sp is not None and sp.trace_id and sp.w3c_id:
            return sp.context()
        return tracer.root_context()
    return TraceContext.from_env()


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------

class Counter:
    """Monotonic thread-safe counter.  ``inc(trace_id=...)`` remembers the
    last incrementing trace as an OpenMetrics exemplar (shed counters link
    a 429 spike straight to a concrete request trace)."""

    __slots__ = ("name", "_value", "_lock", "_exemplar")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()
        self._exemplar: Optional[Dict[str, Any]] = None

    def inc(self, n: Union[int, float] = 1,
            trace_id: Optional[str] = None) -> None:
        with self._lock:
            self._value += n
            if trace_id:
                self._exemplar = {"traceId": trace_id, "value": n}

    def exemplar(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._exemplar) if self._exemplar else None

    @property
    def value(self) -> Union[int, float]:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value: either set explicitly or read through a
    callback (for absorbing external sources like ``compile_stats``)."""

    __slots__ = ("name", "_value", "_fn", "_lock")

    def __init__(self, name: str, fn: Optional[Callable[[], Any]] = None):
        self.name = name
        self._value: Any = 0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, v: Any) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> Any:
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a dead source reads as 0
                return 0
        with self._lock:
            return self._value


class MetricsRegistry:
    """Central named-metric namespace: counters, gauges, latency
    histograms.  ``counter``/``gauge``/``histogram`` are get-or-create, so
    call sites never race on registration; ``snapshot()`` renders the whole
    registry as one JSON-safe dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, LatencyHistogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str,
              fn: Optional[Callable[[], Any]] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn)
            elif fn is not None:
                g._fn = fn
            return g

    def histogram(self, name: str) -> LatencyHistogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = LatencyHistogram()
            return h

    def counters(self) -> Dict[str, Union[int, float]]:
        with self._lock:
            items = list(self._counters.items())
        return {k: c.value for k, c in items}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        return {
            "counters": {k: c.value for k, c in counters},
            "gauges": {k: g.value for k, g in gauges},
            "histograms": {k: h.snapshot() for k, h in hists},
        }


def _default_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    # read-through gauges over the legacy profiling globals: ONE namespace
    # re-exports every scattered counter without moving its source of truth
    # (jax.monitoring listeners keep writing into profiling._COMPILE_STATS)
    reg.gauge("compile.compile_s", lambda: compile_stats()["compile_s"])
    reg.gauge("compile.backend_compiles",
              lambda: compile_stats()["backend_compiles"])
    reg.gauge("compile.cache_hits", lambda: compile_stats()["cache_hits"])
    reg.gauge("compile.cache_misses",
              lambda: compile_stats()["cache_misses"])
    reg.gauge("racing.cv_fits_saved",
              lambda: racing_stats()["cv_fits_saved"])
    reg.gauge("racing.families_raced",
              lambda: racing_stats()["families_raced"])
    reg.gauge("racing.points_pruned",
              lambda: racing_stats()["points_pruned"])
    reg.gauge("host_link.bytes", host_link_bytes)

    def _sparse_stat(key):
        def read():
            # lazy import: telemetry must not pull jax at module import
            from .sparse.transform import sparse_stats
            return sparse_stats()[key]
        return read

    reg.gauge("sparse.nnz_total", _sparse_stat("nnz_total"))
    reg.gauge("sparse.matrices", _sparse_stat("matrices"))
    reg.gauge("sparse.density", _sparse_stat("density"))

    def _dt_stat(key):
        def read():
            # lazy import: telemetry must not pull jax at module import
            from .parallel.device_table import device_table_stats
            return device_table_stats()[key]
        return read

    # one device data plane (ISSUE 19): DeviceTable sparse shipments —
    # logical rows shipped, real COO entries over the link, ladder pad
    # entries synthesized on-device, per-device shards assembled
    reg.gauge("device_table.tables", _dt_stat("tables"))
    reg.gauge("device_table.rows", _dt_stat("rows"))
    reg.gauge("device_table.nnz_streamed", _dt_stat("nnz_streamed"))
    reg.gauge("device_table.pad_entries", _dt_stat("pad_entries"))
    reg.gauge("device_table.shards", _dt_stat("shards"))

    def _stream_stat(key):
        def read():
            # lazy import: telemetry must not pull jax at module import
            from .parallel.streaming import streaming_stats
            return streaming_stats()[key]
        return read

    # mesh streaming (ISSUE 10): mesh.devices / mesh.chunk_bytes are set by
    # maybe_data_mesh / stream_to_device; peak staging + streamed pad rows
    # read through the streamer's own stats.  host_to_device_bytes_total is
    # a plain counter the streamer increments per chunk.
    reg.gauge("mesh.devices")
    reg.gauge("mesh.chunk_bytes")
    reg.counter("host_to_device_bytes_total")
    reg.gauge("mesh.peak_staging_bytes", _stream_stat("peak_staging_bytes"))
    reg.gauge("mesh.stream_chunks", _stream_stat("chunks"))
    reg.gauge("mesh.pad_rows_streamed", _stream_stat("pad_rows"))

    # device-runtime supervision (ISSUE 11): the heartbeat sets
    # supervisor.state (0 available / 1 degraded / 2 outage) and bumps the
    # outage/probe counters; watchdog.abandoned_total counts zombie worker
    # threads run_with_deadline left behind (the failure mode only the
    # subprocess supervisor can actually reclaim); multihost gauges are set
    # by init_distributed.
    reg.gauge("supervisor.state")
    reg.gauge("supervisor.last_probe_latency_s")
    reg.counter("supervisor.probes_total")
    reg.counter("supervisor.outages_total")
    reg.counter("supervisor.mesh_degrades_total")
    reg.counter("watchdog.abandoned_total")
    reg.gauge("multihost.process_count")
    reg.gauge("multihost.initialized")

    def _device_cap():
        # lazy import: telemetry must not pull jax at module import
        from .parallel.supervisor import device_cap
        c = device_cap()
        return -1 if c is None else c

    reg.gauge("supervisor.device_cap", _device_cap)
    return reg


#: Process-default registry.  Serving engines create their own instance per
#: engine (counters reset with the engine); train/bench report through this.
REGISTRY = _default_registry()


# --------------------------------------------------------------------------
# summaries + CLI rendering
# --------------------------------------------------------------------------

def telemetry_summary(tracer: Optional[Tracer] = None,
                      registry: Optional[MetricsRegistry] = None,
                      top_n: int = 15) -> Dict[str, Any]:
    """The ``telemetry.json`` payload: top slowest spans (with tree
    context), span counts by name, and the full metrics snapshot.  Bundled
    next to saved models and embedded in bench aux."""
    tracer = tracer if tracer is not None else active_tracer()
    registry = registry if registry is not None else REGISTRY
    out: Dict[str, Any] = {"metrics": registry.snapshot()}
    if tracer is not None:
        spans = tracer.spans
        by_name: Dict[str, Dict[str, Any]] = {}
        for s in spans:
            agg = by_name.setdefault(
                s.name, {"count": 0, "totalS": 0.0, "maxS": 0.0,
                         "errors": 0})
            agg["count"] += 1
            agg["totalS"] = round(agg["totalS"] + s.duration_s, 6)
            agg["maxS"] = round(max(agg["maxS"], s.duration_s), 6)
            agg["errors"] += int(s.status == "error")
        out["trace"] = {
            "runName": tracer.run_name,
            "spanCount": len(spans),
            "slowestSpans": [s.to_json() for s in tracer.slowest(top_n)],
            "byName": by_name,
        }
    return out


def write_telemetry_summary(path: str,
                            tracer: Optional[Tracer] = None,
                            registry: Optional[MetricsRegistry] = None
                            ) -> str:
    with open(path, "w") as fh:
        json.dump(telemetry_summary(tracer, registry), fh, indent=2,
                  default=str)
    return path


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read spans back from either export format: Chrome trace-event JSON
    (``traceEvents`` with span ids in ``args``) or ``Tracer.to_json()``
    (``spans``).  Returns a list of span dicts with name/spanId/parentId/
    durationS/status keys."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "spans" in doc:
        return list(doc["spans"])
    events = (doc or {}).get("traceEvents", []) if isinstance(doc, dict) \
        else []
    spans = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        spans.append({"name": ev.get("name", "?"),
                      "spanId": args.get("spanId"),
                      "parentId": args.get("parentId"),
                      "startS": float(ev.get("ts", 0.0)) / 1e6,
                      "durationS": float(ev.get("dur", 0.0)) / 1e6,
                      "status": args.get("status", "ok"),
                      "traceId": args.get("traceId", ""),
                      "w3cSpanId": args.get("w3cSpanId", ""),
                      "links": args.get("links") or [],
                      "attrs": {k: v for k, v in args.items()
                                if k not in ("spanId", "parentId", "status",
                                             "traceId", "w3cSpanId",
                                             "links")}})
    return spans


# --------------------------------------------------------------------------
# cross-process trace assembly
# --------------------------------------------------------------------------

def merge_traces(paths: Iterable[str],
                 out_path: Optional[str] = None) -> Dict[str, Any]:
    """Align per-process trace exports onto one wall-clock-anchored Perfetto
    timeline.  Accepts both export formats (chrome trace-event JSON with an
    ``otherData.t0WallS`` anchor, and ``Tracer.to_json()`` native files).
    The earliest ``t0WallS`` across files becomes the merged epoch; each
    file's events are shifted by its anchor delta and its pid remapped to a
    stable per-file index so Perfetto renders one process lane per worker
    (labelled via ``process_name`` metadata with the worker id)."""
    docs: List[Dict[str, Any]] = []
    for p in paths:
        with open(p) as fh:
            doc = json.load(fh)
        if not isinstance(doc, dict):
            continue
        if "spans" in doc:          # native Tracer.to_json() format
            t0 = float(doc.get("t0WallS", 0.0))
            events = []
            for s in doc["spans"]:
                args = {"spanId": s.get("spanId"),
                        "parentId": s.get("parentId"),
                        "status": s.get("status", "ok"),
                        "traceId": s.get("traceId", ""),
                        "w3cSpanId": s.get("w3cSpanId", ""),
                        **(s.get("attrs") or {})}
                if s.get("links"):
                    args["links"] = s["links"]
                events.append({
                    "name": s.get("name", "?"),
                    "cat": str(s.get("name", "?")).split(".", 1)[0],
                    "ph": "X",
                    "ts": round(float(s.get("startS", 0.0)) * 1e6, 1),
                    "dur": round(
                        max(float(s.get("durationS", 0.0)), 0.0) * 1e6, 1),
                    "pid": int(doc.get("pid", 0)),
                    "tid": s.get("thread", 0), "args": args})
            other = {"runName": doc.get("runName", "run"), "t0WallS": t0,
                     "traceId": doc.get("traceId", ""),
                     "pid": doc.get("pid", 0),
                     "workerId": doc.get("workerId"),
                     "rank": doc.get("rank")}
        else:
            events = [e for e in doc.get("traceEvents", [])
                      if e.get("ph") == "X"]
            other = dict(doc.get("otherData") or {})
        docs.append({"path": p, "events": events, "other": other,
                     "t0": float(other.get("t0WallS", 0.0) or 0.0)})
    if not docs:
        merged: Dict[str, Any] = {"traceEvents": [],
                                  "displayTimeUnit": "ms",
                                  "otherData": {"merged": True, "files": []}}
        if out_path:
            with open(out_path, "w") as fh:
                json.dump(merged, fh, default=str)
        return merged

    anchor = min(d["t0"] for d in docs)
    events: List[Dict[str, Any]] = []
    files_meta = []
    for idx, d in enumerate(docs):
        shift_us = (d["t0"] - anchor) * 1e6
        worker_id = d["other"].get("workerId")
        rank = d["other"].get("rank")
        run_name = d["other"].get("runName", "run")
        label = _proc_label(run_name, worker_id, rank)
        events.append({"name": "process_name", "ph": "M", "pid": idx,
                       "tid": 0, "args": {"name": label}})
        events.append({"name": "clock_sync", "ph": "c", "pid": idx,
                       "tid": 0, "ts": round(shift_us, 1),
                       "args": {"sync_id": d["other"].get("traceId", ""),
                                "issue_ts": round(d["t0"] * 1e6, 1)}})
        for ev in d["events"]:
            ev = dict(ev)
            ev["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 1)
            ev["pid"] = idx
            events.append(ev)
        files_meta.append({"path": d["path"], "runName": run_name,
                           "workerId": worker_id, "rank": rank,
                           "originalPid": d["other"].get("pid"),
                           "t0WallS": d["t0"]})
    merged = {"traceEvents": events, "displayTimeUnit": "ms",
              "otherData": {"merged": True, "t0WallS": anchor,
                            "files": files_meta}}
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(merged, fh, default=str)
    return merged


def render_trace_summary(path: str, top_n: int = 10) -> str:
    """The ``trace-summary`` subcommand's table: top-N slowest spans with
    their depth-in-tree, duration, status and attributes."""
    spans = load_trace(path)
    if not spans:
        return f"{path}: no spans"
    by_id = {s.get("spanId"): s for s in spans if s.get("spanId")}

    def depth(s: Dict[str, Any]) -> int:
        d, seen = 0, set()
        while s.get("parentId") and s["parentId"] in by_id \
                and s["parentId"] not in seen:
            seen.add(s["parentId"])
            s = by_id[s["parentId"]]
            d += 1
        return d

    rows = sorted(spans, key=lambda s: -float(s.get("durationS", 0.0)))
    rows = rows[:top_n]
    name_w = max(len("span"),
                 max(len(s.get("name", "?")) + 2 * depth(s) for s in rows))
    lines = [f"{path}: {len(spans)} span(s); top {len(rows)} by duration",
             f"{'span'.ljust(name_w)}  {'seconds':>10}  {'status':<6}  attrs"]
    for s in rows:
        nm = "  " * depth(s) + s.get("name", "?")
        attrs = s.get("attrs") or {}
        attr_s = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        if len(attr_s) > 60:
            attr_s = attr_s[:57] + "..."
        lines.append(f"{nm.ljust(name_w)}  "
                     f"{float(s.get('durationS', 0.0)):>10.4f}  "
                     f"{s.get('status', 'ok'):<6}  {attr_s}")
    return "\n".join(lines)
