"""Unified telemetry — structured trace spans + a central metrics registry.

The runtime story used to be scattered: compile stats, racing counters,
host-link bytes, serving latency histograms and the FailureLog each lived in
their own ad-hoc global with no shared run context.  This module gives every
run one measurement substrate, in the style of Dapper/OpenTelemetry span
trees and Chrome ``chrome://tracing`` timelines:

* ``Tracer`` — thread-safe producer of nested spans.  ``tracer.span(name,
  **attrs)`` is a context manager recording monotonic wall times, a span id,
  the parent span id, a status (``ok``/``error``) and attributes.  Parenting
  is per-thread (each thread nests its own spans); a worker thread with no
  open span of its own parents to the innermost open span of the thread that
  installed the tracer — so the validator's thread-pool candidate fits nest
  under the orchestrating ``selector.sweep`` span.
* ``use_tracer(tracer)`` — the ambient run context, mirroring
  ``resilience.use_failure_log``: deep code calls the module-level
  ``span(...)`` / ``event(...)`` helpers, which no-op (near-zero cost) when
  no tracer is installed.
* ``MetricsRegistry`` — named ``Counter``s, ``Gauge``s and
  ``LatencyHistogram``s behind one namespace.  The process-default
  ``REGISTRY`` absorbs and re-exports today's scattered sources
  (``profiling.compile_stats``, ``profiling.racing_stats``,
  ``profiling.host_link_bytes``) as read-through gauges, so one
  ``snapshot()`` answers "what did this process compile/prune/transfer".
* Exports — ``tracer.export_chrome_trace(path)`` writes Perfetto-loadable
  Chrome trace-event JSON; ``telemetry_summary()`` builds the
  ``telemetry.json`` bundled next to saved models and into bench aux;
  ``render_trace_summary()`` prints the top-N slowest-spans table behind the
  ``transmogrifai_tpu trace-summary`` subcommand.

Span ids correlate with the failure layer: ``resilience.FailureLog.record``
stamps the recording thread's active span id into each event's detail, and
``FaultInjector`` remembers the span each injected fault fired inside — a
chaos-test failure points at the exact span.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from .profiling import (LatencyHistogram, compile_stats, host_link_bytes,
                        racing_stats)

__all__ = [
    "Span", "Tracer", "use_tracer", "active_tracer", "span", "event",
    "current_span_id", "Counter", "Gauge", "MetricsRegistry", "REGISTRY",
    "LatencyHistogram", "telemetry_summary", "write_telemetry_summary",
    "render_trace_summary", "load_trace",
]


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

@dataclass
class Span:
    """One timed unit of work in the trace tree."""

    name: str
    span_id: str
    parent_id: Optional[str]
    start_s: float              # monotonic, relative to the tracer's epoch
    end_s: Optional[float] = None
    status: str = "ok"          # "ok" | "error"
    attrs: Dict[str, Any] = field(default_factory=dict)
    thread: int = 0
    start_wall_s: float = 0.0   # absolute wall clock at span start

    @property
    def duration_s(self) -> float:
        return (self.end_s if self.end_s is not None else self.start_s) \
            - self.start_s

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "spanId": self.span_id,
                "parentId": self.parent_id,
                "startS": round(self.start_s, 6),
                "durationS": round(self.duration_s, 6),
                "status": self.status, "attrs": dict(self.attrs),
                "thread": self.thread,
                "startWallS": round(self.start_wall_s, 3)}


class Tracer:
    """Thread-safe span collector.  See module docstring for the parenting
    rule; all mutation happens under one lock, so concurrent serving/
    validator threads can record freely."""

    def __init__(self, run_name: str = "run"):
        self.run_name = run_name
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._spans: List[Span] = []          # completed, in finish order
        self._stacks: Dict[int, List[Span]] = {}   # open spans per thread
        self._install_thread: Optional[int] = None
        self.t0_mono = time.monotonic()
        self.t0_wall = time.time()

    # -- parenting ---------------------------------------------------------
    def _parent(self, tid: int) -> Optional[Span]:
        stack = self._stacks.get(tid)
        if stack:
            return stack[-1]
        if self._install_thread is not None:
            root = self._stacks.get(self._install_thread)
            if root:
                return root[-1]
        return None

    def current_span(self) -> Optional[Span]:
        """The calling thread's innermost open span (falling back to the
        install thread's — the span a worker's work is logically inside)."""
        with self._lock:
            return self._parent(threading.get_ident())

    def current_span_id(self) -> Optional[str]:
        s = self.current_span()
        return s.span_id if s is not None else None

    # -- recording ---------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        tid = threading.get_ident()
        now = time.monotonic() - self.t0_mono
        with self._lock:
            parent = self._parent(tid)
            sp = Span(name=name, span_id=f"s{next(self._ids)}",
                      parent_id=parent.span_id if parent else None,
                      start_s=now, attrs=dict(attrs), thread=tid,
                      start_wall_s=time.time())
            self._stacks.setdefault(tid, []).append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.status = "error"
            sp.attrs.setdefault("error", f"{type(e).__name__}: {e}")
            raise
        finally:
            sp.end_s = time.monotonic() - self.t0_mono
            with self._lock:
                stack = self._stacks.get(tid, [])
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] is sp:      # robust to interleaved exits
                        del stack[i]
                        break
                self._spans.append(sp)

    def event(self, name: str, **attrs) -> Span:
        """A zero-duration marker span (e.g. a racing prune decision)."""
        now = time.monotonic() - self.t0_mono
        tid = threading.get_ident()
        with self._lock:
            parent = self._parent(tid)
            sp = Span(name=name, span_id=f"s{next(self._ids)}",
                      parent_id=parent.span_id if parent else None,
                      start_s=now, end_s=now, attrs=dict(attrs), thread=tid,
                      start_wall_s=time.time())
            self._spans.append(sp)
            return sp

    @property
    def spans(self) -> List[Span]:
        """Completed spans (finish order); open spans are not included."""
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- export ------------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {"runName": self.run_name, "t0WallS": round(self.t0_wall, 3),
                "spans": [s.to_json() for s in self.spans]}

    def export_chrome_trace(self, path: str) -> str:
        """Write the trace in Chrome trace-event JSON ("X" complete events,
        microsecond timestamps) — loadable in Perfetto / chrome://tracing.
        Span ids and parent ids ride in ``args`` so the span tree survives
        the round trip (``load_trace`` reads them back)."""
        events = []
        for s in self.spans:
            events.append({
                "name": s.name, "cat": s.name.split(".", 1)[0], "ph": "X",
                "ts": round(s.start_s * 1e6, 1),
                "dur": round(max(s.duration_s, 0.0) * 1e6, 1),
                "pid": 0, "tid": s.thread,
                "args": {"spanId": s.span_id, "parentId": s.parent_id,
                         "status": s.status, **s.attrs}})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"runName": self.run_name,
                             "t0WallS": round(self.t0_wall, 3)}}
        with open(path, "w") as fh:
            json.dump(doc, fh, default=str)
        return path

    def slowest(self, top_n: int = 10) -> List[Span]:
        return sorted(self.spans, key=lambda s: -s.duration_s)[:top_n]


# --------------------------------------------------------------------------
# ambient tracer (mirrors resilience.use_failure_log)
# --------------------------------------------------------------------------

# Process-global stack, NOT thread-local: the validator's candidate fits run
# on a thread pool and must record into the tracer their orchestrating
# train() installed.  Concurrent *independent* traced runs in one process
# should pass explicit tracers instead.
_TRACER_STACK: List[Tracer] = []
_TRACER_LOCK = threading.Lock()


def active_tracer() -> Optional[Tracer]:
    """The innermost installed tracer, or None (spans become no-ops)."""
    with _TRACER_LOCK:
        return _TRACER_STACK[-1] if _TRACER_STACK else None


@contextlib.contextmanager
def use_tracer(tracer: Tracer):
    """Install ``tracer`` as the ambient tracer for the dynamic extent."""
    with _TRACER_LOCK:
        _TRACER_STACK.append(tracer)
        if tracer._install_thread is None:
            tracer._install_thread = threading.get_ident()
    try:
        yield tracer
    finally:
        with _TRACER_LOCK:
            for i in range(len(_TRACER_STACK) - 1, -1, -1):
                if _TRACER_STACK[i] is tracer:
                    del _TRACER_STACK[i]
                    break


@contextlib.contextmanager
def span(name: str, **attrs):
    """Record a span on the ambient tracer; a no-op (one attribute check)
    when tracing is off — instrumentation sites pay nothing by default."""
    tracer = active_tracer()
    if tracer is None:
        yield None
        return
    with tracer.span(name, **attrs) as sp:
        yield sp


def event(name: str, **attrs) -> Optional[Span]:
    """Record a zero-duration marker on the ambient tracer (None when off)."""
    tracer = active_tracer()
    if tracer is None:
        return None
    return tracer.event(name, **attrs)


def current_span_id() -> Optional[str]:
    """The calling thread's active span id on the ambient tracer, or None.
    ``resilience.FailureLog`` uses this to correlate failures with spans."""
    tracer = active_tracer()
    if tracer is None:
        return None
    return tracer.current_span_id()


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------

class Counter:
    """Monotonic thread-safe counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: Union[int, float] = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Union[int, float]:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value: either set explicitly or read through a
    callback (for absorbing external sources like ``compile_stats``)."""

    __slots__ = ("name", "_value", "_fn", "_lock")

    def __init__(self, name: str, fn: Optional[Callable[[], Any]] = None):
        self.name = name
        self._value: Any = 0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, v: Any) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> Any:
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a dead source reads as 0
                return 0
        with self._lock:
            return self._value


class MetricsRegistry:
    """Central named-metric namespace: counters, gauges, latency
    histograms.  ``counter``/``gauge``/``histogram`` are get-or-create, so
    call sites never race on registration; ``snapshot()`` renders the whole
    registry as one JSON-safe dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, LatencyHistogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str,
              fn: Optional[Callable[[], Any]] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn)
            elif fn is not None:
                g._fn = fn
            return g

    def histogram(self, name: str) -> LatencyHistogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = LatencyHistogram()
            return h

    def counters(self) -> Dict[str, Union[int, float]]:
        with self._lock:
            items = list(self._counters.items())
        return {k: c.value for k, c in items}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        return {
            "counters": {k: c.value for k, c in counters},
            "gauges": {k: g.value for k, g in gauges},
            "histograms": {k: h.snapshot() for k, h in hists},
        }


def _default_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    # read-through gauges over the legacy profiling globals: ONE namespace
    # re-exports every scattered counter without moving its source of truth
    # (jax.monitoring listeners keep writing into profiling._COMPILE_STATS)
    reg.gauge("compile.compile_s", lambda: compile_stats()["compile_s"])
    reg.gauge("compile.backend_compiles",
              lambda: compile_stats()["backend_compiles"])
    reg.gauge("compile.cache_hits", lambda: compile_stats()["cache_hits"])
    reg.gauge("compile.cache_misses",
              lambda: compile_stats()["cache_misses"])
    reg.gauge("racing.cv_fits_saved",
              lambda: racing_stats()["cv_fits_saved"])
    reg.gauge("racing.families_raced",
              lambda: racing_stats()["families_raced"])
    reg.gauge("racing.points_pruned",
              lambda: racing_stats()["points_pruned"])
    reg.gauge("host_link.bytes", host_link_bytes)

    def _sparse_stat(key):
        def read():
            # lazy import: telemetry must not pull jax at module import
            from .sparse.transform import sparse_stats
            return sparse_stats()[key]
        return read

    reg.gauge("sparse.nnz_total", _sparse_stat("nnz_total"))
    reg.gauge("sparse.matrices", _sparse_stat("matrices"))
    reg.gauge("sparse.density", _sparse_stat("density"))

    def _stream_stat(key):
        def read():
            # lazy import: telemetry must not pull jax at module import
            from .parallel.streaming import streaming_stats
            return streaming_stats()[key]
        return read

    # mesh streaming (ISSUE 10): mesh.devices / mesh.chunk_bytes are set by
    # maybe_data_mesh / stream_to_device; peak staging + streamed pad rows
    # read through the streamer's own stats.  host_to_device_bytes_total is
    # a plain counter the streamer increments per chunk.
    reg.gauge("mesh.devices")
    reg.gauge("mesh.chunk_bytes")
    reg.counter("host_to_device_bytes_total")
    reg.gauge("mesh.peak_staging_bytes", _stream_stat("peak_staging_bytes"))
    reg.gauge("mesh.stream_chunks", _stream_stat("chunks"))
    reg.gauge("mesh.pad_rows_streamed", _stream_stat("pad_rows"))

    # device-runtime supervision (ISSUE 11): the heartbeat sets
    # supervisor.state (0 available / 1 degraded / 2 outage) and bumps the
    # outage/probe counters; watchdog.abandoned_total counts zombie worker
    # threads run_with_deadline left behind (the failure mode only the
    # subprocess supervisor can actually reclaim); multihost gauges are set
    # by init_distributed.
    reg.gauge("supervisor.state")
    reg.gauge("supervisor.last_probe_latency_s")
    reg.counter("supervisor.probes_total")
    reg.counter("supervisor.outages_total")
    reg.counter("supervisor.mesh_degrades_total")
    reg.counter("watchdog.abandoned_total")
    reg.gauge("multihost.process_count")
    reg.gauge("multihost.initialized")

    def _device_cap():
        # lazy import: telemetry must not pull jax at module import
        from .parallel.supervisor import device_cap
        c = device_cap()
        return -1 if c is None else c

    reg.gauge("supervisor.device_cap", _device_cap)
    return reg


#: Process-default registry.  Serving engines create their own instance per
#: engine (counters reset with the engine); train/bench report through this.
REGISTRY = _default_registry()


# --------------------------------------------------------------------------
# summaries + CLI rendering
# --------------------------------------------------------------------------

def telemetry_summary(tracer: Optional[Tracer] = None,
                      registry: Optional[MetricsRegistry] = None,
                      top_n: int = 15) -> Dict[str, Any]:
    """The ``telemetry.json`` payload: top slowest spans (with tree
    context), span counts by name, and the full metrics snapshot.  Bundled
    next to saved models and embedded in bench aux."""
    tracer = tracer if tracer is not None else active_tracer()
    registry = registry if registry is not None else REGISTRY
    out: Dict[str, Any] = {"metrics": registry.snapshot()}
    if tracer is not None:
        spans = tracer.spans
        by_name: Dict[str, Dict[str, Any]] = {}
        for s in spans:
            agg = by_name.setdefault(
                s.name, {"count": 0, "totalS": 0.0, "maxS": 0.0,
                         "errors": 0})
            agg["count"] += 1
            agg["totalS"] = round(agg["totalS"] + s.duration_s, 6)
            agg["maxS"] = round(max(agg["maxS"], s.duration_s), 6)
            agg["errors"] += int(s.status == "error")
        out["trace"] = {
            "runName": tracer.run_name,
            "spanCount": len(spans),
            "slowestSpans": [s.to_json() for s in tracer.slowest(top_n)],
            "byName": by_name,
        }
    return out


def write_telemetry_summary(path: str,
                            tracer: Optional[Tracer] = None,
                            registry: Optional[MetricsRegistry] = None
                            ) -> str:
    with open(path, "w") as fh:
        json.dump(telemetry_summary(tracer, registry), fh, indent=2,
                  default=str)
    return path


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read spans back from either export format: Chrome trace-event JSON
    (``traceEvents`` with span ids in ``args``) or ``Tracer.to_json()``
    (``spans``).  Returns a list of span dicts with name/spanId/parentId/
    durationS/status keys."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "spans" in doc:
        return list(doc["spans"])
    events = (doc or {}).get("traceEvents", []) if isinstance(doc, dict) \
        else []
    spans = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        spans.append({"name": ev.get("name", "?"),
                      "spanId": args.get("spanId"),
                      "parentId": args.get("parentId"),
                      "startS": float(ev.get("ts", 0.0)) / 1e6,
                      "durationS": float(ev.get("dur", 0.0)) / 1e6,
                      "status": args.get("status", "ok"),
                      "attrs": {k: v for k, v in args.items()
                                if k not in ("spanId", "parentId",
                                             "status")}})
    return spans


def render_trace_summary(path: str, top_n: int = 10) -> str:
    """The ``trace-summary`` subcommand's table: top-N slowest spans with
    their depth-in-tree, duration, status and attributes."""
    spans = load_trace(path)
    if not spans:
        return f"{path}: no spans"
    by_id = {s.get("spanId"): s for s in spans if s.get("spanId")}

    def depth(s: Dict[str, Any]) -> int:
        d, seen = 0, set()
        while s.get("parentId") and s["parentId"] in by_id \
                and s["parentId"] not in seen:
            seen.add(s["parentId"])
            s = by_id[s["parentId"]]
            d += 1
        return d

    rows = sorted(spans, key=lambda s: -float(s.get("durationS", 0.0)))
    rows = rows[:top_n]
    name_w = max(len("span"),
                 max(len(s.get("name", "?")) + 2 * depth(s) for s in rows))
    lines = [f"{path}: {len(spans)} span(s); top {len(rows)} by duration",
             f"{'span'.ljust(name_w)}  {'seconds':>10}  {'status':<6}  attrs"]
    for s in rows:
        nm = "  " * depth(s) + s.get("name", "?")
        attrs = s.get("attrs") or {}
        attr_s = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        if len(attr_s) > 60:
            attr_s = attr_s[:57] + "..."
        lines.append(f"{nm.ljust(name_w)}  "
                     f"{float(s.get('durationS', 0.0)):>10.4f}  "
                     f"{s.get('status', 'ok'):<6}  {attr_s}")
    return "\n".join(lines)
