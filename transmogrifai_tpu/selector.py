"""ModelSelector — cross-validated model selection (reference:
core/src/main/scala/com/salesforce/op/stages/impl/selector/ModelSelector.scala:114,143,
factories BinaryClassificationModelSelector.scala:60-133,
MultiClassificationModelSelector.scala, RegressionModelSelector.scala:61,
grids DefaultSelectorParams.scala:36-68).

``fit``: prepare data (splitter), run the validator over every
(model × grid-point), re-fit the winner on the full prepared train split,
evaluate all evaluators, and return a ``SelectedModel`` carrying the
``ModelSelectorSummary`` — the exact reference flow, with Spark-job fan-out
replaced by compiled per-candidate XLA fits.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .columns import Column, ColumnBatch
from .evaluators import (Evaluators, OpBinaryClassificationEvaluator,
                         OpEvaluatorBase, OpMultiClassificationEvaluator,
                         OpRegressionEvaluator)
from .models.base import PredictionModel, PredictorEstimator, extract_xy
from .resilience import record_failure
from .stages.base import Estimator
from .tuning import (DataBalancer, DataCutter, DataSplitter, ModelCandidate,
                     OpCrossValidation, OpTrainValidationSplit, OpValidator,
                     Splitter, ValidationResult)
from .types import OPVector, Prediction, RealNN


class DefaultSelectorParams:
    """≙ DefaultSelectorParams.scala:36-68 — the pinned reference grid values."""

    MAX_DEPTH = [3, 6, 12]
    MAX_BIN = [32]
    MIN_INSTANCES_PER_NODE = [10, 100]
    MIN_INFO_GAIN = [0.001, 0.01, 0.1]
    REGULARIZATION = [0.001, 0.01, 0.1, 0.2]
    MAX_ITER_LIN = [50]
    MAX_ITER_TREE = [20]
    ELASTIC_NET = [0.1, 0.5]
    MAX_TREES = [50]
    SUBSAMPLE_RATE = [1.0]
    STEP_SIZE = [0.1]
    IMPURITY_CLASS = ["gini"]
    IMPURITY_REG = ["variance"]
    TOL = [1e-6]
    NB_SMOOTHING = [1.0]
    XGB_NUM_ROUND = [100]
    XGB_ETA = [0.1, 0.3]
    XGB_MIN_CHILD_WEIGHT = [1.0, 5.0, 10.0]

    # sweep racing (successive halving, Jamieson & Talwalkar 2016): screen
    # the full grid on fold 0 only, keep the top ceil(G/η) (≥ MIN_SURVIVORS)
    # per family, run the remaining folds for survivors only.  Families whose
    # grid can't shrink past the floor run full CV — bit-identical to the
    # unraced sweep.
    RACING = True
    RACING_ETA = 3.0
    RACING_MIN_SURVIVORS = 2


def grid(**param_lists) -> List[Dict[str, Any]]:
    """Cartesian product of param lists (≙ ParamGridBuilder)."""
    keys = list(param_lists)
    return [dict(zip(keys, vals))
            for vals in itertools.product(*(param_lists[k] for k in keys))]


class RandomParamBuilder:
    """≙ RandomParamBuilder: random search over param distributions."""

    def __init__(self, seed: int = 42):
        self._rng = np.random.default_rng(seed)
        self._specs: List[tuple] = []

    def uniform(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        self._specs.append((name, "uniform", low, high))
        return self

    def exponential(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        self._specs.append((name, "exp", low, high))
        return self

    def choice(self, name: str, values: Sequence[Any]) -> "RandomParamBuilder":
        self._specs.append((name, "choice", list(values), None))
        return self

    def build(self, n: int) -> List[Dict[str, Any]]:
        out = []
        for _ in range(n):
            d = {}
            for name, kind, a, b in self._specs:
                if kind == "uniform":
                    d[name] = float(self._rng.uniform(a, b))
                elif kind == "exp":
                    d[name] = float(np.exp(self._rng.uniform(np.log(a), np.log(b))))
                else:
                    d[name] = a[self._rng.integers(len(a))]
            out.append(d)
        return out


@dataclass
class ModelEvaluation:
    model_name: str
    params: Dict[str, Any]
    metric_values: Dict[str, float]
    # pruned by sweep racing after the fold-0 screen: metric_values hold the
    # screen metric (not a full-CV mean) and the point never competed for best
    raced_out: bool = False


@dataclass
class ModelSelectorSummary:
    """≙ ModelSelectorSummary (selector/ModelSelectorSummary.scala)."""

    validation_type: str = ""
    validation_parameters: Dict[str, Any] = field(default_factory=dict)
    data_prep_parameters: Dict[str, Any] = field(default_factory=dict)
    data_prep_results: Dict[str, Any] = field(default_factory=dict)
    evaluation_metric: str = ""
    problem_type: str = ""
    best_model_uid: str = ""
    best_model_name: str = ""
    best_model_type: str = ""
    validation_results: List[ModelEvaluation] = field(default_factory=list)
    train_evaluation: Dict[str, Any] = field(default_factory=dict)
    holdout_evaluation: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "validationType": self.validation_type,
            "validationParameters": self.validation_parameters,
            "dataPrepParameters": self.data_prep_parameters,
            "dataPrepResults": self.data_prep_results,
            "evaluationMetric": self.evaluation_metric,
            "problemType": self.problem_type,
            "bestModelUID": self.best_model_uid,
            "bestModelName": self.best_model_name,
            "bestModelType": self.best_model_type,
            "validationResults": [
                {"modelName": r.model_name, "modelParameters": r.params,
                 "metricValues": r.metric_values,
                 **({"racedOut": True} if r.raced_out else {})}
                for r in self.validation_results],
            "trainEvaluation": self.train_evaluation,
            "holdoutEvaluation": self.holdout_evaluation,
        }


class SelectedModel(PredictionModel):
    """The winning fitted model (≙ SelectedModel, ModelSelector.scala:207).
    Delegates prediction to the wrapped best model; carries the summary."""

    def __init__(self, **params):
        self._best_model: Optional[PredictionModel] = params.pop("best_model", None)
        super().__init__(**params)
        self.summary: Optional[ModelSelectorSummary] = None

    @property
    def best_model(self) -> PredictionModel:
        return self._best_model

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        return self._best_model.predict_arrays(X)

    def supports_device_scores(self) -> bool:
        inner = self._best_model
        if inner is None:
            return False
        sup = getattr(inner, "supports_device_scores", None)
        return sup() if sup is not None else hasattr(inner, "device_scores")

    def device_scores(self, Xd, full: bool = False):
        return self._best_model.device_scores(Xd, full=full)

    def ctor_args(self) -> Dict[str, Any]:
        return dict(self._params)

    # -- nested-model persistence (wrapped best model saved inline) -------
    def save_extra(self):
        if self._best_model is None:
            return {}, {}
        check = getattr(self._best_model, "check_serializable", None)
        if check is not None:
            check()  # e.g. ExternalModel without an importable predict spec
        from .models import MODEL_REGISTRY  # ensure class is resolvable

        def _is_arr(v):
            import jax
            return isinstance(v, (np.ndarray, np.generic, jax.Array))

        inner = self._best_model
        j = {"bestModelClass": type(inner).__name__,
             "bestModelParams": {k: v for k, v in inner._params.items()
                                 if isinstance(v, (str, int, float, bool, list, tuple))
                                 or v is None},
             "bestFittedJson": {k: v for k, v in inner.fitted.items()
                                if not _is_arr(v)}}
        arrays = {f"best/{k}": np.asarray(v) for k, v in inner.fitted.items()
                  if _is_arr(v)}
        return j, arrays

    def load_extra(self, extra_json, arrays):
        from .models import MODEL_REGISTRY
        cls = MODEL_REGISTRY[extra_json["bestModelClass"]]
        fitted = dict(extra_json.get("bestFittedJson") or {})
        for k, v in arrays.items():
            if k.startswith("best/"):
                fitted[k[len("best/"):]] = v
        self._best_model = cls(fitted=fitted,
                               **(extra_json.get("bestModelParams") or {}))


class ModelSelector(Estimator):
    """≙ ModelSelector.scala:114-191."""

    in_kinds = (RealNN, OPVector)
    out_kind = Prediction
    allow_label_as_input = True
    problem_type = "Unknown"

    def __init__(self, validator: OpValidator, splitter: Optional[Splitter],
                 models: Sequence[ModelCandidate],
                 evaluators: Sequence[OpEvaluatorBase] = (),
                 model_types_to_use: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        self.validator = validator
        self.splitter = splitter
        self.models = list(models)
        if model_types_to_use is not None:
            # ≙ setModelsToTry/modelTypesToUse (BinaryClassificationModelSelector.scala)
            wanted = set(model_types_to_use)
            known = {c.model_name for c in self.models}
            unknown = wanted - known
            if unknown:
                raise ValueError(
                    f"model_types_to_use: unknown model types {sorted(unknown)}; "
                    f"available: {sorted(known)}")
            self.models = [c for c in self.models if c.model_name in wanted]
        self.evaluators = list(evaluators)
        self.holdout_eval: Optional[Dict[str, Any]] = None

    def output_name(self) -> str:
        return f"{self.input_features[0].name}_prediction_{self.uid[-6:]}"

    def output_is_response(self) -> bool:
        return False

    # -- the selector flow -----------------------------------------------
    def find_best_estimator(self, batch: ColumnBatch,
                            in_fold_dag=None) -> ValidationResult:
        label = self.input_features[0].name
        features = self.input_features[1].name
        return self.validator.validate(self.models, batch, label, features,
                                       in_fold_dag=in_fold_dag,
                                       splitter=self.splitter)

    def _refit_reusing_grid_executable(self, result, X, y):
        """Final full-data refit through the SAME batched (fold × grid)
        program the CV already compiled: with identical array shapes (all-ones
        fold weights [F, N], the winner's params padded to the family's grid
        width G) jax's executable cache hits and the refit costs F·G redundant
        cheap fits instead of compiling + loading a fresh single-fit program —
        on the tunneled TPU the compile/load dwarfs the compute.  Returns None
        (→ caller falls back to ``fit_arrays``) when the shapes differ (e.g. a
        Balancer resampled the train set) or anything goes wrong.

        With racing/padding live, the winning family's last batched fit may
        have run on fewer folds (survivor round: F-1), a survivor-sized grid,
        or ladder-padded rows — ``validator.family_fit_meta`` records the
        exact (folds, rows, lanes) of the family's most recent batched
        program, and the refit mirrors it (padding X/y with zero-weight rows
        when needed) so the executable-cache key matches."""
        cand = next((c for c in self.models
                     if c.model_name == result.best.model_name), None)
        if cand is None or not cand.grid:
            return None
        meta = getattr(self.validator, "family_fit_meta", {}).get(
            result.best.model_name)
        if meta is not None:
            if meta["real_rows"] != X.shape[0]:
                meta = None   # Balancer/Cutter changed the final train rows
            elif meta["padded"] and not getattr(
                    cand.estimator, "weighted_pad_exact", False):
                meta = None   # never zero-pad an estimator that can't take it
        shape = getattr(self.validator, "last_fit_shape", None)
        if meta is None and (shape is None or shape[1] != X.shape[0]):
            return None
        try:
            import jax
            import jax.numpy as jnp

            if meta is not None:
                F, rows, lanes = meta["folds"], meta["rows"], meta["lanes"]
            else:
                F, rows, lanes = shape[0], shape[1], len(cand.grid)
            from .sparse.matrix import SparseMatrix

            pad = rows - X.shape[0]
            if pad:
                if isinstance(X, SparseMatrix):
                    X = X.pad_rows(rows)   # empty rows, zero-weight below
                else:
                    Xj = X if isinstance(X, jax.Array) else jnp.asarray(
                        X, jnp.float32)
                    X = jnp.pad(Xj, ((0, pad), (0, 0)))
                y = jnp.pad(jnp.asarray(y, jnp.float32), (0, pad))
            # all-ones fold weights materialize ON DEVICE — zero wire bytes;
            # padded rows get weight 0 so they can't perturb the fit
            W = jnp.ones((F, rows), jnp.float32)
            if pad:
                W = W.at[:, -pad:].set(0.0)
            mesh = getattr(self.validator, "last_mesh", None)
            if mesh is not None:
                # match the CV call's shardings exactly — the jit cache keys
                # on them, so a layout mismatch would recompile the whole
                # batched program instead of reusing it
                from .parallel import data_sharding, stream_to_device
                if isinstance(X, SparseMatrix):
                    # DeviceTable dispatch: same row partition and nnz-rung
                    # capacities as the CV stream (same data, same mesh), so
                    # the flat-component shapes match the sweep's compiled
                    # program exactly
                    X = stream_to_device(X, mesh, pad_to=rows)
                else:
                    X = jax.device_put(
                        X if isinstance(X, jax.Array)
                        else jnp.asarray(X, jnp.float32),
                        data_sharding(mesh, 2))
                y = jax.device_put(jnp.asarray(y, jnp.float32),
                                   data_sharding(mesh, 1))
                W = jax.device_put(jnp.asarray(W),
                                   data_sharding(mesh, 2, row_axis=1))
            if pad and not isinstance(X, SparseMatrix):
                # tree families quantile-bin over the true rows only, same
                # as the sweep's padded fit
                from .models.trees import register_real_rows
                register_real_rows(X, rows - pad)
            grids = [dict(result.best_params)] * lanes
            return cand.estimator.fit_arrays_grid(X, y, W, grids)[0][0]
        except Exception as e:  # noqa: BLE001 — reuse is an optimization only
            record_failure(self.uid, "degraded", e,
                           point="selector.refit_reuse",
                           fallback="fresh single-fit program")
            return None

    def _evaluate_all(self, model, X, y) -> Dict[str, Any]:
        """All-evaluator panel; device reductions when X is device-resident."""
        import jax
        import jax.numpy as jnp

        from .sparse.matrix import SparseMatrix

        out: Dict[str, Any] = {}
        dev_out = y_dev = w_dev = None
        if (isinstance(X, (jax.Array, SparseMatrix))
                and hasattr(model, "device_scores")):
            try:
                dev_out = model.device_scores(X, full=True)
                y_dev = jnp.asarray(y, jnp.float32)
                w_dev = jnp.ones_like(y_dev)
            except Exception as e:  # noqa: BLE001 — fall back to host
                record_failure(self.uid, "fallback", e,
                               point="selector.evaluate_device",
                               fallback="host predict path")
                dev_out = None
        pred = None
        for ev in self.evaluators:
            em = None
            if dev_out is not None:
                try:
                    em = ev.evaluate_all_device(y_dev, dev_out, w_dev)
                except Exception as e:  # noqa: BLE001
                    record_failure(self.uid, "fallback", e,
                                   point="selector.evaluate_device",
                                   evaluator=ev.name)
                    em = None
            if em is None:
                if pred is None:
                    pred = model.predict_arrays(X)
                em = ev.evaluate_all(y, pred)
            out[ev.name] = em.to_json()
        return out

    def fit(self, batch: ColumnBatch, in_fold_dag=None) -> SelectedModel:
        label_f, feats_f = self.input_features
        label = label_f.name
        holdout = None
        if self.splitter is not None:
            if self.splitter.reserve_test_fraction > 0:
                # reserve a test holdout before any CV/preparation; the winner
                # is evaluated on it (≙ Splitter.split + holdoutEvaluation)
                batch, holdout = self.splitter.split(batch, label)
            batch = self.splitter.pre_validation_prepare(batch, label)
        result = self.find_best_estimator(batch, in_fold_dag=in_fold_dag)
        train_batch = batch
        if self.splitter is not None:
            train_batch = self.splitter.validation_prepare(batch, label)
        best_est: PredictorEstimator = result.best.estimator
        X, y = extract_xy(train_batch, label_f, feats_f)
        from .telemetry import span
        with span("selector.winner_refit", model=result.best.model_name):
            fitted = self._refit_reusing_grid_executable(result, X, y)
            if fitted is None:
                fitted = best_est.fit_arrays(X, y)
        best_model = best_est.model_cls(fitted=fitted, **best_est._params)

        # evaluate all evaluators on the training data (≙ trainEvaluation) —
        # on device when possible: pulling 1M-row prediction vectors over the
        # host link costs more than the whole grid's compute
        with span("selector.evaluate", split="train"):
            train_eval = self._evaluate_all(best_model, X, y)

        holdout_eval = None
        if holdout is not None and len(holdout):
            Xh, yh = extract_xy(holdout, label_f, feats_f)
            with span("selector.evaluate", split="holdout"):
                holdout_eval = self._evaluate_all(best_model, Xh, yh)
            self.holdout_eval = holdout_eval

        summary = ModelSelectorSummary(
            validation_type=result.validation_type,
            validation_parameters={
                "seed": self.validator.seed, "stratify": self.validator.stratify,
                "parallelism": self.validator.parallelism,
                **({"numFolds": self.validator.num_folds}
                   if isinstance(self.validator, OpCrossValidation) else
                   {"trainRatio": self.validator.train_ratio}
                   if isinstance(self.validator, OpTrainValidationSplit) else {}),
                "racing": dict(zip(("enabled", "eta", "minSurvivors"),
                                   self.validator._racing_config()))},
            data_prep_parameters=(
                {} if self.splitter is None else {
                    k: v for k, v in vars(self.splitter).items()
                    if isinstance(v, (int, float, str, bool))}),
            data_prep_results=(
                {} if self.splitter is None or self.splitter.summary is None
                else self.splitter.summary.info),
            evaluation_metric=result.metric_name,
            problem_type=self.problem_type,
            best_model_uid=best_est.uid,
            best_model_name=result.best.model_name,
            best_model_type=type(best_est).__name__,
            validation_results=[
                ModelEvaluation(r.model_name, r.params,
                                {result.metric_name: r.mean_metric},
                                raced_out=r.raced_out)
                for r in result.all_results],
            train_evaluation=train_eval,
            holdout_evaluation=holdout_eval,
        )

        model = SelectedModel(best_model=best_model, **self._params)
        model.summary = summary
        model.metadata["summary"] = summary.to_json()
        model.fitted = {"best_model_class": type(best_model).__name__,
                        "best_metric": float(result.best_metric)}

        # seal the sweep checkpoint with the winner: a later resume of an
        # already-finished sweep sees every candidate replayed AND which one
        # won, so restart cost is one full-data refit, not a re-sweep
        from .checkpoint import active_sweep_checkpoint
        cp = active_sweep_checkpoint()
        if cp is not None:
            try:
                cp.set_winner(result.best.model_name, result.best_params,
                              float(result.best_metric))
            except Exception as e:  # noqa: BLE001 — durability is best-effort
                from .resilience import record_failure
                record_failure("selector", "degraded", e,
                               point="checkpoint.save",
                               fallback="winner not persisted")
        return self._finalize_model(model)


# --------------------------------------------------------------------------
# factories with reference-default model grids
# --------------------------------------------------------------------------

def _lr_candidates(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.linear import OpLogisticRegression
    return ModelCandidate(
        OpLogisticRegression(),
        grid(reg_param=p.REGULARIZATION, elastic_net_param=p.ELASTIC_NET,
             max_iter=p.MAX_ITER_LIN),
        "OpLogisticRegression")


def _rf_classifier(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.trees import OpRandomForestClassifier
    return ModelCandidate(
        OpRandomForestClassifier(),
        grid(max_depth=p.MAX_DEPTH, min_instances_per_node=p.MIN_INSTANCES_PER_NODE,
             min_info_gain=p.MIN_INFO_GAIN, num_trees=p.MAX_TREES,
             max_bins=p.MAX_BIN),
        "OpRandomForestClassifier")


def _gbt_classifier(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.trees import OpGBTClassifier
    return ModelCandidate(
        OpGBTClassifier(),
        grid(max_depth=p.MAX_DEPTH, min_instances_per_node=p.MIN_INSTANCES_PER_NODE,
             min_info_gain=p.MIN_INFO_GAIN, max_iter=p.MAX_ITER_TREE,
             max_bins=p.MAX_BIN),
        "OpGBTClassifier")


def _svc_candidates(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.linear import OpLinearSVC
    return ModelCandidate(
        OpLinearSVC(),
        grid(reg_param=p.REGULARIZATION, max_iter=p.MAX_ITER_LIN),
        "OpLinearSVC")


def _linreg_candidates(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.linear import OpLinearRegression
    return ModelCandidate(
        OpLinearRegression(),
        grid(reg_param=p.REGULARIZATION, elastic_net_param=p.ELASTIC_NET,
             max_iter=p.MAX_ITER_LIN),
        "OpLinearRegression")


def _rf_regressor(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.trees import OpRandomForestRegressor
    return ModelCandidate(
        OpRandomForestRegressor(),
        grid(max_depth=p.MAX_DEPTH, min_instances_per_node=p.MIN_INSTANCES_PER_NODE,
             min_info_gain=p.MIN_INFO_GAIN, num_trees=p.MAX_TREES,
             max_bins=p.MAX_BIN),
        "OpRandomForestRegressor")


def _gbt_regressor(p=DefaultSelectorParams) -> ModelCandidate:
    from .models.trees import OpGBTRegressor
    return ModelCandidate(
        OpGBTRegressor(),
        grid(max_depth=p.MAX_DEPTH, min_instances_per_node=p.MIN_INSTANCES_PER_NODE,
             min_info_gain=p.MIN_INFO_GAIN, max_iter=p.MAX_ITER_TREE,
             max_bins=p.MAX_BIN),
        "OpGBTRegressor")


def _compact_models(linear_cls, forest_cls) -> List[ModelCandidate]:
    """Fast starter grid (linear reg sweep + one compact forest) for generated
    apps and demos; the full reference default grids stay the constructor
    default of every selector."""
    return [
        ModelCandidate(linear_cls(), grid(reg_param=[0.01, 0.1]),
                       linear_cls.__name__),
        ModelCandidate(forest_cls(),
                       grid(num_trees=[20], max_depth=[6]),
                       forest_cls.__name__),
    ]


class BinaryClassificationModelSelector(ModelSelector):
    """≙ BinaryClassificationModelSelector.scala:60-133 — defaults: LR, RF,
    GBT, LinearSVC on; NB/DT/XGB off; 3-fold CV on AuPR; DataSplitter."""

    problem_type = "BinaryClassification"

    def __init__(self, num_folds: int = 3, seed: int = 42,
                 validation_metric: Optional[OpEvaluatorBase] = None,
                 splitter: Optional[Splitter] = None,
                 models: Optional[Sequence[ModelCandidate]] = None,
                 stratify: bool = False, parallelism: int = 8,
                 use_train_validation_split: bool = False,
                 train_ratio: float = 0.75, **kw):
        ev = validation_metric or Evaluators.BinaryClassification.auPR()
        validator = (OpTrainValidationSplit(train_ratio, ev, seed, stratify, parallelism)
                     if use_train_validation_split
                     else OpCrossValidation(num_folds, ev, seed, stratify, parallelism))
        if models is None:
            models = [_lr_candidates(), _rf_classifier(), _gbt_classifier(),
                      _svc_candidates()]
        evaluators = [OpBinaryClassificationEvaluator()]
        super().__init__(validator, splitter if splitter is not None else DataSplitter(seed),
                         models, evaluators, **kw)

    @staticmethod
    def compact_models() -> List[ModelCandidate]:
        from .models.linear import OpLogisticRegression
        from .models.trees import OpRandomForestClassifier
        return _compact_models(OpLogisticRegression, OpRandomForestClassifier)


class MultiClassificationModelSelector(ModelSelector):
    """≙ MultiClassificationModelSelector — defaults: LR, RF; DataCutter;
    3-fold CV on F1."""

    problem_type = "MultiClassification"

    def __init__(self, num_folds: int = 3, seed: int = 42,
                 validation_metric: Optional[OpEvaluatorBase] = None,
                 splitter: Optional[Splitter] = None,
                 models: Optional[Sequence[ModelCandidate]] = None,
                 stratify: bool = False, parallelism: int = 8, **kw):
        ev = validation_metric or Evaluators.MultiClassification.f1()
        validator = OpCrossValidation(num_folds, ev, seed, stratify, parallelism)
        if models is None:
            models = [_lr_candidates(), _rf_classifier()]
        evaluators = [OpMultiClassificationEvaluator()]
        super().__init__(validator, splitter if splitter is not None else DataCutter(seed=seed),
                         models, evaluators, **kw)

    @staticmethod
    def compact_models() -> List[ModelCandidate]:
        from .models.linear import OpLogisticRegression
        from .models.trees import OpRandomForestClassifier
        return _compact_models(OpLogisticRegression, OpRandomForestClassifier)


class RegressionModelSelector(ModelSelector):
    """≙ RegressionModelSelector.scala:61 — defaults: LinReg, RF, GBT;
    DataSplitter; 3-fold CV on RMSE."""

    problem_type = "Regression"

    def __init__(self, num_folds: int = 3, seed: int = 42,
                 validation_metric: Optional[OpEvaluatorBase] = None,
                 splitter: Optional[Splitter] = None,
                 models: Optional[Sequence[ModelCandidate]] = None,
                 parallelism: int = 8, **kw):
        ev = validation_metric or Evaluators.Regression.rmse()
        validator = OpCrossValidation(num_folds, ev, seed, False, parallelism)
        if models is None:
            models = [_linreg_candidates(), _rf_regressor(), _gbt_regressor()]
        evaluators = [OpRegressionEvaluator()]
        super().__init__(validator, splitter if splitter is not None else DataSplitter(seed),
                         models, evaluators, **kw)

    @staticmethod
    def compact_models() -> List[ModelCandidate]:
        from .models.linear import OpLinearRegression
        from .models.trees import OpRandomForestRegressor
        return _compact_models(OpLinearRegression, OpRandomForestRegressor)


def _combiner_best_metric(m, larger_better: bool) -> float:
    """Best validation metric of one selector's summary, for ensemble
    weighting.  Non-finite values (NaN/inf fold metrics of failed or
    diverged candidates) are excluded from the ranking — but never
    silently: each drop records a ``degraded`` FailureLog note naming the
    candidate and metric, so a candidate that NaN-ed its way out of the
    weighting is visible in the log instead of vanishing."""
    metric = m.summary.evaluation_metric
    vals = []
    for r in m.summary.validation_results:
        v = r.metric_values.get(metric, np.nan)
        if np.isfinite(v):
            vals.append(v)
        else:
            record_failure("combiner", "degraded",
                           f"non-finite {metric}={v} for candidate "
                           f"{r.model_name}; excluded from ensemble "
                           "weighting",
                           point="selector.nonfinite_metric",
                           model=r.model_name, metric=metric)
    if not vals:
        return 0.5
    return max(vals) if larger_better else min(vals)


class SelectedModelCombiner(Estimator):
    """≙ SelectedModelCombiner: weighted-average ensemble of two selectors'
    winners, weights ∝ validation metric."""

    in_kinds = (RealNN, OPVector)
    out_kind = Prediction
    allow_label_as_input = True

    def __init__(self, selector1: ModelSelector, selector2: ModelSelector, **kw):
        super().__init__(**kw)
        self.selector1 = selector1
        self.selector2 = selector2

    def fit(self, batch: ColumnBatch) -> "CombinedModel":
        label_f, feats_f = self.input_features
        self.selector1.set_input(label_f, feats_f)
        self.selector2.set_input(label_f, feats_f)
        m1 = self.selector1.fit(batch)
        m2 = self.selector2.fit(batch)
        larger_better = self.selector1.validator.evaluator.is_larger_better

        # weight by each selector's best validation metric; for
        # smaller-is-better metrics (RMSE, Error) weight inversely
        b1 = _combiner_best_metric(m1, larger_better)
        b2 = _combiner_best_metric(m2, larger_better)
        if larger_better:
            w1, w2 = abs(b1), abs(b2)
        else:
            w1, w2 = 1.0 / max(abs(b1), 1e-12), 1.0 / max(abs(b2), 1e-12)
        tot = (w1 + w2) or 1.0
        model = CombinedModel(model1=m1, model2=m2, w1=w1 / tot, w2=w2 / tot)
        return self._finalize_model(model)


class CombinedModel(PredictionModel):
    def __init__(self, **params):
        self.model1 = params.pop("model1", None)
        self.model2 = params.pop("model2", None)
        self.w1 = params.pop("w1", 0.5)
        self.w2 = params.pop("w2", 0.5)
        super().__init__(**params)

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        p1 = self.model1.predict_arrays(X)
        p2 = self.model2.predict_arrays(X)
        if p1.get("probability") is not None and p2.get("probability") is not None:
            prob = self.w1 * np.asarray(p1["probability"]) + \
                self.w2 * np.asarray(p2["probability"])
            return {"prediction": np.argmax(prob, axis=1).astype(np.float32),
                    "probability": prob, "rawPrediction": np.log(prob + 1e-12)}
        pred = self.w1 * np.asarray(p1["prediction"]) + \
            self.w2 * np.asarray(p2["prediction"])
        return {"prediction": pred}
