"""Fleet-wide content-addressed compiled-program registry: cold ≈ warm.

Serving cold-start is solved (aot.py ships executables inside each bundle),
but every OTHER first run still pays the compile wall in full: a cold 4M-row
train is 463s vs 86s warm (BENCH_11M), a fresh process runs ~107 XLA
compiles (BENCH_STANDING), and every pool worker, tenant activation,
hostgroup rank, and lifecycle retrain re-derives the same executables.  The
programs themselves are already canonicalized — fit-shape ladder rungs,
positional pytree names at the jit boundary — so their identities are
stable across processes and machines with the same ABI.

This module is the registry those identities key into: a content-addressed,
on-disk table of serialized XLA executables under
``<root>/<platform>/<digest[:2]>/<digest>/`` where the digest covers

    kind (grid | score) x family x ladder-rung x canonicalized program
    signature (static config + input avals) x ``aot.abi_stamp()`` x a
    digest of the package source

so a stale entry can never be *found*, only evicted.  Every entry is a
directory written temp+fsync+rename (checkpoint.py conventions): two
processes racing to publish the same key converge on one valid entry, and a
reader never observes a torn payload.  Install verifies the payload's
SHA-256 against the entry metadata and the ABI stamp against the running
process; any mismatch degrades to the ordinary JIT path with a FailureLog
note — exactly the semantics already tested for serving AOT.  The registry
is an optimization, never a correctness dependency.

Three seams feed and drain it:

* **Train** — ``grid_call`` wraps every batched grid-fit dispatch
  (models/linear.py, models/trees.py): registry hit → the deserialized
  executable runs with ZERO traces and ZERO compiles; miss → the ordinary
  jit dispatch runs and a background publish serializes a fresh compile of
  the same program.  ``grid_compile`` is the compile-only twin the
  background pre-trace uses.
* **Serve** — ``compiled.ScoreProgram`` asks the registry before tracing a
  fused scoring program (key includes the model-content family digest), and
  ``aot.export_bundle`` publishes every executable it ships in a bundle —
  so an N-worker pool on a registry-warm machine boots with ≤1 compile
  total even when the bundle itself carries no AOT artifacts.
* **Tenants** — deserialized executables are memoized process-wide by
  payload digest (``shared_load``), so two tenants serving the same
  family x rung share ONE loaded executable and its device memory.

The registry also *manages* the persistent XLA compile cache: when no
explicit ``TRANSMOGRIFAI_COMPILE_CACHE`` is pinned, configuring a registry
root points jax's cache at ``<root>/compile-cache`` — shipping the registry
directory to a fresh machine (or restoring it from CI's ``actions/cache``)
makes EVERY train compile a disk hit, not just the grid programs.  Both
stores are size-capped: ``enforce_budget`` / ``gc_compile_cache`` run
LRU-by-atime eviction under a byte budget, stale-ABI entries first, with
``evicted`` FailureLog notes.

Opt out with ``--no-registry`` / ``registryParams`` /
``TRANSMOGRIFAI_AOT_REGISTRY=0``.
"""

from __future__ import annotations

import contextlib
import glob
import hashlib
import io
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REGISTRY_FORMAT_VERSION = 1
ENTRY_META_NAME = "entry.json"
ENTRY_PAYLOAD_NAME = "payload.bin"

REGISTRY_ENV = "TRANSMOGRIFAI_AOT_REGISTRY"
CAP_ENV = "TRANSMOGRIFAI_AOT_REGISTRY_CAP_BYTES"
KEEP_ENV = "TRANSMOGRIFAI_AOT_REGISTRY_KEEP_MIN"
CACHE_CAP_ENV = "TRANSMOGRIFAI_COMPILE_CACHE_CAP_BYTES"

# default byte budgets: generous for a fleet cache, small enough that a
# long-lived checkpoint dir never grows without bound
DEFAULT_CAP_BYTES = 2 << 30          # registry entries
DEFAULT_CACHE_CAP_BYTES = 2 << 30    # persistent XLA compile cache
DEFAULT_KEEP_MIN = 8                 # newest entries never evicted

_LOCK = threading.RLock()
_STATE: Dict[str, Any] = {
    "enabled": True,        # kill switch (--no-registry / registryParams)
    "root": None,           # explicit root (params/cli); None = env/default
    "cap_bytes": None,
    "keep_min": None,
    "cache_cap_bytes": None,
    "managed_cache": None,  # compile-cache dir this module pinned, if any
}

# process-wide loaded-executable table: payload/key digest -> deserialized
# executable.  THE tenant-sharing seam — two engines installing the same
# payload get the same object (and its device allocations) back.
_LOADED: Dict[str, Any] = {}

# keys whose publish is already queued/done this process (dedup)
_PUBLISHED: set = set()

# grid key -> names of DYNAMIC keyword args the executable was lowered
# with (e.g. linear_grid_fit's traced ``tol``): a deserialized executable
# must be called with exactly the pytree it was lowered from, so these
# ride in each published record and are replayed at call time
_DYN_KWARGS: Dict[str, Tuple[str, ...]] = {}


def _count(name: str, n: int = 1) -> None:
    from .telemetry import REGISTRY
    REGISTRY.counter(name).inc(n)


# -- configuration -----------------------------------------------------------

def set_registry_enabled(on: bool) -> None:
    with _LOCK:
        _STATE["enabled"] = bool(on)


def registry_allowed() -> bool:
    """No kill switch thrown: params/CLI haven't disabled the registry, the
    env hasn't, and AOT itself is on.  (Whether a ROOT is configured is
    :func:`registry_enabled`'s business — callers that are about to default
    a root check this one.)"""
    from .aot import aot_enabled
    with _LOCK:
        if not _STATE["enabled"]:
            return False
    if not aot_enabled():
        return False
    return os.environ.get(REGISTRY_ENV, "") not in ("0", "off")


def registry_enabled() -> bool:
    """True when the registry may be consulted: not killed, and a root is
    known."""
    return registry_allowed() and registry_root() is not None


def registry_root() -> Optional[str]:
    """The registry directory, or None when unconfigured.  Order: explicit
    ``configure(root=...)`` (params/CLI) then the ``TRANSMOGRIFAI_AOT_-
    REGISTRY`` env var (also how pool workers / hostgroup ranks inherit the
    parent's root)."""
    with _LOCK:
        if _STATE["root"]:
            return _STATE["root"]
    env = os.environ.get(REGISTRY_ENV, "")
    if env and env not in ("0", "off", "1"):
        return env
    return None


def configure(root: Optional[str] = None, enabled: Optional[bool] = None,
              cap_bytes: Optional[int] = None,
              keep_min: Optional[int] = None,
              cache_cap_bytes: Optional[int] = None,
              manage_compile_cache: bool = True) -> None:
    """Apply registryParams / CLI flags.  Exports the root into the process
    environment so spawned children (serving pool workers, hostgroup ranks,
    supervised probes) install from the same registry without their own
    plumbing.  Unless a compile cache is already pinned, also parks the
    persistent XLA compile cache under ``<root>/compile-cache`` — the
    registry directory then carries BOTH stores fleet-wide."""
    with _LOCK:
        if enabled is not None:
            _STATE["enabled"] = bool(enabled)
        if cap_bytes is not None:
            _STATE["cap_bytes"] = int(cap_bytes)
        if keep_min is not None:
            _STATE["keep_min"] = int(keep_min)
        if cache_cap_bytes is not None:
            _STATE["cache_cap_bytes"] = int(cache_cap_bytes)
        if root:
            _STATE["root"] = str(root)
            os.environ[REGISTRY_ENV] = str(root)
    if enabled is False:
        os.environ[REGISTRY_ENV] = "0"
        return
    if root and manage_compile_cache and \
            not os.environ.get("TRANSMOGRIFAI_COMPILE_CACHE"):
        from .profiling import set_compile_cache_dir
        cache_dir = os.path.join(str(root), "compile-cache")
        if set_compile_cache_dir(cache_dir):
            with _LOCK:
                _STATE["managed_cache"] = cache_dir
            # children must see the SAME cache (env wins over their own
            # defaulting) — and gets them the fleet-warm entries
            os.environ["TRANSMOGRIFAI_COMPILE_CACHE"] = cache_dir


def managed_compile_cache() -> Optional[str]:
    with _LOCK:
        return _STATE["managed_cache"]


def _cap_bytes() -> int:
    with _LOCK:
        if _STATE["cap_bytes"] is not None:
            return _STATE["cap_bytes"]
    try:
        return int(os.environ.get(CAP_ENV, DEFAULT_CAP_BYTES))
    except ValueError:
        return DEFAULT_CAP_BYTES


def _keep_min() -> int:
    with _LOCK:
        if _STATE["keep_min"] is not None:
            return _STATE["keep_min"]
    try:
        return int(os.environ.get(KEEP_ENV, DEFAULT_KEEP_MIN))
    except ValueError:
        return DEFAULT_KEEP_MIN


def _cache_cap_bytes() -> int:
    with _LOCK:
        if _STATE["cache_cap_bytes"] is not None:
            return _STATE["cache_cap_bytes"]
    try:
        return int(os.environ.get(CACHE_CAP_ENV, DEFAULT_CACHE_CAP_BYTES))
    except ValueError:
        return DEFAULT_CACHE_CAP_BYTES


def reset_for_tests() -> None:
    """Drop process-level state (loaded table, publish dedup, config) —
    test isolation only."""
    with _LOCK:
        _LOADED.clear()
        _PUBLISHED.clear()
        _DYN_KWARGS.clear()
        _STATE.update(enabled=True, root=None, cap_bytes=None,
                      keep_min=None, cache_cap_bytes=None,
                      managed_cache=None)


# -- keys --------------------------------------------------------------------

_CODE_DIGEST: List[Optional[str]] = [None]


def code_digest() -> str:
    """SHA-256 over this package's source files (names + bytes).  Folded
    into every key: the signature scheme cannot see a code change that
    alters what a program COMPUTES at the same shapes, so any source drift
    invalidates the whole fleet's entries — conservative and safe."""
    if _CODE_DIGEST[0] is None:
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.abspath(__file__))
        for path in sorted(glob.glob(os.path.join(pkg, "**", "*.py"),
                                     recursive=True)):
            h.update(os.path.relpath(path, pkg).encode())
            try:
                with open(path, "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(b"?")
        _CODE_DIGEST[0] = h.hexdigest()[:16]
    return _CODE_DIGEST[0]


def _aval_sig(x: Any) -> Any:
    """Canonical JSON-able signature of one pytree leaf: (shape, dtype) for
    anything array-like, repr otherwise (static scalars riding in args)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return [list(int(d) for d in shape), str(dtype)]
    if x is None or isinstance(x, (bool, int, float, str)):
        return repr(x)
    return repr(type(x).__name__)


def args_signature(args: Any) -> List[Any]:
    """Flattened aval signature of a pytree of call arguments."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return [str(treedef)] + [_aval_sig(leaf) for leaf in leaves]


def program_key(kind: str, family: str, rung: int,
                statics: Optional[Dict[str, Any]],
                avals: Any) -> str:
    """The content address: every field that determines which executable is
    correct to run, hashed into one digest.  ``avals`` is anything
    JSON-serializable (usually ``args_signature(args)``)."""
    from .aot import abi_stamp
    doc = {
        "v": REGISTRY_FORMAT_VERSION,
        "kind": str(kind),
        "family": str(family),
        "rung": int(rung),
        "statics": statics or {},
        "avals": avals,
        "abi": abi_stamp(),
        "code": code_digest(),
    }
    blob = json.dumps(doc, sort_keys=True, default=repr).encode()
    return hashlib.sha256(blob).hexdigest()


def model_family_digest(bundle_dir: str) -> Optional[str]:
    """Content digest identifying a model's computation: the serialized DAG
    (model.json) + fitted parameters (params.npz).  Computed from file
    bytes, so the export side (temp bundle dir) and every later load of the
    renamed bundle — or a byte-identical copy deployed as another tenant —
    agree without a MANIFEST."""
    h = hashlib.sha256()
    found = False
    for name in ("model.json", "params.npz"):
        path = os.path.join(bundle_dir, name)
        try:
            with open(path, "rb") as fh:
                while True:
                    b = fh.read(1 << 20)
                    if not b:
                        break
                    h.update(b)
            found = True
        except OSError:
            h.update(b"-")
    return h.hexdigest()[:24] if found else None


# -- storage layout ----------------------------------------------------------

def _platform_dir(root: str) -> str:
    try:
        import jax
        plat = jax.default_backend()
    except Exception:  # noqa: BLE001 — jax-less host
        plat = "cpu"
    return os.path.join(root, plat)


def entry_dir(key: str, root: Optional[str] = None) -> Optional[str]:
    root = root or registry_root()
    if not root:
        return None
    return os.path.join(_platform_dir(root), key[:2], key)


def _fsync_file(path: str) -> None:
    with contextlib.suppress(OSError):
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


# -- publish -----------------------------------------------------------------

def publish(key: str, payload: bytes, meta: Optional[Dict[str, Any]] = None,
            root: Optional[str] = None) -> bool:
    """Atomically install ``payload`` under ``key``.  The entry is staged as
    a temp sibling directory (payload + metadata, both fsynced) and renamed
    into place — concurrent publishers of the same key converge on one
    valid entry; the losers' stages are discarded.  Returns True when this
    call (or a racing winner) left a valid entry behind."""
    from .aot import abi_stamp
    from .resilience import record_failure
    final = entry_dir(key, root)
    if final is None:
        return False
    if os.path.isdir(final):
        _count("aot_registry.publish_dedup")
        return True
    parent = os.path.dirname(final)
    tmp = os.path.join(parent,
                       f".tmp-{key[:8]}-{os.getpid()}-{threading.get_ident()}")
    try:
        os.makedirs(tmp, exist_ok=True)
        doc = dict(meta or {})
        doc.update({
            "formatVersion": REGISTRY_FORMAT_VERSION,
            "key": key,
            "abi": abi_stamp(),
            "payloadSha256": hashlib.sha256(payload).hexdigest(),
            "payloadBytes": len(payload),
            "createdAt": time.time(),
        })
        ppath = os.path.join(tmp, ENTRY_PAYLOAD_NAME)
        with open(ppath, "wb") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        mpath = os.path.join(tmp, ENTRY_META_NAME)
        with open(mpath, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        _fsync_file(tmp)
        try:
            os.rename(tmp, final)
        except OSError:
            # a racing publisher renamed first: their entry is equally
            # valid (same content address) — converge, discard ours
            if os.path.isdir(final):
                _count("aot_registry.publish_dedup")
                return True
            raise
        _fsync_file(parent)
        _count("aot_registry.publishes")
        _count("aot_registry.published_bytes", len(payload))
        enforce_budget(root=root)
        return True
    except Exception as e:  # noqa: BLE001 — the registry is optional
        record_failure("aot_registry", "swallowed", e,
                       point="aot_registry.publish", key=key[:16])
        return False
    finally:
        if os.path.isdir(tmp):
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)


# -- lookup / install --------------------------------------------------------

def lookup(key: str, root: Optional[str] = None) -> Optional[bytes]:
    """Digest- and ABI-verified payload for ``key``, or None.  A tampered or
    torn entry is deleted and recorded as ``degraded`` — the caller falls
    back to JIT, and the next publisher repairs the slot."""
    from .aot import abi_mismatch
    from .resilience import record_failure
    d = entry_dir(key, root)
    if d is None or not os.path.isdir(d):
        _count("aot_registry.misses")
        return None
    try:
        with open(os.path.join(d, ENTRY_META_NAME)) as fh:
            meta = json.load(fh)
        if meta.get("formatVersion", 0) > REGISTRY_FORMAT_VERSION:
            _count("aot_registry.misses")
            return None
        reason = abi_mismatch(meta.get("abi"))
        if reason is not None:
            # cross-jaxVersion / platform / machine stamps never install;
            # the entry is not corrupt — another fleet member owns it
            _count("aot_registry.misses")
            _count("aot_registry.abi_skips")
            return None
        ppath = os.path.join(d, ENTRY_PAYLOAD_NAME)
        with open(ppath, "rb") as fh:
            payload = fh.read()
        if hashlib.sha256(payload).hexdigest() != meta.get("payloadSha256"):
            raise ValueError("payload digest mismatch")
        # touch atime for the LRU eviction order (best-effort: noatime
        # mounts fall back to mtime ordering)
        with contextlib.suppress(OSError):
            now = time.time()
            os.utime(ppath, (now, os.stat(ppath).st_mtime))
        _count("aot_registry.hits")
        return payload
    except Exception as e:  # noqa: BLE001
        _count("aot_registry.tampered")
        _count("aot_registry.misses")
        record_failure("aot_registry", "degraded", e,
                       point="aot_registry.lookup", key=key[:16],
                       fallback="JIT compile")
        import shutil
        shutil.rmtree(d, ignore_errors=True)
        return None


def shared_load(digest: str, payload_rec: Dict[str, Any]) -> Any:
    """Deserialize ``payload_rec`` (serialize_executable triple) memoized on
    ``digest`` — the cross-tenant seam: every caller installing the same
    payload shares ONE loaded executable and its device memory."""
    with _LOCK:
        fn = _LOADED.get(digest)
        if fn is not None:
            _count("aot_registry.shared_hits")
            return fn
    from jax.experimental.serialize_executable import deserialize_and_load
    fn = deserialize_and_load(payload_rec["payload"], payload_rec["inTree"],
                              payload_rec["outTree"])
    with _LOCK:
        # a racing loader may have beaten us — prefer the incumbent so
        # everyone converges on one object
        win = _LOADED.setdefault(digest, fn)
        if win is not fn:
            _count("aot_registry.shared_hits")
        else:
            _count("aot_registry.installs")
    return win


def loaded_count() -> int:
    with _LOCK:
        return len(_LOADED)


def _drop_loaded(digest: str) -> None:
    with _LOCK:
        _LOADED.pop(digest, None)


def _dynamic_kwarg_names(in_tree: Any) -> List[str]:
    """Top-level names of the DYNAMIC keyword arguments a lowered call was
    flattened with.  ``in_tree`` describes ``((args...), {kwargs...})``;
    static_argnames never appear in it, so unflattening the kwargs child
    recovers exactly the traced kwargs (e.g. ``tol``) the executable must
    be called with."""
    import jax
    try:
        children = jax.tree_util.treedef_children(in_tree)
        if len(children) != 2:
            return []
        kwd = children[1]
        proto = jax.tree_util.tree_unflatten(
            kwd, list(range(kwd.num_leaves)))
        if isinstance(proto, dict):
            return sorted(str(k) for k in proto)
    except Exception:  # noqa: BLE001 — fall back to positional-only call
        pass
    return []


# -- fresh serialization (satellite: cache-loaded executables) ---------------

def _reset_jax_compile_cache() -> None:
    """Drop jax's memoized compilation-cache object so the next compile
    re-reads ``jax_compilation_cache_dir``.  jax captures the cache object
    on first use; config updates alone are silently ignored after that."""
    with contextlib.suppress(Exception):
        from jax._src import compilation_cache
        compilation_cache.reset_cache()


@contextlib.contextmanager
def fresh_compile_env():
    """Suspend EVERY compile-caching layer so ``lower().compile()`` inside
    the block is a real backend build: the persistent cache dir is unset,
    jax's memoized cache object dropped, and the in-memory jit/compilation
    memos cleared (they would otherwise hand the same cache-loaded
    executable straight back).  Later dispatches re-trace — acceptable for
    the rare cache-warm-but-registry-cold publish path this guards."""
    import jax
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_compile_cache()
    jax.clear_caches()
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        _reset_jax_compile_cache()


def payload_roundtrips(rec: bytes) -> bool:
    """Ground-truth publishability check: deserialize the payload.  An
    executable jax re-loaded from the PERSISTENT COMPILE CACHE serializes
    without its fusion object code and fails exactly here ("Symbols not
    found") — the PR-9 hazard.  Every detection scheme based on cache-hit
    counters has a blind spot (the hit may predate serialization, e.g.
    during export warm-up scoring), so publishers validate the artifact
    itself."""
    try:
        from jax.experimental.serialize_executable import \
            deserialize_and_load
        obj = pickle.loads(rec)
        deserialize_and_load(obj["payload"], obj["inTree"], obj["outTree"])
        return True
    except Exception:  # noqa: BLE001
        return False


def serialize_fresh(lower_fn, label: str = "") -> Optional[bytes]:
    """``lower_fn() -> Lowered``; returns serialized executable bytes whose
    payload round-trips through ``deserialize_and_load``.

    A cache-warm process must not silently publish garbage OR silently
    skip publishing: we compile once normally, validate the payload by
    deserializing it, and on failure re-lower + re-compile once under
    :func:`fresh_compile_env` so the published payload is always a fresh
    backend build."""
    from .resilience import record_failure
    from jax.experimental.serialize_executable import serialize

    def _attempt() -> bytes:
        compiled = lower_fn().compile()
        payload, in_tree, out_tree = serialize(compiled)
        buf = io.BytesIO()
        pickle.dump({"payload": payload, "inTree": in_tree,
                     "outTree": out_tree,
                     "dynKwargs": _dynamic_kwarg_names(in_tree)},
                    buf, protocol=4)
        return buf.getvalue()
    try:
        with contextlib.suppress(Exception):
            rec = _attempt()
            if payload_roundtrips(rec):
                return rec
        _count("aot_registry.recompiles_for_publish")
        with fresh_compile_env():
            rec = _attempt()
        return rec if payload_roundtrips(rec) else None
    except Exception as e:  # noqa: BLE001 — publish is strictly optional
        record_failure("aot_registry", "swallowed", e,
                       point="aot_registry.serialize", detail=label)
        return None


def _queue_publish(key: str, label: str, lower_fn,
                   meta: Optional[Dict[str, Any]] = None) -> None:
    """Serialize + publish on the background pre-trace thread: the publish
    compile never lands inside a foreground fit/score wall, and
    ``aot.pretrace_drain`` (which export_bundle already calls before
    toggling the cache flag) serializes us against save-time exports."""
    with _LOCK:
        if key in _PUBLISHED:
            return
        _PUBLISHED.add(key)

    def _job():
        if os.path.isdir(entry_dir(key) or "/nonexistent"):
            _count("aot_registry.publish_dedup")
            return
        rec = serialize_fresh(lower_fn, label)
        if rec is not None:
            publish(key, rec, meta)
    from .aot import pretrace_submit
    pretrace_submit(f"registry-publish:{label}", _job)


# -- the train seam ----------------------------------------------------------

def _single_device_args(args: Any) -> bool:
    """Registry executables are compiled from unsharded host avals; a
    mesh-sharded grid program is a different (GSPMD) computation, so any
    multi-device argument bypasses the registry entirely."""
    import jax
    for leaf in jax.tree_util.tree_leaves(args):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                if len(sharding.device_set) > 1:
                    return False
            except Exception:  # noqa: BLE001 — unknown sharding: be safe
                return False
    return True


def _grid_key(label: str, fn_args: tuple,
              sig_statics: Optional[Dict[str, Any]], rung: int) -> str:
    return program_key("grid", label, rung, sig_statics,
                       args_signature(fn_args))


def grid_call(label: str, fn, args: tuple, *,
              static_kwargs: Optional[Dict[str, Any]] = None,
              sig_statics: Optional[Dict[str, Any]] = None,
              rung: Optional[int] = None):
    """Dispatch a batched grid-fit program through the registry.

    Hit: the installed executable runs — zero traces, zero compiles, and
    (via the shared table) one copy of the program per process no matter
    how many candidates/tenants dispatch it.  Miss: the ordinary jit call
    runs (persistent-cache-aware, pre-trace-warmed) and a fresh serialized
    build is published in the background for the rest of the fleet.  Any
    installed-executable failure uninstalls it and retries on the jit path
    — degrade, never break."""
    statics = static_kwargs or {}
    if rung is None:
        first = args[0] if args else None
        rung = int(getattr(first, "shape", (0,))[0] or 0)
    if not (registry_enabled() and _single_device_args(args)):
        _count("aot_registry.bypass")
        return fn(*args, **statics)
    from .resilience import record_failure
    key = _grid_key(label, args, sig_statics or statics, rung)
    with _LOCK:
        loaded = _LOADED.get(key)
    if loaded is None:
        payload = lookup(key)
        if payload is not None:
            try:
                rec = pickle.loads(payload)
                with _LOCK:
                    _DYN_KWARGS[key] = tuple(rec.get("dynKwargs") or ())
                loaded = shared_load(key, rec)
            except Exception as e:  # noqa: BLE001
                record_failure("aot_registry", "degraded", e,
                               point="aot_registry.install", detail=label,
                               fallback="JIT compile")
                _count("aot_registry.install_failures")
                loaded = None
    else:
        _count("aot_registry.hits")
    if loaded is not None:
        try:
            # replay exactly the traced kwargs the executable was lowered
            # with (static_argnames are baked in; traced kwargs like
            # linear_grid_fit's tol must be passed)
            with _LOCK:
                dyn = _DYN_KWARGS.get(key, ())
            return loaded(*args, **{k: statics[k] for k in dyn
                                    if k in statics})
        except Exception as e:  # noqa: BLE001 — shape/ABI drift the stamp
            # could not see: uninstall and fall back to the jit path
            record_failure("aot_registry", "degraded", e,
                           point="aot_registry.call", detail=label,
                           fallback="JIT recompile")
            _count("aot_registry.call_fallbacks")
            _drop_loaded(key)
    out = fn(*args, **statics)
    _queue_publish(key, label,
                   lambda: fn.lower(*args, **statics),
                   {"kind": "grid", "family": label, "rung": int(rung)})
    return out


def grid_compile(label: str, fn, args: tuple, *,
                 static_kwargs: Optional[Dict[str, Any]] = None,
                 sig_statics: Optional[Dict[str, Any]] = None,
                 rung: Optional[int] = None) -> None:
    """Compile-only twin of :func:`grid_call` for the background pre-trace:
    registry hit → deserialize into the shared table NOW (the foreground
    fit then dispatches it with zero compiles); miss → lower+compile as
    before (populating the persistent cache) and publish the fresh build."""
    statics = static_kwargs or {}
    if rung is None:
        first = args[0] if args else None
        rung = int(getattr(first, "shape", (0,))[0] or 0)
    if not (registry_enabled() and _single_device_args(args)):
        fn.lower(*args, **statics).compile()
        return
    key = _grid_key(label, args, sig_statics or statics, rung)
    with _LOCK:
        if key in _LOADED:
            return
    payload = lookup(key)
    if payload is not None:
        try:
            rec = pickle.loads(payload)
            with _LOCK:
                _DYN_KWARGS[key] = tuple(rec.get("dynKwargs") or ())
            shared_load(key, rec)
            return
        except Exception:  # noqa: BLE001 — fall through to the compile
            _count("aot_registry.install_failures")
    rec = serialize_fresh(lambda: fn.lower(*args, **statics), label)
    if rec is not None:
        with _LOCK:
            _PUBLISHED.add(key)
        publish(key, rec, {"kind": "grid", "family": label,
                           "rung": int(rung)})
        with contextlib.suppress(Exception):
            # install our own build too: the foreground fit dispatches the
            # deserialized executable instead of re-tracing through jit
            loaded_rec = pickle.loads(rec)
            with _LOCK:
                _DYN_KWARGS[key] = tuple(loaded_rec.get("dynKwargs") or ())
            shared_load(key, loaded_rec)
    else:
        # unserializable program (or registry write failure): keep the old
        # contract — a plain compile that warms the persistent cache
        fn.lower(*args, **statics).compile()


# -- the scoring seam --------------------------------------------------------

def score_key(family: str, key_tuple: Tuple, arrays: Any) -> str:
    """Content address of one fused scoring program: the model-content
    family digest, the program-table key (stage uids are recorded in
    model.json, so they are stable for every load of the same bundle — and
    for every byte-identical tenant copy), and the input avals.  ``arrays``
    is the call-time pytree or its captured ShapeDtypeStruct specs — both
    hash identically."""
    uids, keep_intermediate, rows = key_tuple
    return program_key("score", family, int(rows),
                       {"uids": list(uids),
                        "keepIntermediate": bool(keep_intermediate)},
                       args_signature(arrays))


def publish_score(family: str, key_tuple: Tuple, program,
                  rec_bytes: bytes, specs: Any = None) -> bool:
    """Publish one export-serialized scoring executable (``aot.py``'s
    ``_serialize_key`` record — a fresh build, the export loop already
    compiles with the persistent cache disabled).  ``specs`` overrides the
    program's first-call avals — the aval-VARIANT seam (ISSUE 19): sparse
    nnz rungs publish one executable per observed input signature under
    the same program-table key."""
    if specs is None:
        specs = program._input_specs.get(key_tuple)
    if specs is None:
        return False
    key = score_key(family, key_tuple, specs)
    return publish(key, rec_bytes,
                   {"kind": "score", "family": family,
                    "rung": int(key_tuple[2])})


def try_install_score(program, key_tuple: Tuple, arrays: Any,
                      sig: Optional[str] = None) -> bool:
    """Consumer side of the scoring seam, called by ``ScoreProgram`` right
    before it would dispatch a freshly-traced program: a registry hit
    installs the published executable over the jit entry, so the call runs
    with zero compiles (pool workers booting on AOT-less bundles, tenants
    activating, lifecycle re-scores).  With ``sig`` (the caller's canonical
    aval signature) the executable installs as a per-(key, sig) VARIANT —
    the registry address already hashes the avals, so each sparse nnz rung
    resolves to its own published build."""
    from .resilience import record_failure
    family = getattr(program, "registry_family", None)
    if not (family and registry_enabled()):
        return False
    try:
        key = score_key(family, key_tuple, arrays)
        payload = lookup(key)
        if payload is None:
            return False
        rec = pickle.loads(payload)
        fn = shared_load(key, rec)
        program.install_executable(key_tuple, fn, rec["canonOut"],
                                   rec["metas"], sig=sig)
        return True
    except Exception as e:  # noqa: BLE001 — stay on the jit path
        record_failure("aot_registry", "degraded", e,
                       point="aot_registry.score_install",
                       fallback="JIT compile")
        _count("aot_registry.install_failures")
        return False


# -- stats / GC --------------------------------------------------------------

def registry_bytes(root: Optional[str] = None) -> int:
    root = root or registry_root()
    if not root or not os.path.isdir(root):
        return 0
    total = 0
    for dirpath, dirnames, filenames in os.walk(root):
        # the managed compile cache is accounted separately
        if os.path.basename(dirpath) == "compile-cache":
            dirnames[:] = []
            continue
        for f in filenames:
            with contextlib.suppress(OSError):
                total += os.stat(os.path.join(dirpath, f)).st_size
    return total


def registry_stats() -> Dict[str, Any]:
    """Counter snapshot + on-disk size — telemetry, /metrics and bench aux
    read this one dict."""
    from .telemetry import REGISTRY
    c = REGISTRY.snapshot()["counters"]

    def g(name: str) -> int:
        return int(c.get(f"aot_registry.{name}", 0))
    return {
        "hits": g("hits"), "misses": g("misses"),
        "publishes": g("publishes"), "evictions": g("evictions"),
        "installs": g("installs"), "shared_hits": g("shared_hits"),
        "bypass": g("bypass"), "tampered": g("tampered"),
        "abi_skips": g("abi_skips"),
        "call_fallbacks": g("call_fallbacks"),
        "recompiles_for_publish": g("recompiles_for_publish"),
        "bytes": registry_bytes(),
        "root": registry_root(),
        "enabled": registry_enabled(),
    }


def _entries(root: str) -> List[Dict[str, Any]]:
    out = []
    for meta_path in glob.glob(os.path.join(
            root, "*", "??", "*", ENTRY_META_NAME)):
        d = os.path.dirname(meta_path)
        size = 0
        atime = 0.0
        for f in (ENTRY_PAYLOAD_NAME, ENTRY_META_NAME):
            with contextlib.suppress(OSError):
                st = os.stat(os.path.join(d, f))
                size += st.st_size
                # LRU rank comes from the PAYLOAD alone: lookup() touches
                # its atime on every hit, whereas entry.json is read by
                # this very scan — counting it would reset the order
                if f == ENTRY_PAYLOAD_NAME:
                    atime = max(atime, st.st_atime, st.st_mtime)
        abi = None
        with contextlib.suppress(Exception):
            with open(meta_path) as fh:
                abi = json.load(fh).get("abi")
        out.append({"dir": d, "bytes": size, "atime": atime, "abi": abi})
    return out


def enforce_budget(root: Optional[str] = None,
                   cap_bytes: Optional[int] = None,
                   keep_min: Optional[int] = None) -> int:
    """Size-capped GC: evict entries (oldest atime first, stale-ABI entries
    before anything else) until the registry fits the byte budget, never
    touching the ``keep_min`` most recently used.  Each eviction leaves an
    ``evicted`` FailureLog note.  Returns the number evicted."""
    from .aot import abi_mismatch
    from .resilience import record_failure
    root = root or registry_root()
    if not root or not os.path.isdir(root):
        return 0
    cap = _cap_bytes() if cap_bytes is None else int(cap_bytes)
    keep = _keep_min() if keep_min is None else int(keep_min)
    entries = _entries(root)
    # stale-ABI first (they can never install here — a fleet of one
    # platform generation keeps only its own), then LRU by atime
    stale = [e for e in entries if abi_mismatch(e["abi"]) is not None]
    fresh = [e for e in entries if abi_mismatch(e["abi"]) is None]
    fresh.sort(key=lambda e: e["atime"])
    total = sum(e["bytes"] for e in entries)
    evicted = 0
    import shutil

    def _evict(e: Dict[str, Any], why: str) -> None:
        nonlocal total, evicted
        shutil.rmtree(e["dir"], ignore_errors=True)
        total -= e["bytes"]
        evicted += 1
        _count("aot_registry.evictions")
        record_failure("aot_registry", "evicted", None,
                       point="aot_registry.gc", entry=os.path.basename(
                           e["dir"])[:16], bytes=e["bytes"], reason=why)
    if total > cap:
        for e in stale:
            if total <= cap:
                break
            _evict(e, "stale ABI")
    evictable = fresh[:-keep] if keep > 0 else fresh
    for e in evictable:
        if total <= cap:
            break
        _evict(e, "LRU under byte budget")
    return evicted


def gc_compile_cache(cache_dir: Optional[str] = None,
                     cap_bytes: Optional[int] = None) -> int:
    """The same LRU-by-atime byte budget for the persistent XLA compile
    cache (it otherwise grows unboundedly — every new shape ladder rung,
    jax upgrade, or workflow variant appends executables forever).  jax's
    cache files are opaque, so eviction is purely LRU; a wrongly-evicted
    entry just recompiles.  Returns the number of files removed."""
    from .resilience import record_failure
    if cache_dir is None:
        cache_dir = os.environ.get("TRANSMOGRIFAI_COMPILE_CACHE") or \
            managed_compile_cache()
        if not cache_dir or cache_dir == "0":
            try:
                import jax
                cache_dir = jax.config.jax_compilation_cache_dir
            except Exception:  # noqa: BLE001
                cache_dir = None
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0
    cap = _cache_cap_bytes() if cap_bytes is None else int(cap_bytes)
    files = []
    for dirpath, _dirnames, filenames in os.walk(cache_dir):
        for f in filenames:
            p = os.path.join(dirpath, f)
            with contextlib.suppress(OSError):
                st = os.stat(p)
                files.append((max(st.st_atime, st.st_mtime), st.st_size, p))
    total = sum(s for _, s, _ in files)
    if total <= cap:
        return 0
    files.sort()
    removed = 0
    for _at, size, p in files:
        if total <= cap:
            break
        with contextlib.suppress(OSError):
            os.unlink(p)
            total -= size
            removed += 1
            _count("aot_registry.cache_evictions")
    if removed:
        record_failure("aot_registry", "evicted", None,
                       point="aot_registry.cache_gc", files=removed,
                       cache=cache_dir, reason="compile cache byte budget")
    return removed
