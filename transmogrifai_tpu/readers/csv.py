"""CSV ingestion with automatic schema inference (reference:
readers/src/main/scala/com/salesforce/op/readers/CSVReaders.scala and
CSVAutoReaders.scala; inference ≙ FeatureBuilder.fromDataFrame auto-typing).

Two paths share the same typed-column semantics on well-formed input
(numeric inference is finite-only and Integral holds for every row on both;
see ``infer_schema_from_records``).  Known divergence, malformed rows only:
stray text after a closing quote (``a,"b"x,c``) is dropped by the native
parser (→ ``b``) but appended by Python's csv module (→ ``bx``); neither
path shifts later columns.

This tolerate-and-continue contract for malformed rows is now UNIFORM
across readers (quality.py): Avro skips undecodable blocks, Parquet nulls
unconvertible timestamp cells, and streaming/record readers quarantine
poison records per-row under an ambient ``QualityConfig`` — each recording
a typed violation instead of raising mid-file, as this reader always has.

* **native columnar** (default): the C++ parser (`native/fastcsv.cpp`) goes
  bytes → typed columns in one pass — no per-row Python objects — and
  ``generate_batch`` builds the ``ColumnBatch`` straight from the columnar
  store when every raw feature uses the default by-name extractor.  This is
  the runtime analog of the reference's executor-side record parsing, done
  native instead of JVM.
* **pure Python** fallback (no toolchain, custom extractors, exotic kinds):
  row dicts through ``FeatureGeneratorStage.extract_column``.
"""

from __future__ import annotations

import csv
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from ..columns import Column, ColumnBatch, column_from_values
from ..features import infer_feature_kind
from ..types import (Binary, Date, DateTime, FeatureType, Integral, Real,
                     Text, is_numeric_kind, is_text_kind)
from .base import DataReader, _generator_of


def _coerce(v: str) -> Any:
    if v is None or v == "":
        return None
    return v


def read_csv_records(path: str, headers: Optional[Sequence[str]] = None,
                     has_header: Optional[bool] = None) -> List[Dict[str, Any]]:
    """Read CSV into records.  If ``headers`` is None, the first row is used as
    the header (has_header defaults True in that case)."""
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    if not rows:
        return []
    if headers is None:
        headers = rows[0]
        rows = rows[1:]
    elif has_header:
        rows = rows[1:]
    return [{h: _coerce(v) for h, v in zip(headers, row)} for row in rows]


def infer_schema_from_records(records: Sequence[Dict[str, Any]],
                              sample: int = 1000) -> Dict[str, Type[FeatureType]]:
    if not records:
        return {}
    schema: Dict[str, Type[FeatureType]] = {}
    cols = records[0].keys()
    subset = records[:sample]
    for c in cols:
        kind = infer_feature_kind([r.get(c) for r in subset])
        # Integral/Binary inferred from the sample must hold for EVERY row —
        # the native parser's is_int covers the whole file, and a column that
        # turns float after the sample would silently truncate through
        # _typed_scalar's int(float(v)).  One cheap full pass keeps the two
        # ingestion paths agreeing.
        if kind in (Integral, Binary) and len(records) > sample:
            kind = infer_feature_kind([r.get(c) for r in records])
        schema[c] = kind
    return schema


def _typed_records(records: List[Dict[str, Any]],
                   schema: Dict[str, Type[FeatureType]]) -> List[Dict[str, Any]]:
    """Coerce string values to the schema's python types."""
    return [{k: _typed_scalar(v, schema.get(k)) for k, v in r.items()}
            for r in records]


def _typed_scalar(v, kind):
    if v is None or kind is None:
        return v
    if issubclass(kind, Binary):
        return _as_bool(v)
    if issubclass(kind, Integral):
        try:
            return int(v)          # exact for arbitrarily large integers
        except (TypeError, ValueError):
            return int(float(v))
    if issubclass(kind, Real):
        return float(v)
    return v


def _as_bool(v: Any) -> bool:
    if isinstance(v, float):
        return v != 0.0
    return str(v).strip().lower() in ("1", "true", "yes", "t")


def _csv_headers(path: str) -> List[str]:
    with open(path, newline="") as f:
        row = next(csv.reader(f), [])
    return list(row)


class CSVReader(DataReader):
    """CSV file reader (≙ CSVReaders / CSVAutoReaders).

    ``schema``: optional name → FeatureType mapping; inferred if absent.
    """

    def __init__(self, path: str, headers: Optional[Sequence[str]] = None,
                 schema: Optional[Dict[str, Type[FeatureType]]] = None,
                 key_field: Optional[str] = None, has_header: Optional[bool] = None):
        self.path = path
        self._key_field = key_field
        self._store: Optional[Dict[str, Any]] = None   # name → f64 array | list
        self._n_rows = 0

        if headers is None:
            headers = _csv_headers(path)
            skip_first = True
        else:
            headers = list(headers)
            skip_first = bool(has_header)

        from ..native import load
        native = load("fastcsv")
        records = None
        if native is not None:
            try:
                # with a user schema, only columns the schema types as
                # plain-numeric may take the float store; Binary goes through
                # raw text (record-path _as_bool semantics), and columns NOT
                # in the schema keep their raw text for read()/joins
                force = ([i for i, h in enumerate(headers)
                          if h not in schema
                          or not is_numeric_kind(schema[h])
                          or issubclass(schema[h], Binary)]
                         if schema else [])
                n, cols, is_int = native.parse(path, len(headers),
                                               skip_first, force)
                self._store = dict(zip(headers, cols))
                self._is_int = dict(zip(headers, is_int))
                self._n_rows = n
            except Exception:  # pragma: no cover — fall back to Python
                self._store = None
        if self._store is None:
            raw = read_csv_records(path, headers=headers,
                                   has_header=skip_first or has_header)
            self.schema = dict(schema) if schema else infer_schema_from_records(raw)
            records = _typed_records(raw, self.schema)
            self._n_rows = len(records)
        else:
            self.schema = (dict(schema) if schema
                           else self._infer_schema_from_store())

        key_fn = ((lambda r: r.get(key_field)) if key_field
                  else (lambda r: id(r)))
        super().__init__(records=records, key_fn=key_fn)

    # -- columnar store helpers -------------------------------------------
    def _infer_schema_from_store(self, sample: int = 1000) -> Dict[str, Type[FeatureType]]:
        schema: Dict[str, Type[FeatureType]] = {}
        for name, col in self._store.items():
            if isinstance(col, np.ndarray):
                vals = col[:sample]
                as_int = self._is_int.get(name, False)
                pyvals = [None if np.isnan(v)
                          else (int(v) if as_int else float(v))
                          for v in vals]
                kind = infer_feature_kind(pyvals)
                # Binary's {0,1} constraint must hold for EVERY row, not just
                # the sample (Integral already does: is_int is whole-file) —
                # mirrors infer_schema_from_records' full-column re-check
                if kind is Binary and len(col) > sample:
                    present = col[~np.isnan(col)]
                    if not bool(np.isin(present, (0.0, 1.0)).all()):
                        kind = Integral if as_int else Real
            else:
                kind = infer_feature_kind(col[:sample])
                # text column (some field failed numeric parse): a clean
                # numeric-looking sample must be re-verified over all rows,
                # as the record path does
                if kind in (Integral, Binary) and len(col) > sample:
                    kind = infer_feature_kind(col)
            schema[name] = kind
        return schema

    def _store_column(self, name: str, kind: Type[FeatureType],
                      non_nullable: bool) -> Column:
        col = self._store[name]
        if is_numeric_kind(kind):
            if isinstance(col, np.ndarray):
                mask = ~np.isnan(col)
                if issubclass(kind, Binary):
                    arr: Any = np.where(mask, col != 0.0, False).astype(bool)
                elif issubclass(kind, (Date, DateTime, Integral)):
                    arr = np.where(mask, col, 0.0).astype(np.int64)
                else:
                    arr = col.astype(np.float32)
                    if non_nullable:
                        arr = np.where(mask, arr, np.float32(0.0))
                return Column(kind, arr, mask=None if non_nullable else mask)
            if issubclass(kind, Binary):
                vals = [None if v is None else _as_bool(v) for v in col]
                return column_from_values(kind, vals)
            # schema says numeric but the column has non-numeric text — same
            # error the typed-record path raises
            vals = [None if v is None else float(v) for v in col]
            return column_from_values(kind, vals)
        if is_text_kind(kind):
            if isinstance(col, np.ndarray):
                as_int = self._is_int.get(name, False)
                vals = [None if np.isnan(v)
                        else (str(int(v)) if as_int else str(float(v)))
                        for v in col]
            else:
                vals = col
            return column_from_values(kind, vals)
        raise TypeError(kind)  # caller falls back to the record path

    def generate_batch(self, raw_features) -> ColumnBatch:
        st = self._store
        if st is not None:
            fast = all(
                (not _generator_of(f).has_custom_extract)
                and f.name in st
                and (is_numeric_kind(f.kind) or is_text_kind(f.kind))
                for f in raw_features)
            if fast:
                cols: Dict[str, Column] = {}
                for f in raw_features:
                    fill_zero = f.kind.non_nullable
                    c = self._store_column(f.name, f.kind, fill_zero)
                    cols[f.name] = c
                cols["key"] = self._key_column()
                return ColumnBatch(cols, self._n_rows)
        return super().generate_batch(raw_features)

    def _key_column(self) -> Column:
        kf = self._key_field
        if kf and kf in self._store:
            col = self._store[kf]
            if isinstance(col, np.ndarray):
                as_int = self._is_int.get(kf, False)
                keys = [("None" if np.isnan(v)
                         else (str(int(v)) if as_int else str(float(v))))
                        for v in col]
            else:
                keys = [("None" if v is None else str(v)) for v in col]
        else:
            keys = [str(i) for i in range(self._n_rows)]
        return column_from_values(Text, keys)

    # -- record path (read(), joins, aggregates) --------------------------
    def read(self) -> List[Dict[str, Any]]:
        if self._records is None and self._store is not None:
            self._records = self._records_from_store()
        return super().read()

    def _records_from_store(self) -> List[Dict[str, Any]]:
        n = self._n_rows
        typed: Dict[str, List[Any]] = {}
        for name, col in self._store.items():
            kind = self.schema.get(name)
            if isinstance(col, np.ndarray):
                mask = ~np.isnan(col)
                if kind is not None and issubclass(kind, Binary):
                    vals = [bool(v != 0.0) if m else None
                            for v, m in zip(col, mask)]
                elif kind is not None and issubclass(kind, Integral):
                    vals = [int(v) if m else None for v, m in zip(col, mask)]
                elif kind is not None and issubclass(kind, Real):
                    vals = [float(v) if m else None for v, m in zip(col, mask)]
                else:
                    as_int = self._is_int.get(name, False)
                    vals = [(str(int(v)) if as_int else str(float(v))) if m
                            else None for v, m in zip(col, mask)]
            else:
                vals = [_typed_scalar(v, kind) for v in col]
            typed[name] = vals
        names = list(typed)
        return [
            {h: typed[h][i] for h in names} for i in range(n)
        ]

