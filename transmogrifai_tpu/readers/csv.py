"""CSV ingestion with automatic schema inference (reference:
readers/src/main/scala/com/salesforce/op/readers/CSVReaders.scala and
CSVAutoReaders.scala; inference ≙ FeatureBuilder.fromDataFrame auto-typing).
"""

from __future__ import annotations

import csv
from typing import Any, Dict, List, Optional, Sequence, Type

from ..features import infer_feature_kind
from ..types import Binary, FeatureType, Integral, Real, Text
from .base import DataReader


def _coerce(v: str) -> Any:
    if v is None or v == "":
        return None
    return v


def read_csv_records(path: str, headers: Optional[Sequence[str]] = None,
                     has_header: Optional[bool] = None) -> List[Dict[str, Any]]:
    """Read CSV into records.  If ``headers`` is None, the first row is used as
    the header (has_header defaults True in that case)."""
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    if not rows:
        return []
    if headers is None:
        headers = rows[0]
        rows = rows[1:]
    elif has_header:
        rows = rows[1:]
    return [{h: _coerce(v) for h, v in zip(headers, row)} for row in rows]


def infer_schema_from_records(records: Sequence[Dict[str, Any]],
                              sample: int = 1000) -> Dict[str, Type[FeatureType]]:
    if not records:
        return {}
    schema: Dict[str, Type[FeatureType]] = {}
    cols = records[0].keys()
    subset = records[:sample]
    for c in cols:
        schema[c] = infer_feature_kind([r.get(c) for r in subset])
    return schema


def _typed_records(records: List[Dict[str, Any]],
                   schema: Dict[str, Type[FeatureType]]) -> List[Dict[str, Any]]:
    """Coerce string values to the schema's python types."""
    out = []
    for r in records:
        t: Dict[str, Any] = {}
        for k, v in r.items():
            kind = schema.get(k)
            if v is None or kind is None:
                t[k] = v
            elif issubclass(kind, Binary):
                t[k] = str(v).strip().lower() in ("1", "true", "yes", "t")
            elif issubclass(kind, Integral):
                t[k] = int(float(v))
            elif issubclass(kind, Real):
                t[k] = float(v)
            else:
                t[k] = v
        out.append(t)
    return out


class CSVReader(DataReader):
    """CSV file reader (≙ CSVReaders / CSVAutoReaders).

    ``schema``: optional name → FeatureType mapping; inferred if absent.
    """

    def __init__(self, path: str, headers: Optional[Sequence[str]] = None,
                 schema: Optional[Dict[str, Type[FeatureType]]] = None,
                 key_field: Optional[str] = None, has_header: Optional[bool] = None):
        raw = read_csv_records(path, headers=headers, has_header=has_header)
        self.schema = dict(schema) if schema else infer_schema_from_records(raw)
        records = _typed_records(raw, self.schema)
        key_fn = ((lambda r: r.get(key_field)) if key_field
                  else (lambda r: id(r)))
        super().__init__(records=records, key_fn=key_fn)
        self.path = path
