"""Reader factory (≙ DataReaders object, readers/DataReaders.scala:44)."""

from __future__ import annotations

from .base import (AggregateParams, AggregateReader, ConditionalParams,
                   ConditionalReader, DataReader)
from .csv import CSVReader


class DataReaders:
    class Simple:
        @staticmethod
        def csv(path: str, **kw) -> CSVReader:
            return CSVReader(path, **kw)

        @staticmethod
        def parquet(path: str, **kw):
            from .parquet import ParquetReader
            return ParquetReader(path, **kw)

        @staticmethod
        def avro(path: str, **kw):
            from .avro import AvroReader
            return AvroReader(path, **kw)

        @staticmethod
        def custom(records=None, read_fn=None, key_fn=None) -> DataReader:
            return DataReader(records=records, read_fn=read_fn, key_fn=key_fn)

    class Aggregate:
        @staticmethod
        def custom(records=None, read_fn=None, key_fn=None,
                   cutoff_time_fn=None) -> AggregateReader:
            return AggregateReader(
                records=records, read_fn=read_fn, key_fn=key_fn,
                aggregate_params=AggregateParams(cutoff_time_fn=cutoff_time_fn))

    class Conditional:
        @staticmethod
        def custom(records=None, read_fn=None, key_fn=None,
                   params: ConditionalParams = None) -> ConditionalReader:
            return ConditionalReader(records=records, read_fn=read_fn,
                                     key_fn=key_fn, conditional_params=params)
