from .base import (AggregateParams, AggregateReader, ConditionalParams,
                   ConditionalReader, DataReader, JoinedReader, Reader)
from .csv import CSVReader, infer_schema_from_records, read_csv_records
from .factory import DataReaders
from .streaming import StreamingReader, StreamingReaders

__all__ = ["Reader", "DataReader", "AggregateReader", "ConditionalReader",
           "JoinedReader", "AggregateParams", "ConditionalParams",
           "CSVReader", "DataReaders", "infer_schema_from_records",
           "read_csv_records", "StreamingReader", "StreamingReaders"]
