from .avro import AvroReader, read_avro_records, write_avro
from .base import (AggregateParams, AggregateReader, ConditionalParams,
                   ConditionalReader, DataReader, JoinedReader, Reader)
from .csv import CSVReader, infer_schema_from_records, read_csv_records
from .factory import DataReaders
from .parquet import ParquetReader, read_parquet_records
from .streaming import StreamingReader, StreamingReaders

__all__ = ["Reader", "DataReader", "AggregateReader", "ConditionalReader",
           "JoinedReader", "AggregateParams", "ConditionalParams",
           "CSVReader", "ParquetReader", "AvroReader", "DataReaders",
           "infer_schema_from_records", "read_csv_records",
           "read_parquet_records", "read_avro_records", "write_avro",
           "StreamingReader", "StreamingReaders"]
