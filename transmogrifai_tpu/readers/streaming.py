"""Streaming readers — micro-batch scoring input (reference:
readers/src/main/scala/com/salesforce/op/readers/StreamingReaders.scala and
the DStream loop in OpWorkflowRunner.scala:225-263).

``stream()`` yields raw ``ColumnBatch``es; the runner feeds each to the
compiled score function (SURVEY §2.6 P6: host loop + async device dispatch
replaces DStream micro-batches).

Malformed records share the unified skip-and-dead-letter contract
(quality.py): each micro-batch assembles through ``Reader.generate_batch``,
so when the streaming runner installs an ambient ``QualityConfig`` a poison
record quarantines with a typed violation instead of dead-lettering its
whole micro-batch.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence

from ..columns import ColumnBatch
from ..features import Feature
from .base import DataReader


class StreamingReader(DataReader):
    """Wraps an iterator of record micro-batches (lists of dicts)."""

    def __init__(self, batches: Optional[Iterable[List[Dict[str, Any]]]] = None,
                 batch_fn: Optional[Callable[[], Iterable[List[Dict[str, Any]]]]] = None,
                 key_fn=None, raw_features: Sequence[Feature] = ()):
        if batches is None and batch_fn is None:
            # fail at construction, not with a TypeError mid-stream
            raise ValueError(
                "StreamingReader needs a batch source: pass `batches` (an "
                "iterable of record micro-batches) or `batch_fn` (a callable "
                "returning one)")
        super().__init__(records=None, read_fn=lambda: [], key_fn=key_fn)
        self._batches = batches
        self._batch_fn = batch_fn
        self.raw_features = list(raw_features)

    def set_raw_features(self, feats: Sequence[Feature]) -> "StreamingReader":
        self.raw_features = list(feats)
        return self

    def stream(self) -> Iterator[ColumnBatch]:
        source = self._batches if self._batches is not None else self._batch_fn()
        for records in source:
            reader = DataReader(records=list(records), key_fn=self.key_fn)
            yield reader.generate_batch(self.raw_features)


class StreamingReaders:
    """≙ StreamingReaders factory."""

    @staticmethod
    def custom(batches=None, batch_fn=None, key_fn=None) -> StreamingReader:
        return StreamingReader(batches=batches, batch_fn=batch_fn, key_fn=key_fn)
