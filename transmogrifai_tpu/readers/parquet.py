"""Parquet ingestion (reference: readers/src/main/scala/com/salesforce/op/
readers/ParquetProductReader.scala).

Columns load via pyarrow straight into numpy/host columns; the arrow schema
maps to feature kinds directly (no value-sniffing needed, unlike CSV)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Type

from ..types import (Binary, Date, DateTime, FeatureType, Geolocation,
                     Integral, MultiPickList, Real, Text, TextList)
from .base import DataReader


def arrow_type_to_kind(t) -> Type[FeatureType]:
    """Arrow dtype → feature kind (≙ FeatureSparkTypes schema mapping)."""
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return Binary
    if pa.types.is_integer(t):
        return Integral
    if pa.types.is_floating(t) or pa.types.is_decimal(t):
        return Real
    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        return DateTime
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return Text
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        vt = t.value_type
        if pa.types.is_string(vt) or pa.types.is_large_string(vt):
            return TextList
        if pa.types.is_floating(vt) or pa.types.is_integer(vt):
            # numeric lists are dense vectors (≙ Spark ml Vector → OPVector);
            # Geolocation is NOT inferred — pass it explicitly via `schema`
            from ..types import OPVector
            return OPVector
        return TextList
    return Text


def _to_epoch_ms(v) -> int:
    """datetime/date → epoch millis.  Naive datetimes are treated as UTC
    (parquet stores UTC instants; ``datetime.timestamp()`` would reinterpret
    them in the host's local timezone)."""
    import calendar
    import datetime

    if isinstance(v, datetime.datetime):
        if v.tzinfo is None:
            return int(calendar.timegm(v.timetuple()) * 1000
                       + v.microsecond // 1000)
        return int(v.timestamp() * 1000)
    if isinstance(v, datetime.date):
        return int(calendar.timegm(v.timetuple()) * 1000)
    return int(v)


def read_parquet_records(path: str) -> List[Dict[str, Any]]:
    import pyarrow.parquet as pq

    table = pq.read_table(path)
    return table.to_pylist()


def infer_schema_from_parquet(path: str) -> Dict[str, Type[FeatureType]]:
    import pyarrow.parquet as pq

    schema = pq.read_schema(path)
    return {name: arrow_type_to_kind(schema.field(name).type)
            for name in schema.names}


class ParquetReader(DataReader):
    """Parquet file reader (≙ ParquetProductReader).

    ``schema``: optional name → FeatureType override; derived from the arrow
    schema when absent."""

    def __init__(self, path: str,
                 schema: Optional[Dict[str, Type[FeatureType]]] = None,
                 key_field: Optional[str] = None):
        records = read_parquet_records(path)
        self.schema = dict(schema) if schema else infer_schema_from_parquet(path)
        # timestamps/dates → epoch millis (the Date/DateTime value
        # convention).  A value that cannot convert nulls out with a typed
        # violation instead of raising mid-file — the unified malformed-row
        # contract (quality.py; CSV has always skipped-and-recorded)
        for name, kind in self.schema.items():
            if issubclass(kind, (Date, DateTime)):
                for r in records:
                    v = r.get(name)
                    if v is not None and not isinstance(v, (int, float)):
                        try:
                            r[name] = _to_epoch_ms(v)
                        except Exception as e:  # noqa: BLE001 — bad cell
                            from ..quality import TYPE_MISMATCH
                            from ..resilience import record_failure
                            from ..telemetry import REGISTRY
                            r[name] = None
                            REGISTRY.counter(
                                "quality.malformed_rows_total").inc()
                            REGISTRY.counter(
                                f"quality.violations_{TYPE_MISMATCH}"
                                "_total").inc()
                            REGISTRY.counter(
                                "quality.violations_total").inc()
                            record_failure(
                                "reader", "quarantined", e,
                                point="reader.quality", file=path,
                                field=name, violation=TYPE_MISMATCH)
        key_fn = ((lambda r: r.get(key_field)) if key_field
                  else (lambda r: id(r)))
        super().__init__(records=records, key_fn=key_fn)
        self.path = path
