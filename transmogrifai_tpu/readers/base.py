"""Data readers — the TPU-native re-design of the readers module (reference:
readers/src/main/scala/com/salesforce/op/readers/Reader.scala:96,
DataReader.scala:173,252,288, JoinedDataReader.scala:218).

A reader yields records (dicts); ``generate_batch`` applies every raw feature's
``extract_fn`` to produce the raw ``ColumnBatch`` (≙ generateDataFrame).
Aggregate/conditional readers implement event-time aggregation with monoid
aggregators and cutoff semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnBatch, column_from_values
from ..features import Feature
from ..stages.generator import FeatureGeneratorStage


def _generator_of(feature: Feature) -> FeatureGeneratorStage:
    st = feature.origin_stage
    if not isinstance(st, FeatureGeneratorStage):
        raise ValueError(f"{feature.name} is not a raw feature")
    return st


class Reader:
    """Base reader (≙ Reader.scala:96)."""

    def __init__(self, key_fn: Optional[Callable[[Dict], Any]] = None):
        self.key_fn = key_fn or (lambda r: r.get("key"))

    def read(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    @staticmethod
    def _screen(records: List[Dict[str, Any]],
                raw_features: Sequence[Feature]) -> List[Dict[str, Any]]:
        """Per-record quarantine at ingestion when a quality config is
        ambient (``quality.use_quality`` — workflow.train and the streaming
        runner install one): malformed records are excluded with typed
        violations instead of crashing column assembly mid-batch.  With no
        ambient config this is the identity — historical behavior."""
        from ..quality import active_quality, screen_records
        if active_quality() is None:
            return records
        return screen_records(records, raw_features, stage="reader")

    def generate_batch(self, raw_features: Sequence[Feature]) -> ColumnBatch:
        records = self._screen(self.read(), raw_features)
        cols: Dict[str, Column] = {}
        for f in raw_features:
            cols[f.name] = _generator_of(f).extract_column(records)
        cols["key"] = column_from_values(
            __import__("transmogrifai_tpu.types", fromlist=["Text"]).Text,
            [str(self.key_fn(r)) for r in records])
        return ColumnBatch(cols, len(records))

    # joins (≙ JoinedDataReader)
    def inner_join(self, other: "Reader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, "inner", **kw)

    def left_outer_join(self, other: "Reader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, "left", **kw)

    def outer_join(self, other: "Reader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, "outer", **kw)


class DataReader(Reader):
    """Simple reader over in-memory records or a record-producing function
    (≙ DataReader.generateDataFrame, DataReader.scala:173)."""

    def __init__(self, records: Optional[Iterable[Dict[str, Any]]] = None,
                 read_fn: Optional[Callable[[], Iterable[Dict[str, Any]]]] = None,
                 key_fn: Optional[Callable[[Dict], Any]] = None):
        super().__init__(key_fn)
        self._records = list(records) if records is not None else None
        self._read_fn = read_fn

    def read(self) -> List[Dict[str, Any]]:
        if self._records is not None:
            return self._records
        return list(self._read_fn())


@dataclass
class AggregateParams:
    """≙ AggregateParams (DataReader.scala:279).

    Either a typed ``cutoff_time`` (CutOffTime + ``time_fn`` event timestamps,
    with optional trailing/leading windows — the reference's
    TimeBasedAggregator semantics) or a bare boolean ``cutoff_time_fn``
    (event → is-before-cutoff)."""
    cutoff_time_fn: Optional[Callable[[Dict], bool]] = None
    cutoff_time: Optional[Any] = None            # aggregators.CutOffTime
    time_fn: Callable[[Dict], int] = lambda r: int(r.get("timestamp", 0))
    predictor_window_ms: Optional[int] = None
    response_window_ms: Optional[int] = None


class AggregateReader(DataReader):
    """Event-time aggregation (≙ AggregateDataReader, DataReader.scala:252):
    group records by key; predictors aggregate events before the cutoff
    (within the trailing predictor window), responses after (within the
    leading response window)."""

    def __init__(self, records=None, read_fn=None, key_fn=None,
                 aggregate_params: Optional[AggregateParams] = None):
        super().__init__(records, read_fn, key_fn)
        self.params = aggregate_params or AggregateParams()

    def generate_batch(self, raw_features: Sequence[Feature]) -> ColumnBatch:
        from ..aggregators import Event, split_events_at_cutoff

        records = self._screen(self.read(), raw_features)
        p = self.params
        grouped: Dict[Any, List[Dict]] = {}
        for r in records:
            grouped.setdefault(self.key_fn(r), []).append(r)

        if p.cutoff_time is not None:
            cutoff_ms = p.cutoff_time.timestamp_ms()
            # Event lists built ONCE per key; per-feature windows re-slice them
            split: Dict[Any, Any] = {}
            for k, events in grouped.items():
                evs = [Event(p.time_fn(r), r) for r in events]
                split[k] = split_events_at_cutoff(
                    evs, cutoff_ms, p.predictor_window_ms,
                    p.response_window_ms)
            cols: Dict[str, Column] = {}
            for f in raw_features:
                gen = _generator_of(f)
                # a per-feature window narrows this feature's slice further:
                # trailing for predictors, leading for responses
                # (≙ FeatureBuilder .window / FeatureAggregator timeWindow)
                win = gen.get("aggregate_window_ms")
                vals = []
                for k in grouped:
                    pred_evs, resp_evs = split[k]
                    evs = resp_evs if f.is_response else pred_evs
                    if win is not None and cutoff_ms is not None:
                        if f.is_response:
                            _, evs = split_events_at_cutoff(
                                evs, cutoff_ms, None, int(win))
                        else:
                            evs, _ = split_events_at_cutoff(
                                evs, cutoff_ms, int(win), None)
                    vals.append(gen.aggregate_records([e.value for e in evs]))
                cols[f.name] = column_from_values(f.kind, vals)
        else:
            cols = {}
            for f in raw_features:
                gen = _generator_of(f)
                cols[f.name] = gen.extract_aggregated(
                    grouped, cutoff_fn=p.cutoff_time_fn,
                    is_response=f.is_response)
        from ..types import Text
        cols["key"] = column_from_values(Text, [str(k) for k in grouped])
        return ColumnBatch(cols, len(grouped))


@dataclass
class ConditionalParams:
    """≙ ConditionalParams (DataReader.scala:351)."""
    target_condition: Callable[[Dict], bool] = lambda r: True
    response_window_ms: Optional[int] = None
    predictor_window_ms: Optional[int] = None
    time_fn: Callable[[Dict], int] = lambda r: int(r.get("timestamp", 0))
    drop_if_target_condition_not_met: bool = True


class ConditionalReader(DataReader):
    """Aggregation relative to per-key first occurrence of a target condition
    (≙ ConditionalDataReader, DataReader.scala:288): predictors aggregate
    events before the condition time (within predictor window), responses
    after (within response window)."""

    def __init__(self, records=None, read_fn=None, key_fn=None,
                 conditional_params: Optional[ConditionalParams] = None):
        super().__init__(records, read_fn, key_fn)
        self.params = conditional_params or ConditionalParams()

    def generate_batch(self, raw_features: Sequence[Feature]) -> ColumnBatch:
        records = self.read()
        p = self.params
        grouped: Dict[Any, List[Dict]] = {}
        for r in records:
            grouped.setdefault(self.key_fn(r), []).append(r)
        keys, rows = [], {}
        for k, events in grouped.items():
            cond_times = [p.time_fn(e) for e in events if p.target_condition(e)]
            if not cond_times:
                if p.drop_if_target_condition_not_met:
                    continue
                cutoff = max(p.time_fn(e) for e in events) + 1
            else:
                cutoff = min(cond_times)
            pred_events, resp_events = [], []
            for e in events:
                t = p.time_fn(e)
                if t < cutoff:
                    if p.predictor_window_ms is None or t >= cutoff - p.predictor_window_ms:
                        pred_events.append(e)
                else:
                    if p.response_window_ms is None or t < cutoff + p.response_window_ms:
                        resp_events.append(e)
            keys.append(k)
            rows[k] = (cutoff, pred_events, resp_events)
        cols: Dict[str, Column] = {}
        for f in raw_features:
            gen = _generator_of(f)
            # per-feature .window() narrows this feature's slice around the
            # per-key condition time (trailing for predictors, leading for
            # responses — ≙ FeatureBuilder.window in ConditionalAggregation)
            win = gen.get("aggregate_window_ms")
            vals = []
            for k in keys:
                cutoff, pred_events, resp_events = rows[k]
                evs = resp_events if f.is_response else pred_events
                if win is not None:
                    if f.is_response:
                        evs = [e for e in evs
                               if p.time_fn(e) < cutoff + int(win)]
                    else:
                        evs = [e for e in evs
                               if p.time_fn(e) >= cutoff - int(win)]
                vals.append(gen.aggregate_records(evs))
            cols[f.name] = column_from_values(f.kind, vals)
        from ..types import Text
        cols["key"] = column_from_values(Text, [str(k) for k in keys])
        return ColumnBatch(cols, len(keys))


class JoinedReader(Reader):
    """Typed key join of two readers (≙ JoinedDataReader.scala:218).

    Two modes, mirroring the reference:

    * **record join** (default): ``read()``/``generate_batch`` emit merged
      record dicts (cross product per key for multi-matches) — enrichment
      joins.
    * **feature join** (``left_features=`` given): each side's reader
      generates (and aggregates) ITS OWN features, then the feature COLUMNS
      join per key — the reference's join-then-aggregate flow
      (JoinedDataReader + post-join aggregation of time-based features).
      ``left_features`` names the features produced from the left reader's
      records; everything else routes to the right reader.
    """

    def __init__(self, left: Reader, right: Reader, how: str = "inner",
                 left_key: Optional[Callable[[Dict], Any]] = None,
                 right_key: Optional[Callable[[Dict], Any]] = None,
                 left_features: Optional[Sequence[str]] = None):
        super().__init__()
        if how not in ("inner", "left", "outer"):
            raise ValueError(f"JoinedReader: how={how!r} must be one of "
                             "'inner', 'left', 'outer'")
        self.left, self.right, self.how = left, right, how
        self.left_key = left_key or left.key_fn
        self.right_key = right_key or right.key_fn
        self.left_features = (set(left_features)
                              if left_features is not None else None)
        if self.left_features is not None and (left_key or right_key):
            raise ValueError(
                "JoinedReader: feature-join mode (left_features=) joins on "
                "each side's own key column — set key_fn on the left/right "
                "readers instead of left_key/right_key")

    def read(self) -> List[Dict[str, Any]]:
        lrecs, rrecs = self.left.read(), self.right.read()
        rmap: Dict[Any, List[Dict]] = {}
        for r in rrecs:
            rmap.setdefault(self.right_key(r), []).append(r)
        out: List[Dict] = []
        seen_right = set()
        for l in lrecs:
            k = self.left_key(l)
            matches = rmap.get(k, [])
            if matches:
                seen_right.add(k)
                for m in matches:
                    merged = dict(m)
                    merged.update(l)
                    merged["key"] = k
                    out.append(merged)
            elif self.how in ("left", "outer"):
                rec = dict(l)
                rec["key"] = k
                out.append(rec)
        if self.how == "outer":
            for k, ms in rmap.items():
                if k not in seen_right:
                    for m in ms:
                        rec = dict(m)
                        rec["key"] = k
                        out.append(rec)
        return out

    def generate_batch(self, raw_features: Sequence[Feature]) -> ColumnBatch:
        from ..types import Text

        if self.left_features is None:
            records = self.read()
            cols: Dict[str, Column] = {}
            for f in raw_features:
                cols[f.name] = _generator_of(f).extract_column(records)
            cols["key"] = column_from_values(
                Text, [str(r.get("key")) for r in records])
            return ColumnBatch(cols, len(records))

        # feature join: each side aggregates its own features, columns merge
        # per key (missing side → null, the feature's empty-aggregation value)
        unknown = self.left_features - {f.name for f in raw_features}
        if unknown:
            raise ValueError(
                f"JoinedReader: left_features {sorted(unknown)} do not match "
                f"any raw feature; available: "
                f"{sorted(f.name for f in raw_features)}")
        lfeats = [f for f in raw_features if f.name in self.left_features]
        rfeats = [f for f in raw_features if f.name not in self.left_features]
        lb = self.left.generate_batch(lfeats)
        rb = self.right.generate_batch(rfeats)
        lkeys = [str(k) for k in lb["key"].values]
        rkeys = [str(k) for k in rb["key"].values]
        for side, ks in (("left", lkeys), ("right", rkeys)):
            if len(set(ks)) != len(ks):
                raise ValueError(
                    f"JoinedReader: the {side} reader emitted duplicate keys "
                    "— feature-join mode needs one aggregated row per key "
                    "(use an AggregateReader or a unique key_fn)")
        lpos = {k: i for i, k in enumerate(lkeys)}
        rpos = {k: i for i, k in enumerate(rkeys)}
        if self.how == "inner":
            keys = [k for k in lkeys if k in rpos]
        elif self.how == "left":
            keys = list(lkeys)
        else:  # outer
            keys = list(lkeys) + [k for k in rkeys if k not in lpos]

        from ..stages.generator import non_nullable_empty_value
        cols = {}
        for feats, batch, pos in ((lfeats, lb, lpos), (rfeats, rb, rpos)):
            for f in feats:
                col = batch[f.name]
                vals = [col.row_value(pos[k]).value if k in pos else None
                        for k in keys]
                if f.kind.non_nullable:
                    zero = non_nullable_empty_value(f.kind)
                    vals = [zero if v is None else v for v in vals]
                cols[f.name] = column_from_values(f.kind, vals)
        cols["key"] = column_from_values(Text, keys)
        return ColumnBatch(cols, len(keys))
