"""Avro ingestion — a self-contained Object Container File codec (reference:
readers/src/main/scala/com/salesforce/op/readers/AvroReaders.scala; the
reference leans on the avro JVM library, this image has none, so the binary
format is implemented directly: header Obj\\x01 + metadata map + sync marker,
blocks of zigzag-varint-framed records, null/deflate codecs).

Covers the Avro types the reference's schemas use: null, boolean, int, long,
float, double, bytes, string, record, enum, array, map, union, fixed."""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple, Type

from ..types import (Binary, DateTime, FeatureType, Integral, Real, Text,
                     TextList)
from .base import DataReader

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# binary primitives
# ---------------------------------------------------------------------------

def _read_varint(buf: io.BytesIO) -> int:
    shift = 0
    result = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return result


def _read_long(buf: io.BytesIO) -> int:
    n = _read_varint(buf)
    return (n >> 1) ^ -(n & 1)  # zigzag


def _write_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag (python ints: arithmetic shift ok)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            break


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    return buf.read(n)


# ---------------------------------------------------------------------------
# schema-directed decode / encode
# ---------------------------------------------------------------------------

def _decode(schema, buf: io.BytesIO) -> Any:
    if isinstance(schema, list):  # union
        idx = _read_long(buf)
        return _decode(schema[idx], buf)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: _decode(f["type"], buf)
                    for f in schema["fields"]}
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)  # block byte size — skipable hint
                    n = -n
                out.extend(_decode(schema["items"], buf) for _ in range(n))
            return out
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    k = _read_bytes(buf).decode("utf-8")
                    out[k] = _decode(schema["values"], buf)
            return out
        if t == "fixed":
            return buf.read(schema["size"])
        return _decode(t, buf)  # e.g. {"type": "string"}
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"unsupported avro type {schema!r}")


def _encode(schema, v: Any, out: io.BytesIO) -> None:
    if isinstance(schema, list):  # union — pick the first matching branch
        for i, branch in enumerate(schema):
            if _matches(branch, v):
                _write_long(out, i)
                _encode(branch, v, out)
                return
        raise ValueError(f"no union branch of {schema} matches {v!r}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                fv = (v or {}).get(f["name"])
                if fv is None and not _accepts_null(f["type"]):
                    raise ValueError(
                        f"record field {f['name']!r} is missing/None but its "
                        f"schema {f['type']!r} is not nullable")
                _encode(f["type"], fv, out)
            return
        if t == "enum":
            _write_long(out, schema["symbols"].index(v))
            return
        if t == "array":
            if v:
                _write_long(out, len(v))
                for item in v:
                    _encode(schema["items"], item, out)
            _write_long(out, 0)
            return
        if t == "map":
            if v:
                _write_long(out, len(v))
                for k, mv in v.items():
                    kb = str(k).encode("utf-8")
                    _write_long(out, len(kb))
                    out.write(kb)
                    _encode(schema["values"], mv, out)
            _write_long(out, 0)
            return
        if t == "fixed":
            out.write(v)
            return
        _encode(t, v, out)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if v else b"\x00")
        return
    if schema in ("int", "long"):
        _write_long(out, int(v))
        return
    if schema == "float":
        out.write(struct.pack("<f", float(v)))
        return
    if schema == "double":
        out.write(struct.pack("<d", float(v)))
        return
    if schema == "bytes":
        _write_long(out, len(v))
        out.write(v)
        return
    if schema == "string":
        b = str(v).encode("utf-8")
        _write_long(out, len(b))
        out.write(b)
        return
    raise ValueError(f"unsupported avro type {schema!r}")


def _accepts_null(schema) -> bool:
    if schema == "null":
        return True
    if isinstance(schema, list):
        return any(_accepts_null(b) for b in schema)
    return False


def _matches(schema, v) -> bool:
    if schema == "null":
        return v is None
    if v is None:
        return False
    if schema == "boolean":
        return isinstance(v, bool)
    if schema in ("int", "long"):
        return isinstance(v, int) and not isinstance(v, bool)
    if schema in ("float", "double"):
        return isinstance(v, (int, float)) and not isinstance(v, bool)
    if schema == "string":
        return isinstance(v, str)
    if schema == "bytes":
        return isinstance(v, bytes)
    if isinstance(schema, dict):
        t = schema["type"]
        return ((t == "array" and isinstance(v, list))
                or (t == "map" and isinstance(v, dict))
                or (t == "record" and isinstance(v, dict))
                or (t == "enum" and isinstance(v, str))
                or (t == "fixed" and isinstance(v, bytes)))
    return True


# ---------------------------------------------------------------------------
# container file read / write
# ---------------------------------------------------------------------------

def _skip_malformed(path: str, what: str, cause) -> None:
    """Dead-letter accounting for a corrupt Avro region: the typed
    violation (``NonCoercibleValue`` — bytes that do not decode) lands in
    the FailureLog and the quality counters, and reading continues — the
    same skip-and-record contract the CSV reader has always had."""
    from ..quality import NON_COERCIBLE_VALUE
    from ..resilience import record_failure
    from ..telemetry import REGISTRY
    REGISTRY.counter("quality.malformed_rows_total").inc()
    REGISTRY.counter(
        f"quality.violations_{NON_COERCIBLE_VALUE}_total").inc()
    REGISTRY.counter("quality.violations_total").inc()
    record_failure("reader", "quarantined", cause, point="reader.quality",
                   file=path, violation=NON_COERCIBLE_VALUE, detail=what)


def read_avro_records(path: str, skip_malformed: bool = False
                      ) -> Tuple[List[Dict[str, Any]], Any]:
    """→ (records, schema json) from an Avro Object Container File.

    With ``skip_malformed`` a block that fails to decompress or decode is
    skipped with a recorded typed violation (decoding cannot resync inside
    a block, so the block is the skip unit) and a bad sync marker stops the
    read at the last good block — the malformed-row contract the CSV
    reader has (``readers/csv.py``), instead of raising mid-file."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = _read_long(buf)
        if n == 0:
            break
        if n < 0:
            _read_long(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode("utf-8")
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = buf.read(16)
    records: List[Any] = []
    while buf.tell() < len(data):
        try:
            count = _read_long(buf)
        except EOFError:
            break
        size = _read_long(buf)
        block = buf.read(size)
        try:
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            elif codec != "null":
                raise ValueError(f"unsupported avro codec {codec!r}")
            bbuf = io.BytesIO(block)
            decoded = [_decode(schema, bbuf) for _ in range(count)]
        except Exception as e:  # noqa: BLE001 — corrupt block
            if not skip_malformed:
                raise
            _skip_malformed(path, f"undecodable block of {count} "
                                  "record(s) skipped", e)
            decoded = []
        if buf.read(16) != sync:
            if not skip_malformed:
                raise ValueError(f"{path}: bad sync marker (corrupt file)")
            # the framing itself is untrustworthy past this point: keep
            # everything decoded so far, drop this block, stop reading
            _skip_malformed(path, "bad sync marker; file truncated at the "
                                  "last good block",
                            ValueError("bad sync marker"))
            break
        records.extend(decoded)
    return records, schema


def write_avro(path: str, records: List[Dict[str, Any]], schema,
               codec: str = "null") -> None:
    """Write an Avro Object Container File (null/deflate codecs)."""
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    _write_long(out, len(meta))
    for k, v in meta.items():
        kb = k.encode("utf-8")
        _write_long(out, len(kb))
        out.write(kb)
        _write_long(out, len(v))
        out.write(v)
    _write_long(out, 0)
    out.write(sync)
    block = io.BytesIO()
    for r in records:
        _encode(schema, r, block)
    payload = block.getvalue()
    if codec == "deflate":
        c = zlib.compressobj(9, zlib.DEFLATED, -15)
        payload = c.compress(payload) + c.flush()
    elif codec != "null":
        raise ValueError(f"unsupported avro codec {codec!r}")
    _write_long(out, len(records))
    _write_long(out, len(payload))
    out.write(payload)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())


# ---------------------------------------------------------------------------
# schema mapping + reader
# ---------------------------------------------------------------------------

def avro_type_to_kind(t) -> Type[FeatureType]:
    if isinstance(t, list):  # union — first non-null branch decides
        branches = [b for b in t if b != "null"]
        return avro_type_to_kind(branches[0]) if branches else Text
    if isinstance(t, dict):
        tt = t["type"]
        if tt == "array":
            return TextList
        if tt in ("enum", "map", "fixed", "record"):
            return Text
        if t.get("logicalType") in ("timestamp-millis", "timestamp-micros"):
            return DateTime
        return avro_type_to_kind(tt)
    if t == "boolean":
        return Binary
    if t in ("int", "long"):
        return Integral
    if t in ("float", "double"):
        return Real
    return Text


def infer_schema_from_avro(avro_schema) -> Dict[str, Type[FeatureType]]:
    return {f["name"]: avro_type_to_kind(f["type"])
            for f in avro_schema.get("fields", [])}


class AvroReader(DataReader):
    """Avro container file reader (≙ AvroReaders.scala)."""

    def __init__(self, path: str,
                 schema: Optional[Dict[str, Type[FeatureType]]] = None,
                 key_field: Optional[str] = None,
                 skip_malformed: bool = True):
        # skip_malformed unifies the malformed-row contract across readers
        # (quality.py): corrupt blocks dead-letter with a typed violation
        # instead of raising mid-file, as CSV has always done
        records, avro_schema = read_avro_records(
            path, skip_malformed=skip_malformed)
        self.avro_schema = avro_schema
        self.schema = (dict(schema) if schema
                       else infer_schema_from_avro(avro_schema))
        key_fn = ((lambda r: r.get(key_field)) if key_field
                  else (lambda r: id(r)))
        super().__init__(records=records, key_fn=key_fn)
        self.path = path
