"""testkit — typed random data generators with controlled emptiness
(reference: testkit/src/main/scala/com/salesforce/op/testkit/: RandomReal,
RandomText, RandomBinary, RandomIntegral, RandomList, RandomMap, RandomSet,
RandomVector, RandomStream, DataSources).

Each generator is an infinite iterator of typed values; ``limit(n)`` takes n,
``with_probability_of_empty`` injects Nones — the same API shape the
reference's test suites rely on.
"""

from __future__ import annotations

import itertools
import string
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np


class RandomData:
    """Base infinite generator."""

    def __init__(self, sample: Callable[[np.random.Generator], Any],
                 seed: int = 42):
        self._sample = sample
        self._rng = np.random.default_rng(seed)
        self._p_empty = 0.0

    def with_probability_of_empty(self, p: float) -> "RandomData":
        self._p_empty = float(p)
        return self

    def reset(self, seed: int) -> "RandomData":
        self._rng = np.random.default_rng(seed)
        return self

    def __iter__(self) -> Iterator[Any]:
        while True:
            if self._p_empty and self._rng.random() < self._p_empty:
                yield None
            else:
                yield self._sample(self._rng)

    def limit(self, n: int) -> List[Any]:
        return list(itertools.islice(iter(self), n))

    def streams(self, n_streams: int, n: int) -> List[List[Any]]:
        return [self.limit(n) for _ in range(n_streams)]


class RandomReal(RandomData):
    """≙ RandomReal: normal/uniform/poisson/exponential/gamma/log-normal."""

    @staticmethod
    def normal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal(lambda r: float(r.normal(mean, sigma)), seed)

    @staticmethod
    def uniform(low: float = 0.0, high: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal(lambda r: float(r.uniform(low, high)), seed)

    @staticmethod
    def poisson(lam: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal(lambda r: float(r.poisson(lam)), seed)

    @staticmethod
    def exponential(scale: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal(lambda r: float(r.exponential(scale)), seed)

    @staticmethod
    def gamma(shape: float = 2.0, scale: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal(lambda r: float(r.gamma(shape, scale)), seed)

    @staticmethod
    def lognormal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal(lambda r: float(r.lognormal(mean, sigma)), seed)


class RandomIntegral(RandomData):
    @staticmethod
    def integers(low: int = 0, high: int = 100, seed: int = 42) -> "RandomIntegral":
        return RandomIntegral(lambda r: int(r.integers(low, high)), seed)

    @staticmethod
    def dates(start_ms: int = 1400000000000, step_ms: int = 86400000,
              seed: int = 42) -> "RandomIntegral":
        return RandomIntegral(
            lambda r: int(start_ms + r.integers(0, 1000) * step_ms), seed)


class RandomBinary(RandomData):
    def __init__(self, p_true: float = 0.5, seed: int = 42):
        super().__init__(lambda r: bool(r.random() < p_true), seed)


class RandomText(RandomData):
    """≙ RandomText: random strings / picklists / emails / urls / countries."""

    _WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
              "theta", "iota", "kappa", "lambda", "mu"]

    @staticmethod
    def strings(min_len: int = 3, max_len: int = 10, seed: int = 42) -> "RandomText":
        chars = string.ascii_lowercase

        def sample(r):
            n = int(r.integers(min_len, max_len + 1))
            return "".join(r.choice(list(chars)) for _ in range(n))

        return RandomText(sample, seed)

    @staticmethod
    def words(n_words: int = 3, seed: int = 42) -> "RandomText":
        def sample(r):
            return " ".join(r.choice(RandomText._WORDS)
                            for _ in range(n_words))
        return RandomText(sample, seed)

    @staticmethod
    def picklists(domain: Sequence[str], seed: int = 42) -> "RandomText":
        domain = list(domain)
        return RandomText(lambda r: str(r.choice(domain)), seed)

    @staticmethod
    def emails(domain: str = "example.com", seed: int = 42) -> "RandomText":
        base = RandomText.strings(4, 8, seed)
        return RandomText(lambda r: base._sample(r) + "@" + domain, seed)

    @staticmethod
    def urls(seed: int = 42) -> "RandomText":
        base = RandomText.strings(4, 8, seed)
        return RandomText(lambda r: f"https://{base._sample(r)}.example.com", seed)

    @staticmethod
    def countries(seed: int = 42) -> "RandomText":
        return RandomText.picklists(
            ["USA", "France", "Germany", "Japan", "Brazil", "India"], seed)

    @staticmethod
    def phones(seed: int = 42) -> "RandomText":
        return RandomText(
            lambda r: "+1" + "".join(str(r.integers(0, 10)) for _ in range(10)),
            seed)


class RandomList(RandomData):
    @staticmethod
    def of(element: RandomData, min_len: int = 0, max_len: int = 5,
           seed: int = 42) -> "RandomList":
        def sample(r):
            n = int(r.integers(min_len, max_len + 1))
            return [element._sample(r) for _ in range(n)]
        return RandomList(sample, seed)


class RandomSet(RandomData):
    @staticmethod
    def of(domain: Sequence[str], min_len: int = 0, max_len: int = 3,
           seed: int = 42) -> "RandomSet":
        domain = list(domain)

        def sample(r):
            n = int(r.integers(min_len, min(max_len, len(domain)) + 1))
            return set(r.choice(domain, size=n, replace=False).tolist())
        return RandomSet(sample, seed)


class RandomMap(RandomData):
    @staticmethod
    def of(value_gen: RandomData, keys: Sequence[str], seed: int = 42) -> "RandomMap":
        keys = list(keys)

        def sample(r):
            return {k: value_gen._sample(r) for k in keys
                    if r.random() > 0.3}
        return RandomMap(sample, seed)


class RandomVector(RandomData):
    @staticmethod
    def dense(dim: int, seed: int = 42) -> "RandomVector":
        return RandomVector(lambda r: r.normal(size=dim).astype(np.float32).tolist(),
                            seed)


class RandomGeolocation(RandomData):
    def __init__(self, seed: int = 42):
        super().__init__(
            lambda r: [float(r.uniform(-90, 90)), float(r.uniform(-180, 180)),
                       float(r.integers(1, 10))], seed)


def random_records(n: int, generators: dict, seed: int = 42) -> List[dict]:
    """Build n records from a name → RandomData mapping (≙ DataSources)."""
    cols = {name: gen.reset(seed + i).limit(n)
            for i, (name, gen) in enumerate(generators.items())}
    return [{k: cols[k][i] for k in cols} for i in range(n)]
