from .sanity_checker import SanityChecker, SanityCheckerModel, SanityCheckerSummary
from .prediction_deindexer import PredictionDeIndexer

__all__ = ["SanityChecker", "SanityCheckerModel", "SanityCheckerSummary",
           "PredictionDeIndexer"]
