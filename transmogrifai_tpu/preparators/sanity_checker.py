"""SanityChecker — automatic feature validation / leakage detection
(reference: core/src/main/scala/com/salesforce/op/stages/impl/preparators/
SanityChecker.scala:535-640 fitFn, SanityCheckerModel:695,
SanityCheckerMetadata.scala; stats from utils/stats/OpStatistics.scala:71,188,234).

On TPU the whole fit is a handful of fused XLA reductions over the HBM-resident
feature matrix: moments + label correlations are one [D+1]-wide matmul pass,
Cramér's V contingency tables are one-hot outer-product matmuls per categorical
group, and the model is a gather of the kept column indices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, TransformerModel
from ..types import OPVector, RealNN
from ..vector_meta import VectorMeta

DEFAULT_MAX_CORRELATION = 0.95
DEFAULT_MIN_CORRELATION = 0.0
DEFAULT_MIN_VARIANCE = 1e-5
DEFAULT_MAX_CRAMERS_V = 0.95
DEFAULT_MAX_RULE_CONFIDENCE = 1.0
DEFAULT_MIN_REQUIRED_RULE_SUPPORT = 1.0
DEFAULT_SAMPLE_UPPER_LIMIT = 1_000_000
DEFAULT_CORRELATION_TYPE = "pearson"


def _label_corr(Xf: jnp.ndarray, yf: jnp.ndarray) -> jnp.ndarray:
    """Per-column Pearson correlation with the label (over raw values —
    or over average ranks, which makes it Spearman)."""
    ym = jnp.mean(yf)
    yc = yf - ym
    ysd = jnp.sqrt(jnp.sum(yc * yc))
    Xc = Xf - jnp.mean(Xf, axis=0)
    cov = yc @ Xc
    xsd = jnp.sqrt(jnp.sum(Xc * Xc, axis=0))
    return cov / jnp.maximum(xsd * ysd, 1e-12)


@partial(jax.jit, static_argnames=("spearman",))
def _col_stats(X: jnp.ndarray, y: jnp.ndarray, spearman: bool = False):
    """Single fused pass: per-column count/mean/var/min/max + corr with the
    label (≙ Statistics.colStats + computeCorrelationsWithLabel,
    OpStatistics.scala:71).  With ``spearman=True`` the rank transform
    (argsort + tie-averaged positions) happens INSIDE the same program
    (≙ SanityChecker.scala:535-640 Spearman option) — one executable, one
    dispatch, no second stats pass (VERDICT r4 next #6).

    Jitted so the centred intermediates fuse into the reductions instead of
    materializing eagerly (an eager pass holds 2-3 full [N, D] temporaries —
    GBs at transmogrified widths).  ``X`` may arrive in bf16 storage; all
    accumulation is forced to f32."""
    Xf = X.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    mean = jnp.mean(Xf, axis=0)
    var = jnp.var(Xf, axis=0, ddof=1)
    mn = jnp.min(Xf, axis=0)
    mx = jnp.max(Xf, axis=0)
    if spearman:
        corr = _label_corr(_rank_transform(Xf), _rank_transform(yf))
    else:
        corr = _label_corr(Xf, yf)
    return mean, var, mn, mx, corr


@partial(jax.jit, static_argnames=("spearman",))
def _col_stats_with_contingency(X, y, union_idx, y_classes, spearman=False):
    """``_col_stats`` + the categorical contingency contraction in ONE
    program (one executable load, two result pulls) — the per-group
    Cramér's V tables come from a single [C, |union|] matmul over the union
    of indicator columns (≙ SanityChecker.scala:575 categoricalTests).
    The contingency always contracts RAW indicator values; only the label
    correlation switches to ranks under ``spearman``."""
    mean, var, mn, mx, corr = _col_stats(X, y, spearman=spearman)
    yoh = (y[:, None] == y_classes[None, :]).astype(jnp.float32)
    cont = yoh.T @ X[:, union_idx].astype(jnp.float32)
    return jnp.stack([mean, var, mn, mx, corr]), cont


@jax.jit
def _rank_transform(a: jnp.ndarray) -> jnp.ndarray:
    """Average-rank transform per column for Spearman correlation — one
    sort + searchsorted per column, fully on device (ties get the average of
    their positions, matching scipy's 'average' ranking)."""

    def col_ranks(c):
        order = jnp.argsort(c)
        ss = c[order]
        left = jnp.searchsorted(ss, ss, side="left").astype(jnp.float32)
        right = jnp.searchsorted(ss, ss, side="right").astype(jnp.float32)
        avg = 0.5 * (left + right - 1.0)
        return jnp.zeros_like(avg).at[order].set(avg)

    if a.ndim == 1:
        return col_ranks(a)
    return jax.vmap(col_ranks, in_axes=1, out_axes=1)(a)


def cramers_v(contingency: np.ndarray) -> float:
    """Cramér's V (≙ OpStatistics.chiSquaredTest, OpStatistics.scala:188) —
    re-exported from utils.stats, the single implementation."""
    from ..utils.stats import chi_squared_test
    return chi_squared_test(contingency)[2]


@dataclass
class SanityCheckerSummary:
    """≙ SanityCheckerSummary metadata."""

    correlation_type: str = DEFAULT_CORRELATION_TYPE
    names: List[str] = field(default_factory=list)
    correlations_with_label: List[float] = field(default_factory=list)
    variances: List[float] = field(default_factory=list)
    means: List[float] = field(default_factory=list)
    mins: List[float] = field(default_factory=list)
    maxs: List[float] = field(default_factory=list)
    cramers_v_by_group: Dict[str, float] = field(default_factory=dict)
    contingency_stats_by_group: Dict[str, Any] = field(default_factory=dict)
    dropped: List[str] = field(default_factory=list)
    drop_reasons: Dict[str, List[str]] = field(default_factory=dict)
    sample_size: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "correlationType": self.correlation_type,
            "names": self.names,
            "correlationsWithLabel": self.correlations_with_label,
            "variances": self.variances,
            "means": self.means,
            "mins": self.mins,
            "maxs": self.maxs,
            "categoricalStats": {
                "cramersV": self.cramers_v_by_group,
                "contingencyStats": self.contingency_stats_by_group},
            "dropped": self.dropped,
            "dropReasons": self.drop_reasons,
            "sampleSize": self.sample_size,
        }


class SanityCheckerModel(TransformerModel):
    """Keeps the surviving column slice (≙ SanityCheckerModel:695)."""

    in_kinds = (RealNN, OPVector)
    out_kind = OPVector
    allow_label_as_input = True

    def transform(self, batch: ColumnBatch) -> Column:
        vec = batch[self.input_features[1].name]
        idx = np.asarray(self.fitted["indices_to_keep"], dtype=np.int64)
        values = jnp.asarray(vec.values)[:, idx]
        meta = vec.meta.select(idx.tolist(), name=self.output_features[0].name) \
            if vec.meta is not None else None
        return Column(OPVector, values, meta=meta)


class SanityChecker(Estimator):
    """≙ SanityChecker estimator on (label, featureVector)."""

    in_kinds = (RealNN, OPVector)
    out_kind = OPVector
    allow_label_as_input = True

    def __init__(self, max_correlation: float = DEFAULT_MAX_CORRELATION,
                 min_correlation: float = DEFAULT_MIN_CORRELATION,
                 min_variance: float = DEFAULT_MIN_VARIANCE,
                 max_cramers_v: float = DEFAULT_MAX_CRAMERS_V,
                 max_rule_confidence: float = DEFAULT_MAX_RULE_CONFIDENCE,
                 min_required_rule_support: float = DEFAULT_MIN_REQUIRED_RULE_SUPPORT,
                 remove_bad_features: bool = True,
                 correlation_type: str = DEFAULT_CORRELATION_TYPE,
                 check_sample_fraction: float = 1.0,
                 sample_upper_limit: int = DEFAULT_SAMPLE_UPPER_LIMIT,
                 seed: int = 42, **kw):
        super().__init__(max_correlation=max_correlation,
                         min_correlation=min_correlation,
                         min_variance=min_variance,
                         max_cramers_v=max_cramers_v,
                         max_rule_confidence=max_rule_confidence,
                         min_required_rule_support=min_required_rule_support,
                         remove_bad_features=remove_bad_features,
                         correlation_type=correlation_type,
                         check_sample_fraction=check_sample_fraction,
                         sample_upper_limit=sample_upper_limit, seed=seed, **kw)

    def output_name(self) -> str:
        return f"{self.input_features[1].name}_sanityChecked_{self.uid[-6:]}"

    def fit(self, batch: ColumnBatch) -> SanityCheckerModel:
        import jax

        label_f, vec_f = self.input_features
        y = np.asarray(batch[label_f.name].values, dtype=np.float32)
        vec = batch[vec_f.name]
        vals = vec.values
        # keep the matrix in its native residency — on real TPU hardware the
        # host link is the bottleneck, so all stats run on device and only the
        # [D]-sized results transfer (≙ colStats on executors)
        Xd = (vals if isinstance(vals, jax.Array)
              else jnp.asarray(np.asarray(vals, np.float32)))
        if Xd.dtype not in (jnp.float32, jnp.bfloat16):
            # bf16 feature-matrix storage passes through untouched — the
            # jitted stats force f32 accumulation internally
            Xd = Xd.astype(jnp.float32)
        n, d = Xd.shape
        meta = vec.meta or VectorMeta(vec_f.name, [])
        names = (meta.column_names() if meta.size == d
                 else [f"f_{i}" for i in range(d)])

        # sampling (≙ SanityChecker sample fraction:524)
        frac = float(self.get("check_sample_fraction", 1.0))
        limit = int(self.get("sample_upper_limit", DEFAULT_SAMPLE_UPPER_LIMIT))
        if frac < 1.0 or n > limit:
            m = min(int(n * frac) if frac < 1.0 else n, limit)
            rng = np.random.default_rng(int(self.get("seed", 42)))
            idx = rng.choice(n, size=m, replace=False)
            Xs, ys_host = Xd[idx], y[idx]
        else:
            Xs, ys_host = Xd, y
        from ..columns import to_device_f32
        # exact bf16-when-lossless wire, weakref-cached: the selector's grid
        # fits reuse the SAME label transfer
        ys = to_device_f32(ys_host, exact=True)
        # multi-device: row-shard the matrix over the mesh 'data' axis so the
        # stats reductions run as ONE GSPMD program with psum collectives
        # (≙ SanityChecker colStats on executors, SanityChecker.scala:575)
        from ..parallel.mesh import data_sharding, maybe_data_mesh
        mesh = maybe_data_mesh(int(Xs.shape[0]))
        if mesh is not None:
            Xs = jax.device_put(Xs, data_sharding(mesh, 2))
            ys = jax.device_put(ys, data_sharding(mesh, 1))

        # Cramér's V + association rules per categorical indicator group
        # (≙ categoricalTests): group = columns with an indicatorValue sharing
        # (parentFeatureName, grouping)
        groups: Dict[Tuple[str, Optional[str]], List[int]] = {}
        if meta.size == d:
            for c in meta.columns:
                if c.indicator_value is not None:
                    groups.setdefault((c.parent_feature_name, c.grouping), []
                                      ).append(c.index)
        y_classes = np.unique(ys_host)
        cont_all = None
        pos_of = {}
        corr_type = self.get("correlation_type", DEFAULT_CORRELATION_TYPE)
        union: List[int] = []
        if len(y_classes) > 100:
            # contingency tables need a CATEGORICAL label: a continuous
            # (regression) response would one-hot into an [N, ~N] block;
            # Cramér's V is meaningless there, so skip the tests entirely
            groups = {}
        if groups:
            # ONE device contraction over the UNION of indicator columns
            # covers every group's contingency — per-group gathers would pay
            # a dispatch + stream sync each on high-latency links, and
            # contracting all D columns would pull width-proportional bytes
            # (≙ categoricalTests, batched)
            union = sorted({i for idxs in groups.values() for i in idxs})
            pos_of = {i: p for p, i in enumerate(union)}
        spearman = corr_type == "spearman"
        if groups:
            # stats + contingency (+ rank transform under spearman) in ONE
            # compiled program, TWO pulls.  Guard: groups only exist for
            # categorical indicator columns, so the label one-hot [N, C]
            # stays small — never build it for a continuous (regression)
            # label with ~N distinct values
            stacked, cont = _col_stats_with_contingency(
                Xs, ys, jnp.asarray(union, jnp.int32),
                jnp.asarray(y_classes, jnp.float32), spearman=spearman)
            mean, var, mn, mx, corr_arr = np.asarray(stacked)
            cont_all = np.asarray(cont)
        else:
            mean, var, mn, mx, corr = _col_stats(Xs, ys, spearman=spearman)
            corr_arr = np.asarray(corr)
            mean, var, mn, mx = (np.asarray(a) for a in (mean, var, mn, mx))
        cramers: Dict[str, float] = {}
        group_fail: Dict[int, List[str]] = {}
        max_rule_conf = float(self.get("max_rule_confidence", 1.0))
        min_rule_supp = float(self.get("min_required_rule_support", 1.0))
        contingency_by_group: Dict[str, Dict] = {}
        for (parent, grouping), idxs in groups.items():
            contingency = cont_all[:, [pos_of[i] for i in idxs]]  # [C, k]
            # full contingency panel: Cramér's V + chi2 + PMI/MI + rule
            # confidences (≙ OpStatistics.contingencyStats:300; reference
            # rows=choices so transpose)
            from ..utils.stats import contingency_stats
            cstats = contingency_stats(contingency.T)
            v = cstats.cramers_v
            gname = parent if grouping is None else f"{parent}({grouping})"
            cramers[gname] = v
            contingency_by_group[gname] = cstats.to_json()
            reasons = []
            if np.isfinite(v) and v > float(self.get("max_cramers_v", 1.0)):
                reasons.append(f"CramersV {v:.4f} > max")
            # association rule confidence (leakage): P(label=c | col=1)
            conf = np.asarray(cstats.max_confidences)
            supp = np.asarray(cstats.supports) * contingency.sum() / max(
                len(ys_host), 1)
            if max_rule_conf < 1.0 or min_rule_supp < 1.0:
                bad = (conf >= max_rule_conf) & (supp >= min_rule_supp)
                if bad.any():
                    reasons.append("rule confidence leakage")
            if reasons:
                for i in idxs:
                    group_fail.setdefault(i, []).extend(reasons)

        # per-column drop rules
        max_corr = float(self.get("max_correlation", DEFAULT_MAX_CORRELATION))
        min_corr = float(self.get("min_correlation", DEFAULT_MIN_CORRELATION))
        min_var = float(self.get("min_variance", DEFAULT_MIN_VARIANCE))
        reasons_by_col: Dict[int, List[str]] = {i: list(r) for i, r in group_fail.items()}
        for i in range(d):
            c = abs(corr_arr[i])
            if np.isfinite(c):
                if c > max_corr:
                    reasons_by_col.setdefault(i, []).append(
                        f"correlation {c:.4f} > maxCorrelation")
                elif c < min_corr:
                    reasons_by_col.setdefault(i, []).append(
                        f"correlation {c:.4f} < minCorrelation")
            if var[i] < min_var:
                reasons_by_col.setdefault(i, []).append(
                    f"variance {var[i]:.2e} < minVariance")

        remove = bool(self.get("remove_bad_features", True))
        drop_idx = sorted(reasons_by_col) if remove else []
        keep = [i for i in range(d) if i not in set(drop_idx)]
        if not keep:  # never drop everything
            keep = list(range(d))
            drop_idx = []

        summary = SanityCheckerSummary(
            correlation_type=corr_type, names=names,
            correlations_with_label=[float(c) for c in corr_arr],
            variances=[float(v) for v in var], means=[float(m) for m in mean],
            mins=[float(v) for v in mn], maxs=[float(v) for v in mx],
            cramers_v_by_group=cramers,
            contingency_stats_by_group=contingency_by_group,
            dropped=[names[i] for i in drop_idx],
            drop_reasons={names[i]: r for i, r in reasons_by_col.items()},
            sample_size=len(ys_host))

        model = SanityCheckerModel(
            fitted={"indices_to_keep": np.asarray(keep, dtype=np.int64)},
            **self._params)
        model.metadata["summary"] = summary.to_json()
        if meta.size == d:  # full input lineage for ModelInsights
            model.metadata["input_vector_meta"] = meta.to_json()
        model.summary = summary
        return self._finalize_model(model)
