"""PredictionDeIndexer (reference: core/.../impl/preparators/
PredictionDeIndexer.scala): maps an indexed prediction back to the original
string labels recorded by a fitted StringIndexer."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Transformer
from ..types import Prediction, Text


class PredictionDeIndexer(Transformer):
    """(response_indexed, prediction) → Text column of original labels."""

    in_kinds = None
    out_kind = Text
    is_device_op = False

    def __init__(self, labels: Sequence[str] = (), **params):
        super().__init__(labels=list(labels), **params)

    def transform(self, batch: ColumnBatch) -> Column:
        pred_col = batch[self.input_features[-1].name]
        labels = list(self.get("labels", []))
        vals = pred_col.values
        if isinstance(vals, dict):
            pred = np.asarray(vals["prediction"]).astype(np.int64)
        else:  # object array of per-row prediction dicts (local row path)
            pred = np.asarray([int((v or {}).get("prediction", -1))
                               for v in vals], np.int64)
        out = np.array(
            [labels[p] if 0 <= p < len(labels) else str(p) for p in pred],
            dtype=object)
        return Column(Text, out)
