"""RawFeatureFilter — pre-training data hygiene (reference:
core/src/main/scala/com/salesforce/op/filters/RawFeatureFilter.scala:137-486,
FeatureDistribution.scala:58 with fillRate:94, jsDivergence,
relativeFillRate/Ratio; results in RawFeatureFilterResults.scala).

Computes per-raw-feature fill rates and value histograms on the training data
(and optionally a scoring set), then drops features whose fill rate is too
low, whose train/score fill rates diverge, whose distributions diverge
(Jensen-Shannon), or whose null pattern correlates with the label.  Histogram
reductions are vectorised; text features hash into bins like the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import zlib

from .columns import Column, ColumnBatch
from .features import Feature
from .types import is_map_kind, is_numeric_kind, is_text_kind


@dataclass
class FeatureDistribution:
    """≙ FeatureDistribution.scala:58."""

    name: str
    key: Optional[str] = None           # map key (map features expand per key)
    count: int = 0
    nulls: int = 0
    distribution: np.ndarray = field(default_factory=lambda: np.zeros(0))
    summary: Dict[str, float] = field(default_factory=dict)

    @property
    def fill_rate(self) -> float:
        """≙ fillRate:94."""
        return 0.0 if self.count == 0 else 1.0 - self.nulls / self.count

    def relative_fill_rate(self, other: "FeatureDistribution") -> float:
        return abs(self.fill_rate - other.fill_rate)

    def relative_fill_ratio(self, other: "FeatureDistribution") -> float:
        a, b = self.fill_rate, other.fill_rate
        mn, mx = min(a, b), max(a, b)
        return float("inf") if mn == 0 else mx / mn

    def js_divergence(self, other: "FeatureDistribution") -> float:
        """Jensen-Shannon divergence of the binned distributions."""
        p, q = self.distribution, other.distribution
        if p.size == 0 or q.size == 0 or p.size != q.size:
            return 0.0
        ps, qs = p.sum(), q.sum()
        if ps == 0 or qs == 0:
            return 0.0
        p = p / ps
        q = q / qs
        m = 0.5 * (p + q)

        def kl(a, b):
            mask = a > 0
            return float(np.sum(a[mask] * np.log2(a[mask] / b[mask])))

        return 0.5 * kl(p, m) + 0.5 * kl(q, m)

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "key": self.key, "count": self.count,
                "nulls": self.nulls, "fillRate": self.fill_rate,
                "distribution": self.distribution.tolist(),
                "summary": self.summary}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "FeatureDistribution":
        # fillRate is derived from count/nulls and not read back
        return FeatureDistribution(
            d["name"], key=d.get("key"), count=int(d.get("count", 0)),
            nulls=int(d.get("nulls", 0)),
            distribution=np.asarray(d.get("distribution") or [],
                                    dtype=np.float64),
            summary={k: float(v)
                     for k, v in (d.get("summary") or {}).items()})


@dataclass
class FeatureSketch:
    """Mergeable per-feature distribution sketch for sharded / streamed data
    (≙ StreamingHistogram.java + FeatureDistribution's monoid `reduce`):
    numeric values go into a Ben-Haim/Tom-Tov streaming histogram (merges
    without a shared binning), text hashes into fixed bins (trivially
    mergeable)."""

    name: str
    key: Optional[str] = None
    count: int = 0
    nulls: int = 0
    histogram: Optional[Any] = None      # StreamingHistogram (numeric kinds)
    text_counts: Optional[np.ndarray] = None  # [text_bins] (text kinds)

    @property
    def fill_rate(self) -> float:
        """≙ FeatureDistribution.fill_rate (count = rows seen, nulls ⊆)."""
        return 0.0 if self.count == 0 else 1.0 - self.nulls / self.count

    def merge(self, other: "FeatureSketch") -> "FeatureSketch":
        assert (self.name, self.key) == (other.name, other.key)
        hist = None
        if self.histogram is not None or other.histogram is not None:
            from .utils.stats import StreamingHistogram
            a = self.histogram or StreamingHistogram()
            b = other.histogram or StreamingHistogram()
            hist = a.merge(b)
        tc = None
        if self.text_counts is not None or other.text_counts is not None:
            za = self.text_counts if self.text_counts is not None else 0.0
            zb = other.text_counts if other.text_counts is not None else 0.0
            tc = za + zb
        return FeatureSketch(self.name, self.key, self.count + other.count,
                             self.nulls + other.nulls, hist, tc)

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "key": self.key, "count": int(self.count),
                "nulls": int(self.nulls),
                "histogram": (self.histogram.to_json()
                              if self.histogram is not None else None),
                "textCounts": ([float(x) for x in self.text_counts]
                               if self.text_counts is not None else None)}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "FeatureSketch":
        hist = None
        if d.get("histogram") is not None:
            from .utils.stats import StreamingHistogram
            hist = StreamingHistogram.from_json(d["histogram"])
        tc = (np.asarray(d["textCounts"], dtype=np.float64)
              if d.get("textCounts") is not None else None)
        return FeatureSketch(d["name"], d.get("key"), int(d.get("count", 0)),
                             int(d.get("nulls", 0)), hist, tc)

    def to_distribution(self, bins: int) -> FeatureDistribution:
        if self.text_counts is not None:
            dist = np.asarray(self.text_counts, dtype=np.float64)
        elif self.histogram is not None:
            dist = self.histogram.to_fixed_bins(bins)
        else:
            dist = np.zeros(bins)
        return FeatureDistribution(self.name, key=self.key, count=self.count,
                                   nulls=self.nulls, distribution=dist)


def compute_sketches(raw_features: Sequence[Feature], batch: ColumnBatch,
                     max_bins: int = 64, text_bins: int = 100
                     ) -> Dict[Tuple[str, Optional[str]], FeatureSketch]:
    """Per-feature mergeable sketches over one shard/micro-batch.  Combine
    shards with ``merge_sketches``; finalize with ``FeatureSketch
    .to_distribution`` — distributions then combine across shards/streams the
    way the reference merges StreamingHistograms (StreamingHistogram.java:269)."""
    from .utils.stats import StreamingHistogram

    out: Dict[Tuple[str, Optional[str]], FeatureSketch] = {}
    for f in raw_features:
        col = batch.get(f.name)
        if col is None:
            continue
        n = len(col)
        kind = f.kind
        if is_map_kind(kind):
            keys = sorted({k for m in col.values if m for k in m})
            for k in keys:
                vals = [m.get(k) if m else None for m in col.values]
                out[(f.name, k)] = _sketch_of(
                    f.name, k, vals, kind, max_bins, text_bins)
            # whole-map presence sketch — also the per-shard row count that
            # merge_sketches uses to pad keys absent from a shard
            out[(f.name, None)] = FeatureSketch(
                f.name, None, n,
                int(sum(1 for m in col.values if not m)),
                text_counts=np.zeros(text_bins))
            continue
        vals = (list(col.values) if col.is_host_object()
                else np.asarray(col.values))
        if not col.is_host_object() and col.mask is not None:
            vals = np.where(np.asarray(col.mask), vals, np.nan)
        out[(f.name, None)] = _sketch_of(
            f.name, None, vals, kind, max_bins, text_bins)
    return out


def _sketch_of(name, key, vals, kind, max_bins, text_bins) -> FeatureSketch:
    from .types import map_value_kind
    from .utils.stats import StreamingHistogram

    n = len(vals)
    vkind = map_value_kind(kind) if is_map_kind(kind) else kind
    if is_numeric_kind(vkind):
        arr = np.asarray(
            [float(v) if isinstance(v, (int, float, np.floating, np.integer))
             and not isinstance(v, bool) else
             (1.0 if v is True else 0.0 if v is False else np.nan)
             for v in vals] if isinstance(vals, list) else vals,
            dtype=np.float64)
        finite = np.isfinite(arr)
        hist = StreamingHistogram(max_bins).update_all(arr[finite])
        return FeatureSketch(name, key, n, int((~finite).sum()),
                             histogram=hist)
    counts = np.zeros(text_bins)
    nulls = 0
    for v in vals:
        # same emptiness convention as _value_presence: None/""/[]/{} are null
        if v is None or (isinstance(v, float) and np.isnan(v)) or (
                hasattr(v, "__len__") and len(v) == 0):
            nulls += 1
            continue
        for item in (v if isinstance(v, (list, set, frozenset, tuple))
                     else [v]):
            counts[_stable_text_bin(item, text_bins)] += 1.0
    return FeatureSketch(name, key, n, nulls, text_counts=counts)


def merge_sketches(a: Dict, b: Dict) -> Dict:
    """Monoid merge of two shards' sketch maps.  A map key absent from one
    shard is padded with that shard's row count as nulls (taken from the
    feature's whole-map sketch) so per-key counts/fill rates stay exact."""
    def _pad(sk: FeatureSketch, side: Dict) -> FeatureSketch:
        if sk.key is None:
            return sk
        base = side.get((sk.name, None))
        if base is None or base.count == 0:
            return sk
        missing = FeatureSketch(sk.name, sk.key, base.count, base.count)
        if sk.histogram is not None:
            from .utils.stats import StreamingHistogram
            missing.histogram = StreamingHistogram(sk.histogram.max_bins)
        if sk.text_counts is not None:
            missing.text_counts = np.zeros_like(sk.text_counts)
        return sk.merge(missing)

    out: Dict = {}
    for k in set(a) | set(b):
        if k in a and k in b:
            out[k] = a[k].merge(b[k])
        elif k in a:
            out[k] = _pad(a[k], b)
        else:
            out[k] = _pad(b[k], a)
    return out


_HIST_FNS: Dict[int, Any] = {}


def _sharded_numeric_hist(mesh, arr, keep, lo, hi, bins: int) -> np.ndarray:
    """np.histogram over [lo, hi] with the COUNT REDUCTION sharded over the
    mesh 'data' axis (XLA inserts the psum).  Bin indices are computed on
    host in float64 with np.histogram's own edge semantics, so the
    distributions are bit-identical with the mesh on or off — a float32
    device binning would move edge-adjacent large-magnitude values (epoch
    timestamps) across bins and make drop decisions mesh-dependent."""
    import jax
    import jax.numpy as jnp

    from .parallel.mesh import data_sharding

    edges = np.linspace(lo, hi, bins + 1)
    idx = np.searchsorted(edges, arr, side="right") - 1
    idx = np.where(arr == hi, bins - 1, idx)        # last bin is inclusive
    valid = keep & (idx >= 0) & (idx < bins)
    idx = np.where(valid, idx, 0).astype(np.int32)

    fn = _HIST_FNS.get(bins)
    if fn is None:
        @jax.jit
        def fn(i, m):
            oh = (i[:, None] == jnp.arange(bins)[None, :]
                  ).astype(jnp.float32)
            return jnp.sum(oh * m.astype(jnp.float32)[:, None], axis=0)
        _HIST_FNS[bins] = fn
    i = jax.device_put(jnp.asarray(idx), data_sharding(mesh, 1))
    m = jax.device_put(jnp.asarray(valid), data_sharding(mesh, 1))
    return np.asarray(fn(i, m)).astype(np.float64)


def _stable_text_bin(item, text_bins: int) -> int:
    """Process-stable hash bin (crc32, not Python's randomized hash()) so
    sketches/distributions built in different processes stay mergeable and
    train-vs-score comparable."""
    return zlib.crc32(str(item).encode("utf-8")) % text_bins


def _value_presence(col: Column) -> np.ndarray:
    if col.is_host_object():
        if is_text_kind(col.kind):
            # cached one-pass profile (ops/text_profile.py) — the same scan
            # the vectorizers reuse, so RFF costs no extra column walk
            from .ops.text_profile import column_profile
            return column_profile(col).presence
        return np.array([v is not None and v != "" and v != [] and v != {}
                         for v in col.values])
    if col.mask is not None:
        return np.asarray(col.mask)
    return np.ones(len(col), dtype=bool)


def numeric_ranges(feature: Feature, col: Column
                   ) -> Dict[Optional[str], Tuple[float, float]]:
    """Per-(feature[, map-key]) numeric (min, max) — the reference's Summary
    pass.  Train + score ranges merge so BOTH sides bin identically; without a
    shared range a pure mean shift produces near-identical histogram shapes
    and JS divergence never fires."""
    kind = feature.kind
    out: Dict[Optional[str], Tuple[float, float]] = {}

    def rng_of(vals):
        arr = np.asarray(
            [float(v) if isinstance(v, (int, float, np.integer, np.floating))
             and not isinstance(v, bool) else np.nan for v in vals],
            dtype=np.float64)
        arr = arr[np.isfinite(arr)]
        if not arr.size:
            return None
        return float(arr.min()), float(arr.max())

    if is_map_kind(kind):
        from .types import map_value_kind
        if not is_numeric_kind(map_value_kind(kind)):
            return out
        from .ops.map_profile import map_expansion
        exp = map_expansion(col)
        if exp is not None:
            # cached one-pass expansion (bool-free: bools fall through to
            # the Python path below, where rng_of treats them as NaN)
            for j, k in enumerate(exp.keys):
                v = exp.vals[:, j]
                v = v[np.isfinite(v)]
                if v.size:
                    out[k] = (float(v.min()), float(v.max()))
            return out
        keys = sorted({k for m in col.values if m for k in m})
        for k in keys:
            r = rng_of([m.get(k) if m else None for m in col.values])
            if r is not None:
                out[k] = r
        return out
    if is_numeric_kind(kind) and not col.is_host_object():
        vals = np.asarray(col.values, dtype=np.float64)
        if col.mask is not None:
            vals = vals[np.asarray(col.mask)]
        vals = vals[np.isfinite(vals)]
        if vals.size:
            out[None] = (float(vals.min()), float(vals.max()))
    elif is_numeric_kind(kind):
        r = rng_of(list(col.values))
        if r is not None:
            out[None] = r
    return out


def merge_ranges(a: Dict, b: Dict) -> Dict:
    out = dict(a)
    for k, (lo, hi) in b.items():
        if k in out:
            out[k] = (min(out[k][0], lo), max(out[k][1], hi))
        else:
            out[k] = (lo, hi)
    return out


def compute_distribution(feature: Feature, col: Column, bins: int,
                         text_bins: int,
                         ranges: Optional[Dict] = None
                         ) -> List[FeatureDistribution]:
    """Per-feature histogram(s).  Maps expand per key (≙ PreparedFeatures).
    ``ranges`` pins the numeric binning range per key (shared train/score
    Summary)."""
    n = len(col)
    present = _value_presence(col)
    out = []
    kind = feature.kind
    ranges = ranges or {}
    if is_map_kind(kind):
        from .types import map_value_kind
        vkind = map_value_kind(kind)
        exp = None
        if is_numeric_kind(vkind):
            from .ops.map_profile import map_expansion
            exp = map_expansion(col)
        if exp is not None:
            idx = exp.key_index()
            for k in sorted(exp.keys):
                j = idx[k]
                sub_present = exp.present[:, j]
                dist = _histogram_of(exp.vals[:, j], sub_present, vkind,
                                     bins, text_bins,
                                     value_range=ranges.get(k))
                out.append(FeatureDistribution(
                    feature.name, key=k, count=n,
                    nulls=int((~sub_present).sum()), distribution=dist))
            if not exp.keys:
                out.append(FeatureDistribution(feature.name, count=n, nulls=n,
                                               distribution=np.zeros(bins)))
            return out
        keys = sorted({k for m in col.values if m for k in m})
        for k in keys:
            vals = [m.get(k) if m else None for m in col.values]
            sub_present = np.array([v is not None for v in vals])
            # histogram by the map's VALUE kind: a RealMap's values are
            # numeric and must bin numerically, not hash as text
            dist = _histogram_of(vals, sub_present, vkind, bins, text_bins,
                                 value_range=ranges.get(k))
            out.append(FeatureDistribution(
                feature.name, key=k, count=n,
                nulls=int((~sub_present).sum()), distribution=dist))
        if not keys:
            out.append(FeatureDistribution(feature.name, count=n, nulls=n,
                                           distribution=np.zeros(bins)))
        return out
    if is_text_kind(kind) and col.is_host_object():
        # hashed whole-value bins straight from the cached column profile
        from .ops.text_profile import column_profile
        dist = column_profile(col).crc_hist(text_bins)
    else:
        dist = _histogram_of(list(np.asarray(col.values, dtype=object))
                             if col.is_host_object() else np.asarray(col.values),
                             present, kind, bins, text_bins,
                             value_range=ranges.get(None))
    out.append(FeatureDistribution(feature.name, count=n,
                                   nulls=int((~present).sum()),
                                   distribution=dist))
    return out


def _histogram_of(vals, present: np.ndarray, kind, bins: int,
                  text_bins: int, value_range=None) -> np.ndarray:
    if is_numeric_kind(kind):
        arr = np.asarray(
            [float(v) if (v is not None and not isinstance(v, str)) else np.nan
             for v in vals] if isinstance(vals, list) else vals,
            dtype=np.float64)
        keep = present & np.isfinite(arr)
        if not keep.any():
            return np.zeros(bins)
        if value_range is not None:
            lo, hi = value_range
        else:
            lo, hi = float(arr[keep].min()), float(arr[keep].max())
        if lo == hi:
            hi = lo + 1.0
        # multi-device: the binning reduction runs as one GSPMD program with
        # rows sharded over 'data' (≙ RawFeatureFilter's executor-side
        # FeatureDistribution reduce, RawFeatureFilter.scala:137)
        from .parallel.mesh import maybe_data_mesh
        mesh = maybe_data_mesh(int(arr.size))
        if mesh is not None:
            return _sharded_numeric_hist(mesh, arr, keep, lo, hi, bins)
        h, _ = np.histogram(arr[keep], bins=bins, range=(lo, hi))
        return h.astype(np.float64)
    # text-ish: hash values into text_bins (≙ text hashed into bins)
    h = np.zeros(text_bins)
    for v, p in zip(vals, present):
        if not p or v is None:
            continue
        for item in (v if isinstance(v, (list, set, tuple)) else [v]):
            h[_stable_text_bin(item, text_bins)] += 1.0
    return h


@dataclass
class RawFeatureFilterResults:
    """≙ RawFeatureFilterResults."""

    train_distributions: List[FeatureDistribution] = field(default_factory=list)
    score_distributions: List[FeatureDistribution] = field(default_factory=list)
    dropped: List[str] = field(default_factory=list)
    dropped_map_keys: Dict[str, List[str]] = field(default_factory=dict)
    reasons: Dict[str, List[str]] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "rawFeatureDistributions": [d.to_json() for d in self.train_distributions],
            "scoringFeatureDistributions": [d.to_json() for d in self.score_distributions],
            "featuresDropped": self.dropped,
            "mapKeysDropped": self.dropped_map_keys,
            "exclusionReasons": self.reasons,
        }


class RawFeatureFilter:
    """≙ RawFeatureFilter.scala: configurable thresholds, train + optional
    scoring reader."""

    def __init__(self, min_fill_rate: float = 0.001,
                 max_fill_difference: float = 0.9,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.9,
                 max_correlation: float = 0.95,
                 bins: int = 100, text_bins: int = 255,
                 score_reader=None, protected_features: Sequence[str] = ()):
        self.min_fill_rate = float(min_fill_rate)
        self.max_fill_difference = float(max_fill_difference)
        self.max_fill_ratio_diff = float(max_fill_ratio_diff)
        self.max_js_divergence = float(max_js_divergence)
        self.max_correlation = float(max_correlation)
        self.bins = int(bins)
        self.text_bins = int(text_bins)
        self.score_reader = score_reader
        self.protected = set(protected_features)

    def filter_batch(self, batch: ColumnBatch, raw_features: Sequence[Feature]
                     ) -> Tuple[ColumnBatch, List[Feature], RawFeatureFilterResults]:
        """≙ generateFilteredRaw:486: returns (clean batch, dropped features,
        results)."""
        results = RawFeatureFilterResults()
        dists: Dict[str, List[FeatureDistribution]] = {}
        label_values: Optional[np.ndarray] = None
        label_name = next((f.name for f in raw_features if f.is_response), None)
        if label_name and label_name in batch:
            label_values = np.asarray(batch[label_name].values, dtype=np.float64)

        score_batch = None
        if self.score_reader is not None:
            score_batch = self.score_reader.generate_batch(
                [f for f in raw_features if not f.is_response])

        for f in raw_features:
            if f.name not in batch or f.is_response:
                continue
            # shared Summary range over BOTH readers so train and score bin
            # identically (≙ Summary.scala) — a mean shift must move mass to
            # different bins, or JS divergence can never see it
            ranges = numeric_ranges(f, batch[f.name])
            score_col = (score_batch[f.name] if score_batch is not None
                         and f.name in score_batch else None)
            if score_col is not None:
                ranges = merge_ranges(ranges, numeric_ranges(f, score_col))
            fdists = compute_distribution(f, batch[f.name], self.bins,
                                          self.text_bins, ranges=ranges)
            dists[f.name] = fdists
            results.train_distributions.extend(fdists)
            sdists: List[FeatureDistribution] = []
            if score_col is not None:
                sdists = compute_distribution(f, score_col, self.bins,
                                              self.text_bins, ranges=ranges)
                results.score_distributions.extend(sdists)
            if f.name in self.protected:
                continue

            reasons: List[str] = []
            # minimum fill rate (≙ minFill)
            if all(d.fill_rate < self.min_fill_rate for d in fdists):
                reasons.append(
                    f"fill rate {fdists[0].fill_rate:.4f} < minFillRate")
            # null-label correlation (leakage through missingness)
            if label_values is not None and len(np.unique(label_values)) > 1:
                presence = _value_presence(batch[f.name]).astype(np.float64)
                if presence.std() > 0:
                    corr = float(np.corrcoef(presence, label_values)[0, 1])
                    if np.isfinite(corr) and abs(corr) > self.max_correlation:
                        reasons.append(
                            f"null-label correlation {corr:.4f} > max")

            # train-vs-score distribution shift, compared PER KEY for maps
            # (≙ getFeaturesToExclude pairing distributions by (name, key));
            # shifted map keys drop individually, the whole feature drops
            # only when every key fails
            sd_by_key = {d.key: d for d in sdists}
            shifted_keys: List[str] = []
            for d in fdists:
                sd = sd_by_key.get(d.key)
                if sd is None:
                    continue
                kreasons = []
                if d.relative_fill_rate(sd) > self.max_fill_difference:
                    kreasons.append("fill rate difference train/score too large")
                if d.relative_fill_ratio(sd) > self.max_fill_ratio_diff:
                    kreasons.append("fill rate ratio train/score too large")
                js = d.js_divergence(sd)
                if js > self.max_js_divergence:
                    kreasons.append(f"JS divergence {js:.4f} > max")
                if not kreasons:
                    continue
                if d.key is None:
                    reasons.extend(kreasons)
                else:
                    shifted_keys.append(d.key)
                    results.reasons[f"{f.name}[{d.key}]"] = kreasons
            all_keys = [d.key for d in fdists if d.key is not None]
            if shifted_keys:
                results.dropped_map_keys[f.name] = shifted_keys
                if len(shifted_keys) == len(all_keys):
                    reasons.append("every map key failed train/score checks")
            if reasons:
                results.dropped.append(f.name)
                results.reasons[f.name] = reasons + \
                    results.reasons.get(f.name, [])

        dropped = set(results.dropped)
        dropped_features = [f for f in raw_features if f.name in dropped]
        clean = batch.drop(results.dropped)
        # strip dropped keys out of surviving map columns (≙ generateFilteredRaw
        # cleaning map values of excluded keys)
        for name, keys in results.dropped_map_keys.items():
            if name in dropped or name not in clean:
                continue
            kset = set(keys)
            col = clean[name]
            vals = np.empty(len(col), dtype=object)
            for i, m in enumerate(col.values):
                vals[i] = ({k: v for k, v in m.items() if k not in kset}
                           if m else m)
            clean = clean.with_column(name, Column(col.kind, vals))
        return clean, dropped_features, results
