"""Feature graph nodes and builders — the TPU-native re-design of
``Feature``/``FeatureLike`` (reference: features/src/main/scala/com/salesforce/
op/features/FeatureLike.scala:50) and ``FeatureBuilder``
(FeatureBuilder.scala:48, fromDataFrame at :232).

A ``Feature`` is a lazy symbolic column: name + kind + origin stage + parents.
The workflow reconstructs the stage DAG by DFS over ``parent_stages`` — exactly
the reference's tracing model, which maps 1:1 onto JAX's trace-then-compile.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from .types import (
    FEATURE_TYPES, Binary, FeatureType, Integral, Real, RealNN, Text,
    is_numeric_kind,
)

_uid_counters: Dict[str, itertools.count] = {}


def make_uid(class_name: str) -> str:
    c = _uid_counters.setdefault(class_name, itertools.count())
    return f"{class_name}_{next(c):012x}"


class Feature:
    """A node in the feature DAG (≙ FeatureLike)."""

    def __init__(self, name: str, kind: Type[FeatureType], is_response: bool,
                 origin_stage: Optional["PipelineStage"], parents: Sequence["Feature"],
                 uid: Optional[str] = None):
        self.name = name
        self.kind = kind
        self.is_response = bool(is_response)
        self.origin_stage = origin_stage
        self.parents: Tuple[Feature, ...] = tuple(parents)
        self.uid = uid or make_uid("Feature")

    @property
    def is_raw(self) -> bool:
        from .stages.generator import FeatureGeneratorStage
        return self.origin_stage is None or isinstance(self.origin_stage, FeatureGeneratorStage)

    def parent_stages(self) -> Dict["PipelineStage", int]:
        """DFS over lineage → stage → max distance from this feature
        (≙ FeatureLike.parentStages, used by computeDAG)."""
        dist: Dict[Any, int] = {}
        stack: List[Tuple[Feature, int]] = [(self, 0)]
        while stack:
            feat, d = stack.pop()
            st = feat.origin_stage
            if st is None:
                continue
            if dist.get(st, -1) < d:
                dist[st] = d
            for p in feat.parents:
                stack.append((p, d + 1))
        return dist

    def all_features(self) -> List["Feature"]:
        seen: Dict[str, Feature] = {}
        stack = [self]
        while stack:
            f = stack.pop()
            if f.uid in seen:
                continue
            seen[f.uid] = f
            stack.extend(f.parents)
        return list(seen.values())

    def raw_features(self) -> List["Feature"]:
        return [f for f in self.all_features() if f.is_raw]

    def history(self) -> Dict[str, Any]:
        return {
            "name": self.name, "uid": self.uid, "type": self.kind.__name__,
            "isResponse": self.is_response,
            "originStage": self.origin_stage.uid if self.origin_stage else None,
            "parents": [p.uid for p in self.parents],
        }

    def __repr__(self):
        return f"Feature<{self.kind.__name__}>({self.name!r})"

    # ---- DSL sugar (≙ dsl/Rich*Feature) — thin delegates to stages ------
    def transform_with(self, stage: "PipelineStage", *others: "Feature") -> "Feature":
        stage.set_input(self, *others)
        return stage.get_output()

    def alias(self, name: str) -> "Feature":
        from .stages.transformers import AliasTransformer
        return self.transform_with(AliasTransformer(name=name))

    def vectorize(self, **kw) -> "Feature":
        from .ops.transmogrify import transmogrify
        return transmogrify([self], **kw)

    def transmogrify(self, **kw) -> "Feature":
        return self.vectorize(**kw)

    def sanity_check(self, feature_vector: "Feature", **kw) -> "Feature":
        from .preparators.sanity_checker import SanityChecker
        st = SanityChecker(**kw)
        st.set_input(self, feature_vector)
        return st.get_output()

    def auto_bucketize(self, label: "Feature", **kw) -> "Feature":
        """Label-driven decision-tree bucketization
        (≙ RichNumericFeature.autoBucketize)."""
        from .ops.bucketizers import DecisionTreeNumericBucketizer
        st = DecisionTreeNumericBucketizer(**kw)
        st.set_input(label, self)
        return st.get_output()

    def bucketize(self, splits, **kw) -> "Feature":
        """Fixed-split bucketization (≙ RichNumericFeature.bucketize)."""
        from .ops.bucketizers import NumericBucketizer
        return self.transform_with(NumericBucketizer(splits=splits, **kw))

    def scale(self, scaling_type: str = "Linear", scaling_args=None, **kw) -> "Feature":
        from .ops.bucketizers import ScalerTransformer
        return self.transform_with(ScalerTransformer(
            scaling_type=scaling_type, scaling_args=scaling_args, **kw))

    def descale(self, scaled: "Feature", **kw) -> "Feature":
        from .ops.bucketizers import DescalerTransformer
        return self.transform_with(DescalerTransformer(**kw), scaled)

    # ---- arithmetic (≙ RichNumericFeature +,-,*,/ incl. scalar variants) --
    def _binary_math(self, other, op: str) -> "Feature":
        from .stages.transformers import (BinaryMathTransformer,
                                          UnaryMathTransformer)
        if isinstance(other, Feature):
            return self.transform_with(BinaryMathTransformer(op=op), other)
        if op == "plus":
            return self.transform_with(
                UnaryMathTransformer(op="addScalar", scalar=float(other)))
        if op == "minus":
            return self.transform_with(
                UnaryMathTransformer(op="addScalar", scalar=-float(other)))
        if op == "multiply":
            return self.transform_with(
                UnaryMathTransformer(op="multiplyScalar", scalar=float(other)))
        return self.transform_with(
            UnaryMathTransformer(op="multiplyScalar", scalar=1.0 / float(other)))

    def __add__(self, other) -> "Feature":
        return self._binary_math(other, "plus")

    def __sub__(self, other) -> "Feature":
        return self._binary_math(other, "minus")

    def __mul__(self, other) -> "Feature":
        return self._binary_math(other, "multiply")

    def __truediv__(self, other) -> "Feature":
        return self._binary_math(other, "divide")

    def abs(self) -> "Feature":
        from .stages.transformers import UnaryMathTransformer
        return self.transform_with(UnaryMathTransformer(op="abs"))

    def sqrt(self) -> "Feature":
        from .stages.transformers import UnaryMathTransformer
        return self.transform_with(UnaryMathTransformer(op="sqrt"))

    def log(self, base: float = None) -> "Feature":
        from .stages.transformers import UnaryMathTransformer
        return self.transform_with(UnaryMathTransformer(op="log", scalar=base))

    def power(self, p: float) -> "Feature":
        from .stages.transformers import UnaryMathTransformer
        return self.transform_with(UnaryMathTransformer(op="power", scalar=p))

    # ---- text (≙ RichTextFeature) ----------------------------------------
    def tokenize(self, **kw) -> "Feature":
        from .ops.text import TextTokenizer
        return self.transform_with(TextTokenizer(**kw))

    def smart_vectorize(self, **kw) -> "Feature":
        from .ops.text import SmartTextVectorizer
        return self.transform_with(SmartTextVectorizer(**kw))

    def text_len(self) -> "Feature":
        from .ops.text import TextLenTransformer
        return self.transform_with(TextLenTransformer())

    def detect_languages(self) -> "Feature":
        from .ops.text_specialized import LangDetector
        return self.transform_with(LangDetector())

    def ngram_similarity(self, other: "Feature", **kw) -> "Feature":
        from .ops.text_specialized import TextNGramSimilarity
        return self.transform_with(TextNGramSimilarity(**kw), other)

    # email/url/phone sugar (≙ RichTextFeature.isValidEmail, toDomain, ...)
    def is_valid_email(self) -> "Feature":
        from .ops.text_specialized import ValidEmailTransformer
        return self.transform_with(ValidEmailTransformer())

    def to_domain_picklist(self) -> "Feature":
        from .ops.text_specialized import (EmailToPickListTransformer,
                                           UrlToPickListTransformer)
        from .types import URL
        cls = (UrlToPickListTransformer if issubclass(self.kind, URL)
               else EmailToPickListTransformer)
        return self.transform_with(cls())

    def is_valid_phone(self, default_region: str = "US") -> "Feature":
        from .ops.text_specialized import IsValidPhoneDefaultCountry
        return self.transform_with(
            IsValidPhoneDefaultCountry(default_region=default_region))

    def detect_mime_types(self, type_hint: str = "") -> "Feature":
        from .ops.text_specialized import MimeTypeDetector
        return self.transform_with(MimeTypeDetector(type_hint=type_hint))

    # ---- dates (≙ RichDateFeature) ---------------------------------------
    def to_unit_circle(self, **kw) -> "Feature":
        from .ops.dates import DateToUnitCircleVectorizer
        return self.transform_with(DateToUnitCircleVectorizer(**kw))

    def to_time_period(self, period: str = "DayOfWeek") -> "Feature":
        from .ops.dates import TimePeriodTransformer
        return self.transform_with(TimePeriodTransformer(period=period))

    # ---- sets / maps (≙ RichSetFeature / RichMapFeature) -----------------
    def jaccard_similarity(self, other: "Feature") -> "Feature":
        from .ops.text_specialized import JaccardSimilarity
        return self.transform_with(JaccardSimilarity(), other)

    def filter_map(self, white_list_keys=(), black_list_keys=(), **kw) -> "Feature":
        from .stages.transformers import FilterMap
        return self.transform_with(FilterMap(
            white_list_keys=white_list_keys,
            black_list_keys=black_list_keys, **kw))

    # ---- generic (≙ RichFeature) -----------------------------------------
    def exists(self) -> "Feature":
        from .stages.transformers import ExistsTransformer
        return self.transform_with(ExistsTransformer())

    def to_occur(self, match_fn=None) -> "Feature":
        from .stages.transformers import ToOccurTransformer
        return self.transform_with(ToOccurTransformer(match_fn=match_fn))

    def replace_with(self, match_value, replace_with) -> "Feature":
        from .stages.transformers import ReplaceTransformer
        return self.transform_with(ReplaceTransformer(
            match_value=match_value, replace_with=replace_with))

    def filter(self, predicate_fn=None, default=None) -> "Feature":
        from .stages.transformers import FilterTransformer
        return self.transform_with(FilterTransformer(
            predicate_fn=predicate_fn, default=default))

    def occurs_in(self, other: "Feature") -> "Feature":
        """Is this text contained in ``other`` (≙ SubstringTransformer)."""
        from .stages.transformers import SubstringTransformer
        return self.transform_with(SubstringTransformer(), other)

    def map_values(self, fn, out_kind=None, name: str = None) -> "Feature":
        """Arbitrary row-level lambda stage (≙ RichFeature.map via
        UnaryLambdaTransformer).  Not serializable — session-local sugar."""
        from .columns import column_from_values
        from .stages.base import LambdaTransformer
        from .stages.transformers import _host_values

        def batch_fn(col):
            vals = [fn(v) for v in _host_values(col)]
            return column_from_values(out_kind or self.kind, vals)

        return self.transform_with(LambdaTransformer(
            batch_fn, out_kind or self.kind, name=name or "map",
            is_device_op=False))

    # ---- vectors (≙ RichVectorFeature.combine) ---------------------------
    def combine(self, *others: "Feature") -> "Feature":
        from .ops.combiner import VectorsCombiner
        return self.transform_with(VectorsCombiner(), *others)


class FeatureBuilder:
    """Typed feature declaration (≙ FeatureBuilder.scala:48).

    Usage::

        age = FeatureBuilder.Real("age").extract(lambda r: r.get("age")).as_predictor()
        survived = FeatureBuilder.RealNN("survived").extract(...).as_response()
    """

    def __init__(self, name: str, kind: Type[FeatureType]):
        self.name = name
        self.kind = kind
        self._extract: Optional[Callable[[Dict[str, Any]], Any]] = None
        self._aggregator = None
        self._extract_source: Optional[str] = None

    def extract(self, fn: Callable[[Dict[str, Any]], Any], source: Optional[str] = None) -> "FeatureBuilder":
        self._extract = fn
        self._extract_source = source
        return self

    def aggregate(self, aggregator) -> "FeatureBuilder":
        self._aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "FeatureBuilder":
        """Trailing event-time window for aggregate readers: only events
        within ``window_ms`` before the cutoff feed this feature
        (≙ FeatureBuilderWithExtract.window / FeatureAggregator timeWindow)."""
        self._window_ms = int(window_ms)
        return self

    def _build(self, is_response: bool) -> Feature:
        from .stages.generator import FeatureGeneratorStage
        stage = FeatureGeneratorStage(
            name=self.name, kind=self.kind, extract_fn=self._extract,
            aggregator=self._aggregator, extract_source=self._extract_source,
            aggregate_window_ms=getattr(self, "_window_ms", None))
        feat = Feature(self.name, self.kind, is_response, stage, parents=())
        stage._output = feat
        return feat

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)

    # Typed constructors for every feature type, e.g. FeatureBuilder.Real("x").
    # Installed below via _install_typed_constructors().


def _install_typed_constructors():
    for name, kind in FEATURE_TYPES.items():
        def ctor(fname: str, _k=kind) -> FeatureBuilder:
            return FeatureBuilder(fname, _k)
        setattr(FeatureBuilder, name, staticmethod(ctor))


_install_typed_constructors()


# --------------------------------------------------------------------------
# Schema inference (≙ FeatureBuilder.fromDataFrame, FeatureBuilder.scala:232)
# --------------------------------------------------------------------------

def infer_feature_kind(values: Sequence[Any]) -> Type[FeatureType]:
    """Infer a feature type from raw (string-ish) sample values."""
    non_null = [v for v in values if v is not None and v != ""]
    if not non_null:
        return Text
    def _is_int(v):
        try:
            int(str(v))
            return True
        except ValueError:
            return False
    def _is_float(v):
        try:
            # finite only: literal "nan"/"inf" markers stay text, matching
            # the native parser's (fastcsv.cpp parse_double) inference
            return math.isfinite(float(str(v)))
        except ValueError:
            return False
    if all(isinstance(v, bool) for v in non_null):
        return Binary
    if all(_is_int(v) for v in non_null):
        uniq = {int(str(v)) for v in non_null}
        if uniq <= {0, 1}:
            return Binary
        return Integral
    if all(_is_float(v) for v in non_null):
        return Real
    uniq = {str(v) for v in non_null}
    if len(uniq) <= max(30, int(0.1 * len(non_null))) and len(uniq) < len(non_null):
        from .types import PickList
        return PickList
    return Text


def features_from_schema(schema: Dict[str, Type[FeatureType]], response: str,
                         response_kind: Type[FeatureType] = RealNN,
                         non_nullable: Sequence[str] = ()) -> Tuple[Feature, List[Feature]]:
    """Build (response, predictors) from a name → kind schema
    (≙ FeatureBuilder.fromDataFrame[RealNN](df, response))."""
    if response not in schema:
        raise ValueError(
            f"response feature {response!r} is not present in the schema; "
            f"available: {sorted(schema)}")
    resp = FeatureBuilder(response, response_kind).as_response()
    predictors = []
    for name, kind in schema.items():
        if name == response:
            continue
        predictors.append(FeatureBuilder(name, kind).as_predictor())
    return resp, predictors
