"""Typed feature values — the TPU-native re-design of TransmogrifAI's FeatureType
hierarchy (reference: features/src/main/scala/com/salesforce/op/features/types/
FeatureType.scala:44, Numerics.scala, Text.scala, Maps.scala, OPCollection.scala).

Design notes (TPU-first):
  * The reference wraps every *value* in a typed object (``Real(Option[Double])``).
    On TPU the unit of work is the *column*: a dense device array plus a presence
    mask.  The classes here therefore play two roles:
      1. a *kind* tag carried by columns/features — used for Transmogrifier-style
       type dispatch, schema inference, and serialization;
      2. a thin row-level value wrapper for the local-scoring path (reference
       ``local/`` module) and for tests, mirroring ``value`` / ``isEmpty``.
  * Nullability: ``Option[T]`` becomes a mask array at the column level; at the
    value level ``None`` means empty, matching ``FeatureType.isEmpty``.
  * The full registry (``FEATURE_TYPES``, cf. FeatureType.featureTypeTags at
    FeatureType.scala:263-300) is used by schema inference and model manifests.
"""

from __future__ import annotations

import math
from typing import Any, ClassVar, Dict, List, Optional, Tuple, Type

__all__ = [
    "FeatureType", "OPNumeric", "Real", "RealNN", "Binary", "Integral",
    "Percent", "Currency", "Date", "DateTime",
    "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea", "PickList",
    "ComboBox", "Country", "State", "PostalCode", "City", "Street",
    "OPCollection", "OPList", "OPSet", "OPVector", "TextList", "DateList",
    "DateTimeList", "MultiPickList", "Geolocation",
    "OPMap", "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap", "URLMap",
    "TextAreaMap", "PickListMap", "ComboBoxMap", "BinaryMap", "IntegralMap",
    "RealMap", "PercentMap", "CurrencyMap", "DateMap", "DateTimeMap",
    "MultiPickListMap", "CountryMap", "StateMap", "CityMap", "PostalCodeMap",
    "StreetMap", "NameStats", "GeolocationMap", "Prediction",
    "FEATURE_TYPES", "feature_type_from_name", "is_numeric_kind",
    "is_text_kind", "is_map_kind", "map_value_kind",
]


class FeatureType:
    """Root of the feature type hierarchy (FeatureType.scala:44).

    Subclasses set class-level traits mirroring the reference's marker traits:
    ``non_nullable`` (NonNullable:122), ``is_categorical`` (Categorical:155),
    ``is_location`` (Location:140), ``single_response`` / ``multi_response``.
    """

    non_nullable: ClassVar[bool] = False
    is_categorical: ClassVar[bool] = False
    is_location: ClassVar[bool] = False
    single_response: ClassVar[bool] = False
    multi_response: ClassVar[bool] = False

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        if value is None and self.non_nullable:
            raise ValueError(f"{type(self).__name__} cannot be empty (NonNullable)")
        self.value = value

    @property
    def is_empty(self) -> bool:
        v = self.value
        if v is None:
            return True
        if isinstance(v, (list, tuple, set, dict, str)):
            return len(v) == 0
        return False

    @property
    def non_empty(self) -> bool:
        return not self.is_empty

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self.value == other.value

    def __hash__(self):
        v = self.value
        if isinstance(v, (list, set, dict)):
            v = repr(v)
        return hash((type(self).__name__, v))

    def __repr__(self):
        return f"{type(self).__name__}({self.value!r})"

    @classmethod
    def type_name(cls) -> str:
        return cls.__name__

    @classmethod
    def empty(cls) -> "FeatureType":
        return cls(None)


# --------------------------------------------------------------------------
# Numerics (Numerics.scala:40-150)
# --------------------------------------------------------------------------

class OPNumeric(FeatureType):
    """Base for numeric kinds; value is float/int or None."""

    def to_double(self) -> Optional[float]:
        return None if self.value is None else float(self.value)


class Real(OPNumeric):
    pass


class RealNN(Real):
    non_nullable = True
    single_response = True


class Binary(OPNumeric):
    is_categorical = True
    single_response = True

    def __init__(self, value: Optional[bool] = None):
        if value is not None:
            value = bool(value)
        super().__init__(value)

    def to_double(self) -> Optional[float]:
        return None if self.value is None else float(self.value)


class Integral(OPNumeric):
    def __init__(self, value: Optional[int] = None):
        if value is not None:
            value = int(value)
        super().__init__(value)


class Percent(Real):
    pass


class Currency(Real):
    pass


class Date(Integral):
    """Milliseconds since epoch, like the reference (joda millis)."""


class DateTime(Date):
    pass


# --------------------------------------------------------------------------
# Text + subtypes (Text.scala:48-301)
# --------------------------------------------------------------------------

class Text(FeatureType):
    def __init__(self, value: Optional[str] = None):
        if value is not None:
            value = str(value)
        super().__init__(value)


class Email(Text):
    def prefix(self) -> Optional[str]:
        if self.is_empty or "@" not in self.value:
            return None
        p = self.value.split("@")
        return p[0] if len(p) == 2 and p[0] and p[1] else None

    def domain(self) -> Optional[str]:
        if self.is_empty or "@" not in self.value:
            return None
        p = self.value.split("@")
        return p[1] if len(p) == 2 and p[0] and p[1] else None


class Base64(Text):
    def as_bytes(self) -> Optional[bytes]:
        import base64 as _b64
        return None if self.is_empty else _b64.b64decode(self.value)


class Phone(Text):
    pass


class ID(Text):
    pass


class URL(Text):
    def domain(self) -> Optional[str]:
        if self.is_empty:
            return None
        from urllib.parse import urlparse
        return urlparse(self.value).hostname

    def protocol(self) -> Optional[str]:
        if self.is_empty:
            return None
        from urllib.parse import urlparse
        return urlparse(self.value).scheme or None

    def is_valid(self) -> bool:
        if self.is_empty:
            return False
        from urllib.parse import urlparse
        try:
            u = urlparse(self.value)
            return u.scheme in ("http", "https", "ftp") and bool(u.hostname)
        except ValueError:
            return False


class TextArea(Text):
    pass


class PickList(Text):
    is_categorical = True


class ComboBox(Text):
    pass


class Country(Text):
    is_location = True


class State(Text):
    is_location = True


class PostalCode(Text):
    is_location = True


class City(Text):
    is_location = True


class Street(Text):
    is_location = True


# --------------------------------------------------------------------------
# Collections (OPCollection.scala:37, OPList.scala, OPSet.scala, OPVector.scala)
# --------------------------------------------------------------------------

class OPCollection(FeatureType):
    pass


class OPList(OPCollection):
    def __init__(self, value: Optional[List] = None):
        super().__init__(list(value) if value is not None else [])

    @property
    def is_empty(self) -> bool:
        return len(self.value) == 0


class OPSet(OPCollection):
    is_categorical = True
    multi_response = True

    def __init__(self, value=None):
        super().__init__(set(value) if value is not None else set())

    @property
    def is_empty(self) -> bool:
        return len(self.value) == 0


class OPVector(OPCollection):
    """Dense numeric vector (reference wraps Spark ml Vector, OPVector.scala:41).

    Column-level storage is a [N, D] float array; the row-level wrapper keeps a
    list/np array of floats.
    """

    def __init__(self, value=None):
        if value is None:
            value = []
        super().__init__(value)

    @property
    def is_empty(self) -> bool:
        return len(self.value) == 0


class TextList(OPList):
    pass


class DateList(OPList):
    pass


class DateTimeList(DateList):
    pass


class MultiPickList(OPSet):
    pass


class Geolocation(OPList):
    """(lat, lon, accuracy) triple (Geolocation.scala:47)."""

    def __init__(self, value=None):
        if value is not None:
            value = list(value)
            if len(value) not in (0, 3):
                raise ValueError("Geolocation requires (lat, lon, accuracy)")
            if len(value) == 3:
                lat, lon, _ = value
                if not (-90 <= lat <= 90) or not (-180 <= lon <= 180):
                    raise ValueError(f"invalid lat/lon: {lat},{lon}")
        super().__init__(value)

    @property
    def lat(self) -> float:
        return self.value[0] if self.value else math.nan

    @property
    def lon(self) -> float:
        return self.value[1] if self.value else math.nan

    @property
    def accuracy(self) -> float:
        return self.value[2] if self.value else math.nan


# --------------------------------------------------------------------------
# Maps (Maps.scala:40-394, OPMap.scala:38)
# --------------------------------------------------------------------------

class OPMap(OPCollection):
    """String-keyed map; ``value_kind`` gives the element feature type."""

    value_kind: ClassVar[Type[FeatureType]] = FeatureType

    def __init__(self, value: Optional[Dict[str, Any]] = None):
        super().__init__(dict(value) if value is not None else {})

    @property
    def is_empty(self) -> bool:
        return len(self.value) == 0


class TextMap(OPMap):
    value_kind = Text


class EmailMap(OPMap):
    value_kind = Email


class Base64Map(OPMap):
    value_kind = Base64


class PhoneMap(OPMap):
    value_kind = Phone


class IDMap(OPMap):
    value_kind = ID


class URLMap(OPMap):
    value_kind = URL


class TextAreaMap(OPMap):
    value_kind = TextArea


class PickListMap(OPMap):
    value_kind = PickList
    is_categorical = True


class ComboBoxMap(OPMap):
    value_kind = ComboBox


class BinaryMap(OPMap):
    value_kind = Binary
    is_categorical = True


class IntegralMap(OPMap):
    value_kind = Integral


class RealMap(OPMap):
    value_kind = Real


class PercentMap(RealMap):
    value_kind = Percent


class CurrencyMap(RealMap):
    value_kind = Currency


class DateMap(OPMap):
    value_kind = Date


class DateTimeMap(DateMap):
    value_kind = DateTime


class MultiPickListMap(OPMap):
    value_kind = MultiPickList
    is_categorical = True


class CountryMap(TextMap):
    is_location = True


class StateMap(TextMap):
    is_location = True


class CityMap(TextMap):
    is_location = True


class PostalCodeMap(TextMap):
    is_location = True


class StreetMap(TextMap):
    is_location = True


class NameStats(TextMap):
    """Name-detection stats map (Maps.scala NameStats)."""

    class Key:
        IS_NAME_INDICATOR = "isNameIndicator"
        ORIGINAL_NAME = "originalName"
        GENDER = "gender"


class GeolocationMap(OPMap):
    value_kind = Geolocation


class Prediction(RealMap):
    """The universal model output (Maps.scala:339-394): a RealMap with keys
    ``prediction``, ``probability_i``, ``rawPrediction_i``."""

    non_nullable = True

    PREDICTION = "prediction"
    RAW_PREDICTION = "rawPrediction"
    PROBABILITY = "probability"

    def __init__(self, value: Optional[Dict[str, float]] = None,
                 prediction: Optional[float] = None,
                 raw_prediction=None, probability=None):
        if value is None:
            if prediction is None:
                raise ValueError("Prediction requires a 'prediction' key")
            value = {self.PREDICTION: float(prediction)}
            for base, arr in ((self.RAW_PREDICTION, raw_prediction),
                              (self.PROBABILITY, probability)):
                if arr is not None:
                    for i, v in enumerate(arr):
                        value[f"{base}_{i}"] = float(v)
        if self.PREDICTION not in value:
            raise ValueError("Prediction map must contain key 'prediction'")
        super().__init__(value)

    @property
    def prediction(self) -> float:
        return self.value[self.PREDICTION]

    def _keyed(self, base: str) -> List[float]:
        items = [(int(k.rsplit("_", 1)[1]), v) for k, v in self.value.items()
                 if k.startswith(base + "_")]
        return [v for _, v in sorted(items)]

    @property
    def raw_prediction(self) -> List[float]:
        return self._keyed(self.RAW_PREDICTION)

    @property
    def probability(self) -> List[float]:
        return self._keyed(self.PROBABILITY)


# --------------------------------------------------------------------------
# Registry & helpers (cf. FeatureType.featureTypeTags, FeatureType.scala:263-300)
# --------------------------------------------------------------------------

FEATURE_TYPES: Dict[str, Type[FeatureType]] = {
    c.__name__: c for c in [
        Real, RealNN, Binary, Integral, Percent, Currency, Date, DateTime,
        Text, Email, Base64, Phone, ID, URL, TextArea, PickList, ComboBox,
        Country, State, PostalCode, City, Street,
        OPVector, TextList, DateList, DateTimeList, MultiPickList, Geolocation,
        TextMap, EmailMap, Base64Map, PhoneMap, IDMap, URLMap, TextAreaMap,
        PickListMap, ComboBoxMap, BinaryMap, IntegralMap, RealMap, PercentMap,
        CurrencyMap, DateMap, DateTimeMap, MultiPickListMap, CountryMap,
        StateMap, CityMap, PostalCodeMap, StreetMap, NameStats, GeolocationMap,
        Prediction,
    ]
}


def feature_type_from_name(name: str) -> Type[FeatureType]:
    try:
        return FEATURE_TYPES[name]
    except KeyError:
        raise ValueError(f"unknown feature type: {name!r}") from None


def is_numeric_kind(kind: Type[FeatureType]) -> bool:
    return issubclass(kind, OPNumeric)


def is_text_kind(kind: Type[FeatureType]) -> bool:
    return issubclass(kind, Text)


def is_map_kind(kind: Type[FeatureType]) -> bool:
    return issubclass(kind, OPMap)


def map_value_kind(kind: Type[FeatureType]) -> Type[FeatureType]:
    assert is_map_kind(kind)
    return kind.value_kind
