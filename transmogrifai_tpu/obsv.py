"""Training control plane — live introspection for in-flight train runs.

Serving has been fully observable since PR 5 (/metrics with exemplars,
distributed tracing), but a running *train* exposed nothing until it
finished or died: BENCH_11M_ATTEMPTS_r4 and OUTAGE_r5 were reconstructed
after the fact from per-rank heartbeat files and partial logs.  This module
is the train-side control plane (ROADMAP item 3):

* ``ProgressBoard`` — a lock-free snapshot object the sweep's *existing*
  seams publish into (``OpValidator.validate`` attempt loops,
  ``PhaseTimer.phase``, the memory/supervisor retry paths).  Publishing is
  a dict merge under a small lock at coarse boundaries — candidate-fit
  start/finish, fold, prune, phase — never new instrumentation in inner
  loops.  Readers get the current dict by reference, no lock.
* ``ObsServer`` — a stdlib ``ThreadingHTTPServer`` the runner starts for
  ``train`` / ``lifecycle`` / ``train-hosts`` runs when an obs port is
  configured (``--obs-port`` / ``obsParams.port`` /
  ``TRANSMOGRIFAI_OBS_PORT``; off by default, zero sockets and zero new
  spans when off).  ``GET /metrics`` renders ``telemetry.REGISTRY`` as
  Prometheus text (the serving renderer's conventions), ``GET /statusz``
  returns the live sweep JSON (phase, candidate, fold, raced-out set,
  memory plan + shrink level, supervisor state, EWMA-based ETA), and
  ``GET /traces`` returns the PR-13 telemetry summary.
* ``FlightRecorder`` — a bounded ring (``TRANSMOGRIFAI_BLACKBOX_SPANS``
  cap) of progress events, retry notes and metric deltas, dumped
  atomically as ``blackbox.json`` (same tmp + ``os.replace`` convention as
  ``write_outage_record``) on ``DataQualityError`` /
  ``MemoryExhaustedError`` / ``HostLostError`` / unhandled exception /
  SIGTERM, with the FailureLog tail and last span summaries attached — a
  crash postmortem starts with the last minute of telemetry instead of
  archaeology.  The outage record references the dump.

Cross-host: inside a host group each rank serves on its own port (the
launcher exports ``base + 1 + rank`` per child and keeps ``base`` for
itself), and the launcher polls rank ``/metrics``, re-serving one merged
panel via ``merge_worker_metrics(label="rank")`` plus a
``hostgroup_rank_up{rank=...}`` family — replacing heartbeat-file-only
visibility.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from .resilience import record_failure
from .telemetry import (REGISTRY, MetricsRegistry, active_tracer,
                        telemetry_summary)

#: Default flight-recorder ring capacity (entries, not bytes).
DEFAULT_BLACKBOX_CAP = 512

#: blackbox.json schema tag — bump on shape changes so postmortem tooling
#: can dispatch.
BLACKBOX_SCHEMA = "transmogrifai_blackbox_v1"

#: Top-level keys every blackbox.json carries (the CI smoke validates this).
BLACKBOX_KEYS = ("schema", "reason", "error", "utc", "pid", "rank", "cap",
                 "entries", "counterDeltas", "progress", "failureLogTail",
                 "spanSummaries")

_METRIC_PREFIX = "transmogrifai_train"


def _utc() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# --------------------------------------------------------------------------
# progress board
# --------------------------------------------------------------------------

class ProgressBoard:
    """Latest-wins progress snapshot: publishers merge fields under a small
    lock at coarse seam boundaries; readers take the current dict by
    reference with no lock (the dict is never mutated after the swap, so a
    reader can serialize it while the next publish builds a fresh one).

    ``note_unit`` maintains the per-fold/per-fit EWMA that backs the
    ``/statusz`` ETA."""

    def __init__(self, ewma_alpha: float = 0.3):
        self._lock = threading.Lock()
        self._snap: Dict[str, Any] = {}
        self._seq = 0
        self._ewma_alpha = float(ewma_alpha)
        self._ewma_s: Optional[float] = None

    def publish(self, **fields: Any) -> Dict[str, Any]:
        with self._lock:
            self._seq += 1
            snap = dict(self._snap)
            snap.update(fields)
            snap["seq"] = self._seq
            snap["updatedUtc"] = _utc()
            snap["updatedMono"] = time.monotonic()
            self._snap = snap
        rec = active_recorder()
        if rec is not None:
            rec.note("progress", **fields)
        return snap

    def note_unit(self, duration_s: float,
                  remaining_units: Optional[int] = None) -> None:
        """Feed one completed work unit (a candidate fit, a fold block)
        into the EWMA; with ``remaining_units`` the board publishes an
        ``etaS`` estimate."""
        a = self._ewma_alpha
        with self._lock:
            self._ewma_s = (float(duration_s) if self._ewma_s is None
                            else a * float(duration_s)
                            + (1.0 - a) * self._ewma_s)
            ewma = self._ewma_s
        fields: Dict[str, Any] = {"unitEwmaS": round(ewma, 3)}
        if remaining_units is not None:
            fields["remainingUnits"] = int(remaining_units)
            fields["etaS"] = round(ewma * max(0, int(remaining_units)), 3)
        self.publish(**fields)

    def snapshot(self) -> Dict[str, Any]:
        return self._snap   # reference to an immutable-by-convention dict

    @property
    def seq(self) -> int:
        return self._seq

    def reset(self) -> None:
        with self._lock:
            self._snap = {}
            self._seq = 0
            self._ewma_s = None


#: Process-default board — the sweep seams publish here; /statusz reads it.
BOARD = ProgressBoard()


# --------------------------------------------------------------------------
# Prometheus rendering over a MetricsRegistry
# --------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def render_registry_metrics(registry: Optional[MetricsRegistry] = None,
                            prefix: str = _METRIC_PREFIX) -> str:
    """One ``MetricsRegistry`` as Prometheus text exposition — the same
    ``# HELP`` / ``# TYPE`` / sample conventions the serving renderer uses,
    with dotted registry names flattened to underscore metric names.
    Histograms render as summaries (quantile samples + ``_sum``/``_count``)
    so the scrape stays cheap and the log-bucket internals stay private."""
    registry = registry if registry is not None else REGISTRY
    snap = registry.snapshot()
    lines: List[str] = []

    for name in sorted(snap["counters"]):
        v = snap["counters"][name]
        if not _is_num(v):
            continue
        n = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# HELP {n} Counter {name} (telemetry registry)")
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {v}")
    for name in sorted(snap["gauges"]):
        v = snap["gauges"][name]
        if v is None:
            v = 0
        if not _is_num(v):
            continue
        n = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# HELP {n} Gauge {name} (telemetry registry)")
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {v}")
    for name in sorted(snap["histograms"]):
        h = snap["histograms"][name]
        if not isinstance(h, dict):
            continue
        n = f"{prefix}_{_sanitize(name)}_seconds"
        lines.append(f"# HELP {n} Latency summary {name} "
                     "(telemetry registry)")
        lines.append(f"# TYPE {n} summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            qv = h.get(key)
            if _is_num(qv):
                lines.append(f'{n}{{quantile="{q}"}} {qv}')
        lines.append(f"{n}_sum {h.get('sum', 0)}")
        lines.append(f"{n}_count {h.get('count', 0)}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# /statusz assembly
# --------------------------------------------------------------------------

_T0 = time.monotonic()


def statusz_snapshot(board: Optional[ProgressBoard] = None,
                     registry: Optional[MetricsRegistry] = None
                     ) -> Dict[str, Any]:
    """The live ``/statusz`` JSON: the board's sweep progress plus the
    memory / supervisor / hostgroup state read through the registry's
    gauges at snapshot time (the gauges lazy-import their sources, so this
    never pulls jax before the run itself did)."""
    board = board if board is not None else BOARD
    registry = registry if registry is not None else REGISTRY
    snap = registry.snapshot()
    g, c = snap["gauges"], snap["counters"]
    out: Dict[str, Any] = {
        "utc": _utc(),
        "pid": os.getpid(),
        "uptimeS": round(time.monotonic() - _T0, 3),
        "progress": board.snapshot(),
        "memory": {
            "shrinkLevel": g.get("memory.shrink_level", 0),
            "shrinksTotal": c.get("memory.shrinks_total", 0),
        },
        "supervisor": {
            "state": g.get("supervisor.state", 0),
            "probesTotal": c.get("supervisor.probes_total", 0),
            "outagesTotal": c.get("supervisor.outages_total", 0),
            "lastProbeLatencyS": g.get("supervisor.last_probe_latency_s", 0),
        },
    }
    from .parallel import hostgroup
    if hostgroup.hostgroup_env_present():
        out["hostgroup"] = {
            "rank": hostgroup.current_rank(),
            "worldSize": hostgroup.group_world_size(),
            "generation": int(os.environ.get(
                "TRANSMOGRIFAI_HOSTGROUP_GENERATION", "0") or 0),
        }
    rec = active_recorder()
    if rec is not None:
        out["blackbox"] = {"cap": rec.cap, "entries": len(rec),
                          "lastDump": rec.last_dump_path}
    return out


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

def blackbox_cap() -> int:
    try:
        return max(8, int(os.environ.get("TRANSMOGRIFAI_BLACKBOX_SPANS",
                                         str(DEFAULT_BLACKBOX_CAP))))
    except ValueError:
        return DEFAULT_BLACKBOX_CAP


def default_blackbox_path() -> str:
    """Where the crash dump lands: ``TRANSMOGRIFAI_BLACKBOX_PATH`` wins;
    inside a host group the rank writes ``blackbox-rank<r>.json`` into the
    shared run dir (next to heartbeats, so the launcher can collect it);
    ``TRANSMOGRIFAI_OUTAGE_DIR`` is next; the working directory is last —
    the recorder only exists when the operator opted into the control
    plane, so the run is explicitly configured."""
    p = os.environ.get("TRANSMOGRIFAI_BLACKBOX_PATH")
    if p:
        return p
    run_dir = os.environ.get("TRANSMOGRIFAI_HOSTGROUP_RUN_DIR")
    if run_dir:
        from .parallel.hostgroup import current_rank
        return os.path.join(run_dir, f"blackbox-rank{current_rank()}.json")
    d = os.environ.get("TRANSMOGRIFAI_OUTAGE_DIR")
    if d:
        return os.path.join(d, "blackbox.json")
    return os.path.join(os.getcwd(), "blackbox.json")


class FlightRecorder:
    """Bounded in-memory ring of control-plane events plus a one-shot
    atomic crash dump.  ``note()`` is a deque append under a lock —
    publishers are the same coarse seams that feed the ``ProgressBoard``,
    so the hot path never sees it."""

    def __init__(self, cap: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 failure_tail: int = 32, span_tail: int = 32):
        self.cap = cap if cap is not None else blackbox_cap()
        self.registry = registry if registry is not None else REGISTRY
        self.failure_tail = int(failure_tail)
        self.span_tail = int(span_tail)
        self._ring: "collections.deque" = collections.deque(maxlen=self.cap)
        self._lock = threading.Lock()
        # metric deltas are relative to recorder install, so the dump shows
        # what THIS run did, not the process's lifetime totals
        try:
            self._baseline = dict(self.registry.counters())
        except Exception:  # noqa: BLE001 — a broken gauge source must not
            #               keep the recorder from starting
            self._baseline = {}
        self.last_dump_path: Optional[str] = None

    def note(self, kind: str, **fields: Any) -> None:
        e = {"tUtc": _utc(), "kind": str(kind)}
        e.update(fields)
        with self._lock:
            self._ring.append(e)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def counter_deltas(self) -> Dict[str, Any]:
        try:
            cur = self.registry.counters()
        except Exception:  # noqa: BLE001
            return {}
        return {k: v - self._baseline.get(k, 0)
                for k, v in sorted(cur.items())
                if v != self._baseline.get(k, 0)}

    def payload(self, reason: str,
                error: Optional[BaseException] = None) -> Dict[str, Any]:
        from .resilience import active_failure_log
        tracer = active_tracer()
        spans: List[Dict[str, Any]] = []
        if tracer is not None:
            for s in tracer.spans[-self.span_tail:]:
                spans.append({"name": s.name,
                              "startS": round(s.start_s, 4),
                              "durationS": round(s.duration_s, 4),
                              "status": s.status})
        tail = [e.to_json()
                for e in active_failure_log().events[-self.failure_tail:]]
        rank = None
        if os.environ.get("TRANSMOGRIFAI_HOSTGROUP_RANK") is not None:
            from .parallel.hostgroup import current_rank
            rank = current_rank()
        return {
            "schema": BLACKBOX_SCHEMA,
            "reason": str(reason),
            "error": (f"{type(error).__name__}: {error}"
                      if error is not None else None),
            "utc": _utc(),
            "pid": os.getpid(),
            "rank": rank,
            "cap": self.cap,
            "entries": self.entries(),
            "counterDeltas": self.counter_deltas(),
            "progress": BOARD.snapshot(),
            "failureLogTail": tail,
            "spanSummaries": spans,
        }

    def dump(self, path: Optional[str] = None, *, reason: str,
             error: Optional[BaseException] = None) -> Optional[str]:
        """Atomically write ``blackbox.json`` (tmp sibling + ``os.replace``
        — the ``write_outage_record`` convention).  Best-effort: a full
        disk must not mask the crash being recorded."""
        path = path or default_blackbox_path()
        try:
            doc = self.payload(reason, error)
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, indent=2, default=str)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001
            record_failure("obsv", "swallowed", e, point="obsv.blackbox",
                           path=path)
            return None
        self.last_dump_path = path
        return path


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()
_LAST_DUMP: Optional[str] = None


def install_recorder(rec: Optional[FlightRecorder]
                     ) -> Optional[FlightRecorder]:
    """Install (or, with ``None``, remove) the process-wide recorder.
    Returns what was installed.  Either way the remembered dump path is
    cleared — ``last_blackbox_path`` is scoped to one recorder's
    lifetime, so an outage record never points at a previous run's
    blackbox."""
    global _RECORDER, _LAST_DUMP
    with _RECORDER_LOCK:
        _RECORDER = rec
        _LAST_DUMP = None
    return rec


def active_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def blackbox_note(kind: str, **fields: Any) -> None:
    """The one-liner deep seams use (memory shrinks, supervisor retries,
    host losses).  A single global read when the control plane is off."""
    rec = _RECORDER
    if rec is not None:
        rec.note(kind, **fields)


def dump_blackbox(reason: str, error: Optional[BaseException] = None,
                  path: Optional[str] = None) -> Optional[str]:
    """Dump the installed recorder's ring (no-op → None when the control
    plane is off).  Remembers the path so the outage record can point at
    it."""
    global _LAST_DUMP
    rec = _RECORDER
    if rec is None:
        return None
    out = rec.dump(path, reason=reason, error=error)
    if out is not None:
        _LAST_DUMP = out
    return out


def last_blackbox_path() -> Optional[str]:
    """The most recent dump this process wrote, if any — referenced from
    outage records."""
    rec = _RECORDER
    if rec is not None and rec.last_dump_path:
        return rec.last_dump_path
    return _LAST_DUMP


# --------------------------------------------------------------------------
# admin HTTP server
# --------------------------------------------------------------------------

#: Live servers (tests assert this is empty when the plane is off).
_ACTIVE_SERVERS: List["ObsServer"] = []


class ObsServer:
    """The admin endpoint: ``/metrics`` (Prometheus text), ``/statusz``
    (live JSON), ``/traces`` (telemetry summary), ``/healthz``.  One
    daemonized ``ThreadingHTTPServer``; ``port=0`` binds an ephemeral port
    (tests).  ``metrics_fn`` / ``statusz_fn`` override the defaults — the
    hostgroup launcher serves its merged rank panel through them."""

    def __init__(self, port: int, *, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 board: Optional[ProgressBoard] = None,
                 metrics_fn: Optional[Callable[[], str]] = None,
                 statusz_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 prefix: str = _METRIC_PREFIX):
        self.host = host
        self.port = int(port)
        self.registry = registry if registry is not None else REGISTRY
        self.board = board if board is not None else BOARD
        self.metrics_fn = metrics_fn
        self.statusz_fn = statusz_fn
        self.prefix = prefix
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- request handling --------------------------------------------------
    def _metrics_text(self) -> str:
        if self.metrics_fn is not None:
            return self.metrics_fn()
        return render_registry_metrics(self.registry, prefix=self.prefix)

    def _statusz_doc(self) -> Dict[str, Any]:
        if self.statusz_fn is not None:
            return self.statusz_fn()
        return statusz_snapshot(self.board, self.registry)

    def _traces_doc(self) -> Dict[str, Any]:
        return telemetry_summary(active_tracer(), self.registry)

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence per-request noise
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(200, server._metrics_text().encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/statusz":
                        body = json.dumps(server._statusz_doc(), indent=2,
                                          default=str).encode()
                        self._send(200, body, "application/json")
                    elif path == "/traces":
                        body = json.dumps(server._traces_doc(), indent=2,
                                          default=str).encode()
                        self._send(200, body, "application/json")
                    elif path in ("/", "/healthz"):
                        self._send(200, b"ok\n", "text/plain")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — a scrape bug must
                    #                     never touch the run it watches
                    record_failure("obsv", "swallowed", e,
                                   point="obsv.server", path=path)
                    try:
                        self._send(500, f"{e}\n".encode(), "text/plain")
                    except Exception:  # noqa: BLE001
                        pass

        return Handler

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ObsServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"obs-server:{self.port}",
                                        kwargs={"poll_interval": 0.2},
                                        daemon=True)
        self._thread.start()
        _ACTIVE_SERVERS.append(self)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        finally:
            if self in _ACTIVE_SERVERS:
                _ACTIVE_SERVERS.remove(self)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def active_servers() -> List[ObsServer]:
    return list(_ACTIVE_SERVERS)


def obs_port_from_env() -> int:
    """The configured admin port; 0/unset = control plane off."""
    try:
        return int(os.environ.get("TRANSMOGRIFAI_OBS_PORT", "0") or 0)
    except ValueError:
        return 0


def obs_enabled() -> bool:
    return obs_port_from_env() > 0


def maybe_start_obs_server(port: Optional[int] = None,
                           **kw: Any) -> Optional[ObsServer]:
    """Start the admin server when a port is configured; None (and a
    recorded degradation, never a raised error) otherwise or on a bind
    failure — observability must not fail the run it watches."""
    port = port if port is not None else obs_port_from_env()
    if not port or port <= 0:
        return None
    try:
        return ObsServer(port, **kw).start()
    except OSError as e:
        record_failure("obsv", "degraded", e, point="obsv.server",
                       port=port,
                       fallback="run continues without admin endpoint")
        return None
