"""Monoid aggregators for event-time feature aggregation — the TPU-native
equivalent of MonoidAggregatorDefaults (reference: features/src/main/scala/com/
salesforce/op/aggregators/MonoidAggregatorDefaults.scala:41) built on Algebird.

Each feature kind has a default monoid used when an aggregate/conditional
reader groups multiple events per key into one row.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Type

from .types import (
    Binary, Date, DateList, DateTime, DateTimeList, FeatureType, Geolocation,
    Integral, MultiPickList, OPList, OPMap, OPSet, OPVector, Real, RealNN,
    Text, TextArea, TextList, is_map_kind, is_numeric_kind, is_text_kind,
)


class MonoidAggregator:
    """zero + plus over raw python values (None = empty)."""

    def __init__(self, zero: Any, plus: Callable[[Any, Any], Any],
                 name: str = "custom"):
        self.zero = zero
        self.plus = plus
        self.name = name

    def aggregate(self, values: Sequence[Any]) -> Any:
        acc = self.zero
        for v in values:
            if v is None:
                continue
            acc = v if acc is None else self.plus(acc, v)
        return acc


def _sum(a, b):
    return a + b


def _min(a, b):
    return min(a, b)


def _max(a, b):
    return max(a, b)


def _concat(a, b):
    return list(a) + list(b)


def _union(a, b):
    return set(a) | set(b)


def _merge_maps(a, b):
    out = dict(a)
    out.update(b)
    return out


def _concat_text(a, b):
    return f"{a} {b}"


def _logical_or(a, b):
    return bool(a) or bool(b)


def default_aggregator(kind: Type[FeatureType]) -> MonoidAggregator:
    """Defaults mirror MonoidAggregatorDefaults.aggregatorOf: numerics sum,
    booleans OR, text concatenates, lists concat, sets union, maps
    last-write-wins merge, dates take max (most recent)."""
    if issubclass(kind, Binary):
        return MonoidAggregator(None, _logical_or, "or")
    if issubclass(kind, (Date, DateTime)):
        return MonoidAggregator(None, _max, "maxDate")
    if is_numeric_kind(kind):
        return MonoidAggregator(None, _sum, "sum")
    if issubclass(kind, (TextArea,)):
        return MonoidAggregator(None, _concat_text, "concatText")
    if is_text_kind(kind):
        return MonoidAggregator(None, lambda a, b: b, "last")
    if issubclass(kind, Geolocation):
        return MonoidAggregator(None, lambda a, b: b, "lastGeo")
    if issubclass(kind, OPSet):
        return MonoidAggregator(None, _union, "union")
    if issubclass(kind, OPVector):
        return MonoidAggregator(None, lambda a, b: [x + y for x, y in zip(a, b)], "sumVec")
    if issubclass(kind, OPList):
        return MonoidAggregator(None, _concat, "concat")
    if is_map_kind(kind):
        return MonoidAggregator(None, _merge_maps, "mergeMaps")
    return MonoidAggregator(None, lambda a, b: b, "last")


class CustomMonoidAggregator(MonoidAggregator):
    """User-supplied monoid (≙ CustomMonoidAggregator)."""
