"""Monoid aggregators for event-time feature aggregation — the TPU-native
equivalent of MonoidAggregatorDefaults (reference: features/src/main/scala/com/
salesforce/op/aggregators/MonoidAggregatorDefaults.scala:41) built on Algebird.

Each feature kind has a default monoid used when an aggregate/conditional
reader groups multiple events per key into one row.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Type

from .types import (
    Binary, Date, DateList, DateTime, DateTimeList, FeatureType, Geolocation,
    Integral, MultiPickList, OPList, OPMap, OPSet, OPVector, Real, RealNN,
    Text, TextArea, TextList, is_map_kind, is_numeric_kind, is_text_kind,
)


class MonoidAggregator:
    """zero + plus over raw python values (None = empty)."""

    def __init__(self, zero: Any, plus: Callable[[Any, Any], Any],
                 name: str = "custom"):
        self.zero = zero
        self.plus = plus
        self.name = name

    def aggregate(self, values: Sequence[Any]) -> Any:
        acc = self.zero
        for v in values:
            if v is None:
                continue
            acc = v if acc is None else self.plus(acc, v)
        return acc


def _sum(a, b):
    return a + b


def _min(a, b):
    return min(a, b)


def _max(a, b):
    return max(a, b)


def _concat(a, b):
    return list(a) + list(b)


def _union(a, b):
    return set(a) | set(b)


def _merge_maps(a, b):
    out = dict(a)
    out.update(b)
    return out


def _concat_text(a, b):
    return f"{a} {b}"


def _logical_or(a, b):
    return bool(a) or bool(b)


def default_aggregator(kind: Type[FeatureType]) -> MonoidAggregator:
    """Defaults mirror MonoidAggregatorDefaults.aggregatorOf: numerics sum,
    booleans OR, text concatenates, lists concat, sets union, maps
    last-write-wins merge, dates take max (most recent)."""
    if issubclass(kind, Binary):
        return MonoidAggregator(None, _logical_or, "or")
    if issubclass(kind, (Date, DateTime)):
        return MonoidAggregator(None, _max, "maxDate")
    if is_numeric_kind(kind):
        return MonoidAggregator(None, _sum, "sum")
    if issubclass(kind, (TextArea,)):
        return MonoidAggregator(None, _concat_text, "concatText")
    if is_text_kind(kind):
        return MonoidAggregator(None, lambda a, b: b, "last")
    if issubclass(kind, Geolocation):
        return MonoidAggregator(None, lambda a, b: b, "lastGeo")
    if issubclass(kind, OPSet):
        return MonoidAggregator(None, _union, "union")
    if issubclass(kind, OPVector):
        return MonoidAggregator(None, lambda a, b: [x + y for x, y in zip(a, b)], "sumVec")
    if issubclass(kind, OPList):
        return MonoidAggregator(None, _concat, "concat")
    if is_map_kind(kind):
        return MonoidAggregator(None, _merge_maps, "mergeMaps")
    return MonoidAggregator(None, lambda a, b: b, "last")


class CustomMonoidAggregator(MonoidAggregator):
    """User-supplied monoid (≙ CustomMonoidAggregator)."""


# ---------------------------------------------------------------------------
# Event-time machinery (≙ features/.../aggregators/: Event[O], CutOffTime,
# TimeBasedAggregator)
# ---------------------------------------------------------------------------

from dataclasses import dataclass  # noqa: E402


@dataclass(frozen=True)
class Event:
    """A timestamped value (≙ Event[O], features/.../aggregators/Event.scala):
    the unit the aggregate/conditional readers group and window over."""
    time_ms: int
    value: Any

    def __lt__(self, other):
        return self.time_ms < other.time_ms


_MS_PER_DAY = 24 * 60 * 60 * 1000


class CutOffTime:
    """Cut-off point separating predictor history from response future
    (≙ CutOffTime.scala: UnixEpoch / DaysAgo / DDMMYYYY / NoCutoff).

    ``timestamp_ms(now_ms)`` resolves the cutoff; None means no cutoff (all
    events are predictor history).
    """

    def __init__(self, kind: str, value: Optional[int] = None):
        self.kind = kind
        self.value = value

    # -- factories (≙ CutOffTime companion object) -------------------------
    @staticmethod
    def unix_epoch(ms: int) -> "CutOffTime":
        return CutOffTime("UnixEpoch", int(ms))

    @staticmethod
    def days_ago(days: int) -> "CutOffTime":
        return CutOffTime("DaysAgo", int(days))

    @staticmethod
    def dd_mm_yyyy(date: str) -> "CutOffTime":
        """'ddMMyyyy' string, e.g. '04051999' → epoch ms at UTC midnight."""
        import datetime as _dt
        d = _dt.datetime.strptime(date, "%d%m%Y").replace(
            tzinfo=_dt.timezone.utc)
        return CutOffTime("DDMMYYYY", int(d.timestamp() * 1000))

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime("NoCutoff", None)

    def timestamp_ms(self, now_ms: Optional[int] = None) -> Optional[int]:
        if self.kind == "NoCutoff":
            return None
        if self.kind == "DaysAgo":
            if now_ms is None:
                import time as _time
                now_ms = int(_time.time() * 1000)
            return now_ms - self.value * _MS_PER_DAY
        return self.value


def split_events_at_cutoff(
        events: Sequence[Event], cutoff_ms: Optional[int],
        predictor_window_ms: Optional[int] = None,
        response_window_ms: Optional[int] = None,
) -> "tuple[List[Event], List[Event]]":
    """(predictor_events, response_events) for one key — the TimeBasedAggregator
    window rule: predictors take events strictly BEFORE the cutoff (within the
    trailing ``predictor_window_ms`` when given); responses take events at or
    after it (within the leading ``response_window_ms``).  With no cutoff
    everything is predictor history."""
    if cutoff_ms is None:
        return list(events), []
    pred: List[Event] = []
    resp: List[Event] = []
    for ev in events:
        if ev.time_ms < cutoff_ms:
            if (predictor_window_ms is None
                    or ev.time_ms >= cutoff_ms - predictor_window_ms):
                pred.append(ev)
        else:
            if (response_window_ms is None
                    or ev.time_ms < cutoff_ms + response_window_ms):
                resp.append(ev)
    return pred, resp
