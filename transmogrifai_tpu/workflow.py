"""Workflow — the user-facing DAG container and fitted model (reference:
core/src/main/scala/com/salesforce/op/OpWorkflow.scala:207,234,344,382-458,
OpWorkflowCore.scala:52, OpWorkflowModel.scala:184-394,
OpWorkflowModelWriter.scala:76, OpWorkflowModelReader.scala).

``train`` reconstructs the stage DAG from the result features, generates raw
data through the reader (optionally filtered by RawFeatureFilter), fits the
DAG layer-by-layer, and returns a ``WorkflowModel`` whose transformer DAG is a
pure column program (the reference's persist-every-K Catalyst hacks are
unnecessary — HBM residency + XLA fusion replace them, SURVEY.md §2.6 P5).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .columns import Column, ColumnBatch
from .dag import apply_dag, compute_dag, cut_dag, dag_stages, fit_dag, fit_layer
from .features import Feature
from .readers.base import DataReader, Reader
from .stages.base import Estimator, PipelineStage, Transformer, TransformerModel
from .stages.generator import FeatureGeneratorStage
from .stages.serialization import (feature_to_json, kind_by_name,
                                   stage_fitted_arrays, stage_from_json,
                                   stage_to_json)
from .types import Prediction

MODEL_JSON = "op-model.json"
PARAMS_NPZ = "params.npz"


class _WorkflowCore:
    """Shared between Workflow and WorkflowModel (≙ OpWorkflowCore.scala:52)."""

    def __init__(self):
        self.reader: Optional[Reader] = None
        self.result_features: Tuple[Feature, ...] = ()
        self.raw_features: List[Feature] = []
        self.blacklisted: List[Feature] = []
        self.parameters: Dict[str, Any] = {}
        self._input_batch: Optional[ColumnBatch] = None

    # -- input wiring ------------------------------------------------------
    def set_reader(self, reader: Reader):
        self.reader = reader
        return self

    def set_input_records(self, records: Sequence[Dict[str, Any]],
                          key_fn=None):
        self.reader = DataReader(records=list(records), key_fn=key_fn)
        return self

    def set_input_batch(self, batch: ColumnBatch):
        self._input_batch = batch
        return self

    def set_parameters(self, params: Dict[str, Any]):
        self.parameters = dict(params)
        return self

    # -- raw data ----------------------------------------------------------
    def generate_raw_data(self) -> ColumnBatch:
        """≙ OpWorkflow.generateRawData:234."""
        if self._input_batch is not None:
            return self._input_batch
        if self.reader is None:
            raise ValueError("no reader or input batch set — call set_reader/"
                             "set_input_records/set_input_batch first")
        raw = [f for f in self.raw_features
               if f.name not in {b.name for b in self.blacklisted}]
        return self.reader.generate_batch(raw)

    def _collect_features(self):
        feats: Dict[str, Feature] = {}
        for rf in self.result_features:
            for f in rf.all_features():
                feats[f.uid] = f
        self.raw_features = sorted(
            (f for f in feats.values() if f.is_raw), key=lambda f: f.name)
        return feats


class Workflow(_WorkflowCore):
    """≙ OpWorkflow."""

    def __init__(self):
        super().__init__()
        self._workflow_cv = False
        self._raw_feature_filter = None
        self._model_stages: Dict[str, TransformerModel] = {}
        self._sanitizers: Dict[str, bool] = {}

    def set_result_features(self, *features: Feature) -> "Workflow":
        """≙ setResultFeatures: reconstruct the stage DAG (OpWorkflow.scala:207)."""
        self.result_features = tuple(features)
        self._collect_features()
        self._validate_stages()
        return self

    def with_workflow_cv(self) -> "Workflow":
        """≙ withWorkflowCV (OpWorkflowCore.scala:104): refit the feature
        stages feeding the model selector inside each CV fold."""
        self._workflow_cv = True
        return self

    def with_sanitizers(self, nan_check: bool = False,
                        purity_check: bool = True,
                        serialization_check: bool = True) -> "Workflow":
        """Opt-in discipline checks during train (sanitizer.py — the analog
        of the reference's closure-serializability validation and of JVM
        sanitizers): ``nan_check`` turns on jax_debug_nans for the whole fit;
        ``purity_check`` asserts every fitted transformer is deterministic;
        ``serialization_check`` asserts every stage JSON-round-trips."""
        self._sanitizers = {"nan": nan_check, "purity": purity_check,
                            "serialization": serialization_check}
        return self

    def apply_stage_params(self, op_params) -> "Workflow":
        """Per-stage-class hyperparameter injection from OpParams
        (≙ OpWorkflow.setStageParameters, OpWorkflow.scala:178-199).  Entries
        matching no stage warn — a typo'd class name must not silently train
        with defaults."""
        import warnings

        stages = dag_stages(compute_dag(self.result_features))
        for match, kv in (op_params.stage_params or {}).items():
            hit = False
            for st in stages:
                cls_name = type(st).__name__
                if cls_name == match or cls_name.startswith(match):
                    hit = True
                    for k, v in kv.items():
                        st.set(k, v)
            if not hit:
                warnings.warn(
                    f"stageParams entry {match!r} matched no stage in the "
                    f"workflow (stages: "
                    f"{sorted({type(s).__name__ for s in stages})})",
                    stacklevel=2)
        return self

    def apply_racing_params(self, racing) -> "Workflow":
        """Push OpParams.racing ({enabled, eta, minSurvivors}) onto every
        ModelSelector's validator — racing is a validator behavior, not a
        stage hyper-parameter, so it rides its own channel instead of
        stageParams."""
        if not racing:
            return self
        for st in dag_stages(compute_dag(self.result_features)):
            v = getattr(st, "validator", None)
            if v is None or not hasattr(v, "racing"):
                continue
            if "enabled" in racing:
                v.racing = bool(racing["enabled"])
            if "eta" in racing:
                v.racing_eta = float(racing["eta"])
            if "minSurvivors" in racing:
                v.racing_min_survivors = int(racing["minSurvivors"])
        return self

    def with_raw_feature_filter(self, **kw) -> "Workflow":
        """≙ withRawFeatureFilter (OpWorkflow.scala:538)."""
        from .filters import RawFeatureFilter
        self._raw_feature_filter = RawFeatureFilter(**kw)
        return self

    def with_model_stages(self, model: "WorkflowModel") -> "Workflow":
        """≙ withModelStages (OpWorkflow.scala:471): reuse fitted stages with
        matching uids for partial retraining."""
        for layer in model.fitted_dag:
            for st in layer:
                self._model_stages[st.uid.replace("_model", "")] = st
        return self

    def _apply_blacklist(self):
        """≙ setBlacklist (OpWorkflow.scala:117): remove blacklisted raw
        features from every stage's inputs; stages that lose all inputs die
        and their outputs cascade to downstream consumers."""
        dead = {f.uid for f in self.blacklisted}
        if not dead:
            return
        dag = compute_dag(self.result_features)
        for layer in dag:  # deepest-first = closest to raw data
            for st in layer:
                if not st.input_features:
                    continue
                new_inputs = tuple(f for f in st.input_features
                                   if f.uid not in dead)
                if not new_inputs:
                    for out in st.output_features:
                        dead.add(out.uid)
                    continue
                if len(new_inputs) != len(st.input_features):
                    st.input_features = new_inputs
                    for out in st.output_features:
                        out.parents = new_inputs
        lost = [f.name for f in self.result_features if f.uid in dead]
        if lost:
            raise ValueError(
                f"RawFeatureFilter removed all inputs of result feature(s) "
                f"{lost}; relax the filter thresholds or protect features")

    def _validate_stages(self):
        """≙ OpWorkflow stage validation :277-335 — distinct uids and
        stage-type sanity."""
        dag = compute_dag(self.result_features)
        seen = set()
        for st in dag_stages(dag):
            if st.uid in seen:
                raise ValueError(f"duplicate stage uid {st.uid}")
            seen.add(st.uid)
            if not isinstance(st, (Transformer, Estimator)):
                raise TypeError(f"stage {st} is neither Transformer nor Estimator")

    # -- training ----------------------------------------------------------
    def train(self, resume_from: Optional[str] = None) -> "WorkflowModel":
        """≙ OpWorkflow.train:344.

        The whole fit runs under a train-scoped ``FailureLog`` (ambient, so
        compiled-segment demotions, validator candidate skips and device
        fallbacks report into it from any depth/thread); the log is exposed
        on the returned model as ``model.failure_log``.

        ``resume_from`` names a sweep-checkpoint directory: completed
        selector candidates are flushed there after each candidate family,
        and a restarted train pointed at the same directory replays them
        instead of re-fitting (resumptions appear in the failure log with
        action ``resumed``).  For the dynamic extent of the call SIGTERM/
        SIGINT request a graceful stop at the next candidate boundary; the
        sweep flushes a final checkpoint and the call raises
        ``TrainingPreempted`` (carrying ``resume_from`` and the failure
        log) instead of dying mid-write."""
        from .checkpoint import (SweepCheckpoint, TrainingPreempted,
                                 preemption_guard, use_sweep_checkpoint)
        from .profiling import PhaseTimer
        from .resilience import FailureLog, record_failure, use_failure_log
        from .sanitizer import (audit_dag_purity, audit_stage_serialization,
                                nan_guard)
        from .telemetry import span

        timer = PhaseTimer()
        flog = FailureLog()
        sweep_cp = None
        if resume_from is not None:
            sweep_cp = SweepCheckpoint(resume_from)
        try:
            with span("workflow.train",
                      resumed=bool(sweep_cp is not None and len(sweep_cp))), \
                    use_failure_log(flog), preemption_guard("train"), \
                    use_sweep_checkpoint(sweep_cp):
                if sweep_cp is not None and len(sweep_cp):
                    record_failure(
                        "train", "resumed",
                        f"sweep checkpoint with {len(sweep_cp)} completed "
                        "candidate(s)", point="checkpoint.load",
                        resume_from=sweep_cp.path)
                return self._train_guarded(timer, flog)
        except TrainingPreempted as e:
            e.failure_log = flog
            raise

    def _train_guarded(self, timer, flog) -> "WorkflowModel":
        """Body of ``train`` — runs with the failure log, preemption guard
        and sweep checkpoint already ambient."""
        from .sanitizer import (audit_dag_purity, audit_stage_serialization,
                                nan_guard)
        # the poison-data firewall (quality.py) brackets ingestion: the
        # ambient config lets readers quarantine malformed records per-row
        # (instead of raising mid-file), and the post-assembly screen drops
        # NaN/Inf rows before anything ships to the device.  Past
        # maxQuarantineFraction, training aborts with DataQualityError —
        # never silently fits on a fraction of the data.
        from .quality import QualityConfig, screen_batch, use_quality
        qcfg = QualityConfig.resolve(self.parameters.get("quality"))
        with timer.phase("read"):
            if qcfg.enabled:
                with use_quality(qcfg):
                    batch = self.generate_raw_data()
                batch = screen_batch(batch, self.raw_features, qcfg,
                                     stage="train")
            else:
                batch = self.generate_raw_data()
        with timer.phase("prefetch"):
            self._prefetch_text_profiles(batch)
        rff_results = None
        if self._raw_feature_filter is not None:
            with timer.phase("rff"):
                batch, dropped, rff_results = \
                    self._raw_feature_filter.filter_batch(
                        batch, self.raw_features)
                self.blacklisted = dropped
                self._apply_blacklist()
        dag = compute_dag(self.result_features)
        if self._sanitizers.get("serialization"):
            audit_stage_serialization(dag_stages(dag))
        raw_batch = batch if self._sanitizers.get("purity") else None
        with nan_guard(self._sanitizers.get("nan", False)):
            if self._workflow_cv:
                batch, fitted_dag = self._fit_with_workflow_cv(batch, dag,
                                                               timer)
            else:
                batch, fitted_dag = self._fit_plain(batch, dag, timer)
        if raw_batch is not None:
            audit_dag_purity(fitted_dag, raw_batch)
        model = WorkflowModel(
            result_features=self.result_features,
            fitted_dag=fitted_dag,
            raw_features=self.raw_features,
            blacklisted=self.blacklisted,
            parameters=self.parameters,
            rff_results=rff_results)
        model.reader = self.reader
        model._input_batch = self._input_batch
        model.train_batch = batch
        model.app_metrics = timer.app_metrics("train")
        model.failure_log = flog
        return model

    def _prefetch_text_profiles(self, batch) -> None:
        """Start the async host→device transfers a training run will need,
        up front: packed token ids for hashing vectorizers (profiled ONCE,
        cached on the Column) and the bf16-wire copies of numeric raw
        columns + the label.  The 5-12 MB/s host link then overlaps
        RawFeatureFilter + fit host work instead of serializing after it
        (the TPU analog of the reference keeping row work on executors,
        SmartTextVectorizer.scala:80).  Large batches only: tiny workflows
        would pay dispatch latency for nothing."""
        if len(batch) < 100_000:
            return
        import jax

        from .columns import to_device_f32
        from .ops.text import HashingVectorizer, SmartTextVectorizer
        if jax.default_backend() == "cpu":
            return      # no slow link to hide
        try:
            for st in dag_stages(compute_dag(self.result_features)):
                if isinstance(st, (SmartTextVectorizer, HashingVectorizer)):
                    num_hashes = int(st.get("num_hashes") or 0)
                    for f in st.input_features:
                        col = batch.get(f.name)
                        if col is None or not col.is_host_object():
                            continue
                        vals = col.values
                        if len(vals) and not isinstance(
                                next((v for v in vals if v is not None), ""),
                                str):
                            continue    # token lists take the legacy path
                        from .ops.text_profile import column_profile
                        prof = column_profile(col)
                        if num_hashes:
                            prof.prefetch(num_hashes)
            # numeric raw columns + label: the weakref transfer cache makes
            # these THE copies every later consumer (frontier _prep,
            # vectorizer fits, selector y) reuses
            for f in self.raw_features:
                col = batch.get(f.name)
                if col is None or col.is_host_object():
                    continue
                v = col.values
                if (isinstance(v, np.ndarray)
                        and v.dtype in (np.float32, np.float64)):
                    to_device_f32(v, exact=f.is_response)
        except Exception as e:  # noqa: BLE001 — prefetch must never break
            # train, but a dead prefetch means the host link no longer hides
            # behind RFF/fit work — observable, not invisible
            from .resilience import record_failure
            record_failure("workflow.prefetch", "swallowed", e,
                           point="workflow.prefetch")

    def _fit_plain(self, batch, dag, timer=None):
        """Fit the DAG with DEFERRED transform application: estimators fit
        layer-by-layer as before, but fitted transforms apply lazily — each
        run of pending transforms compiles into ONE fused XLA program
        (ScoreProgram with staged stages) the moment a downstream estimator
        needs their outputs.  The whole vectorizer layer + combiner becomes
        a single program instead of one dispatch/compile per stage — the fit
        path's analog of the reference's single bulk row map
        (FitStagesUtil.scala:96)."""
        import itertools

        from .compiled import ScoreProgram
        from .dag import prune_batch
        from .profiling import PhaseTimer
        from .selector import ModelSelector
        timer = timer or PhaseTimer()
        fitted_dag = []
        # columns that outlive the DAG: raw inputs (label profile, re-scoring),
        # result outputs (evaluate), and the row key
        keep = ({f.name for f in self.raw_features}
                | {f.name for f in self.result_features} | {"key"})
        pending: List[Transformer] = []      # fitted, not yet applied
        pending_out: set = set()

        def flush(b, remaining=()):
            """Apply pending transforms as one fused program, then release
            every column no remaining consumer needs — a deferred flush must
            not extend intermediate liveness past what the eager layer-by-
            layer fit had (e.g. the combined feature vector must be GONE
            from HBM before the selector's CV grid runs)."""
            if not pending:
                return b
            prog = ScoreProgram(
                [[m] for m in pending],
                [f.name for m in pending for f in m.output_features])
            b = prog(b, keep_intermediate=True)
            pending.clear()
            pending_out.clear()
            return prune_batch(b, remaining, keep)

        for i, layer in enumerate(dag):
            new_layer = []
            for st in layer:
                if st.uid in self._model_stages:
                    new_layer.append(self._model_stages[st.uid])
                else:
                    new_layer.append(st)
            kinds = sorted({type(s).__name__ for s in new_layer})
            tag = ("selector" if any(isinstance(s, ModelSelector)
                                     for s in new_layer)
                   else "fit:" + "+".join(kinds))
            with timer.phase(tag):
                models = []
                for j, st in enumerate(new_layer):
                    if isinstance(st, Estimator):
                        if any(f.name in pending_out
                               for f in st.input_features):
                            batch = flush(batch, itertools.chain(
                                new_layer[j:],
                                (s for l in dag[i + 1:] for s in l)))
                        m = st.fit(batch)
                    elif isinstance(st, Transformer):
                        m = st
                    else:
                        raise TypeError(
                            f"stage {st} is neither Transformer nor Estimator")
                    models.append(m)
                    pending.append(m)
                    pending_out.update(f.name for f in m.output_features)
            fitted_dag.append(models)
            batch = prune_batch(
                batch, itertools.chain(
                    pending, (s for l in dag[i + 1:] for s in l)), keep)
        with timer.phase("fit:apply_tail"):
            batch = flush(batch)
        return batch, fitted_dag

    def _fit_with_workflow_cv(self, batch, dag, timer=None):
        """≙ OpWorkflow.fitStages workflow-CV branch :411-457: cut the DAG at
        the model selector, fit 'before' once, refit 'during' inside each fold."""
        from .profiling import PhaseTimer
        from .selector import ModelSelector
        timer = timer or PhaseTimer()
        selector = None
        for st in dag_stages(dag):
            if isinstance(st, ModelSelector):
                selector = st
                break
        if selector is None:
            return self._fit_plain(batch, dag, timer)
        before, during, after = cut_dag(dag, selector)
        fitted_dag = []
        for layer in before:
            with timer.phase(
                    "fit:" + "+".join(sorted({type(s).__name__
                                              for s in layer}))):
                batch, fitted = fit_layer(batch, layer)
            fitted_dag.append(fitted)
        # 'during' estimators are refit per fold by the validator; fit them on
        # the full data first (the final model's feature stages) so every
        # 'after' stage — selector or side branch, in any within-layer order —
        # sees its inputs materialized
        for dl in during:
            with timer.phase(
                    "fit:" + "+".join(sorted({type(s).__name__
                                              for s in dl}))):
                batch, f2 = fit_layer(batch, dl)
            fitted_dag.append(f2)
        for layer in after:
            new_layer = []
            for st in layer:
                if st is selector:
                    with timer.phase("selector"):
                        model = selector.fit(batch, in_fold_dag=during)
                        new_layer.append(model)
                        batch = model.transform_batch(batch)
                else:
                    tag = "fit:" + type(st).__name__
                    with timer.phase(tag):
                        if isinstance(st, Estimator):
                            m = st.fit(batch)
                        else:
                            m = st
                        batch = m.transform_batch(batch)
                    new_layer.append(m)
            fitted_dag.append(new_layer)
        return batch, fitted_dag

    # -- loading -----------------------------------------------------------
    @staticmethod
    def load_model(path: str) -> "WorkflowModel":
        return WorkflowModel.load(path)


class WorkflowModel(_WorkflowCore):
    """≙ OpWorkflowModel: the fitted DAG."""

    def __init__(self, result_features: Sequence[Feature] = (),
                 fitted_dag: Optional[List[List[Transformer]]] = None,
                 raw_features: Sequence[Feature] = (),
                 blacklisted: Sequence[Feature] = (),
                 parameters: Optional[Dict[str, Any]] = None,
                 rff_results=None):
        super().__init__()
        self.result_features = tuple(result_features)
        self.fitted_dag = fitted_dag or []
        self.raw_features = list(raw_features)
        self.blacklisted = list(blacklisted)
        self.parameters = dict(parameters or {})
        self.rff_results = rff_results
        self.train_batch: Optional[ColumnBatch] = None
        self.app_metrics = None     # AppMetrics from train() (profiling.py)
        self.failure_log = None     # FailureLog from train() (resilience.py)
        self.baselines = None       # ModelBaselines from load() (lifecycle)

    # -- access ------------------------------------------------------------
    @property
    def stages(self) -> List[Transformer]:
        return [s for layer in self.fitted_dag for s in layer]

    def get_stage(self, uid: str) -> Transformer:
        for s in self.stages:
            if s.uid == uid or s.uid == uid + "_model":
                return s
        raise KeyError(uid)

    @property
    def selected_model(self):
        from .selector import SelectedModel
        for s in self.stages:
            if isinstance(s, SelectedModel):
                return s
        return None

    # -- scoring -----------------------------------------------------------
    def score_program(self):
        """The fitted DAG compiled for repeated scoring: host prologue →
        ONE jitted XLA program over the device-resident middle → host
        epilogue (≙ the reference's bulk applyOpTransformations row map,
        FitStagesUtil.scala:96, minus the persist-every-K hacks).  Cached on
        the model; jit re-uses the executable across calls with one compile
        per input shape."""
        if getattr(self, "_score_program", None) is None:
            from .compiled import ScoreProgram
            self._score_program = ScoreProgram(
                self.fitted_dag, [f.name for f in self.result_features])
        return self._score_program

    def score(self, batch: Optional[ColumnBatch] = None,
              keep_raw_features: bool = False,
              keep_intermediate_features: bool = False) -> ColumnBatch:
        """≙ OpWorkflowModel.score:255 — apply the whole fitted transformer
        DAG and return the result-feature columns."""
        from .telemetry import span
        if batch is None:
            batch = self.generate_raw_data()
        with span("workflow.score", rows=len(batch)):
            scored = self.score_program()(
                batch, keep_intermediate=keep_intermediate_features)
        names = [f.name for f in self.result_features if f.name in scored]
        if keep_intermediate_features:
            return scored
        keep = list(names)
        if keep_raw_features:
            keep = [f.name for f in self.raw_features if f.name in scored] + keep
        if "key" in scored:
            keep = ["key"] + keep
        return scored.select([n for n in dict.fromkeys(keep)])

    def score_fn(self):
        """≙ scoreFn: returns a callable batch → scored batch with the DAG
        precomputed."""
        return lambda batch: self.score(batch)

    def evaluate(self, evaluator, label_feature: Optional[Feature] = None,
                 batch: Optional[ColumnBatch] = None) -> Dict[str, Any]:
        """≙ OpWorkflowModel.evaluate:320."""
        if batch is None:
            batch = self.generate_raw_data()
        label = label_feature
        if label is None:
            # the label the model actually trained on — the selector's first
            # input (e.g. an INDEXED text response), not the raw string column
            sm = self.selected_model
            if sm is not None and sm.input_features:
                label = sm.input_features[0]
        if label is None:
            label = next(
                (f for f in self.raw_features if f.is_response), None)
        if label is None:
            raise ValueError(
                "evaluate: no response feature in the model's raw features — "
                "pass label_feature explicitly")
        try:
            scored = self.score_program()(batch)
        except KeyError as e:
            raise ValueError(
                f"evaluate: column {e.args[0]!r} required by the DAG is "
                "missing from the scoring data — evaluation needs labelled "
                "rows (use score() for label-free data)") from e
        has_intermediate = False
        if label.name not in scored:
            # a DAG-computed label (e.g. an indexed text response) may live in
            # an intermediate column the lean score pass dropped
            scored = self.score_program()(batch, keep_intermediate=True)
            has_intermediate = True
        if label.name not in scored:
            raise ValueError(
                f"evaluate: response column {label.name!r} is not present in "
                "the scoring data — evaluation needs labelled rows (use "
                "score() for label-free data)")
        pred_f = next(
            (f for f in self.result_features if f.kind is Prediction), None)
        if pred_f is None:
            # fallback: any dict-valued (Prediction-shaped) result column
            if not has_intermediate:
                scored = self.score_program()(batch, keep_intermediate=True)
            pred_f = next(
                (f for f in self.result_features
                 if f.name in scored and isinstance(scored[f.name].values, dict)),
                None)
        if pred_f is None:
            raise ValueError(
                "evaluate: no Prediction-typed result feature on this model; "
                f"result features: {[f.name for f in self.result_features]}")
        pred_col = scored[pred_f.name]
        import jax
        if any(isinstance(v, jax.Array) for v in pred_col.values.values()):
            # device-resident scores (the compiled score program keeps them in
            # HBM): run the whole metric panel as device reductions — only
            # scalars cross the host link
            import jax.numpy as jnp
            y_dev = jnp.asarray(
                np.asarray(scored[label.name].values, dtype=np.float32))
            dev_out = dict(pred_col.values)
            em = evaluator.evaluate_all_device(
                y_dev, dev_out, jnp.ones_like(y_dev))
            if em is not None:
                return em.to_json()
        y = np.asarray(scored[label.name].values, dtype=np.float64)
        pred = {k: np.asarray(v) for k, v in pred_col.values.items()}
        for opt in ("probability", "rawPrediction"):
            pred.setdefault(opt, None)
        return evaluator.evaluate_all(y, pred).to_json()

    def score_and_evaluate(self, evaluator, **kw):
        return self.score(**kw), self.evaluate(evaluator)

    def compute_data_up_to(self, feature: Feature,
                           batch: Optional[ColumnBatch] = None) -> ColumnBatch:
        """≙ computeDataUpTo (OpWorkflowCore.scala:299)."""
        if batch is None:
            batch = self.generate_raw_data()
        return apply_dag(batch, self.fitted_dag, up_to_feature=feature)

    # -- insights ----------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """≙ OpWorkflowModel.summary: ModelInsights JSON."""
        from .insights import ModelInsights
        return ModelInsights.extract(self).to_json()

    def summary_pretty(self) -> str:
        from .insights import ModelInsights
        return ModelInsights.extract(self).pretty()

    # -- persistence (≙ OpWorkflowModelWriter.toJson) -----------------------
    def save(self, path: str, overwrite: bool = True,
             aot: Optional[bool] = None):
        """Atomically write the model bundle to ``path``.

        The bundle is staged in a temp sibling directory, checksummed into
        a ``MANIFEST.json``, fsynced and renamed into place — a crash mid-
        save can never leave a torn bundle at ``path``.  With
        ``overwrite=False`` a non-empty ``path`` raises ``FileExistsError``
        instead of being replaced.

        Unless opted out (``aot=False`` / ``--no-aot`` /
        ``TRANSMOGRIFAI_NO_AOT=1``), the fused scoring programs are AOT-
        compiled across the serving padding ladder and shipped inside the
        bundle as digest-covered serialized executables (see aot.py) — a
        fresh process then serves its first score without invoking XLA."""
        from .aot import abi_stamp, aot_enabled, export_bundle
        from .checkpoint import atomic_bundle_write
        manifest_extra: Dict[str, Any] = {"kind": "workflow-model"}
        do_aot = aot_enabled() if aot is None else (bool(aot) and aot_enabled())
        with atomic_bundle_write(path, overwrite=overwrite,
                                 manifest_extra=manifest_extra) as tmp:
            self._write_bundle_files(tmp)
            if do_aot:
                n = export_bundle(self, tmp)
                if n:
                    # read by atomic_bundle_write at successful exit — the
                    # stamp lands in MANIFEST only when export worked
                    manifest_extra["aot"] = {"abi": abi_stamp(),
                                             "executables": n}

    def _write_bundle_files(self, path: str) -> None:
        all_feats: Dict[str, Feature] = {}
        for rf in self.result_features:
            for f in rf.all_features():
                all_feats[f.uid] = f
        stages_json, arrays = [], {}
        for layer_i, layer in enumerate(self.fitted_dag):
            for st in layer:
                d = stage_to_json(st)
                d["layer"] = layer_i
                d["outputFeatures"] = [f.uid for f in st.output_features]
                stages_json.append(d)
                arrays.update(stage_fitted_arrays(st))
        # raw generator stages (for schema/lineage); blacklisted raw features
        # were rewired out of the DAG and have no lineage to persist
        raw_json = []
        for f in self.raw_features:
            if f.uid not in all_feats:
                continue
            st = f.origin_stage
            if isinstance(st, FeatureGeneratorStage):
                d = {"uid": st.uid, "name": st.name,
                     "type": f.kind.__name__,
                     "isResponse": f.is_response,
                     "outputFeature": f.uid}
                if st.get("aggregate_window_ms") is not None:
                    d["aggregateWindowMs"] = int(st.get("aggregate_window_ms"))
                if st.extract_source:
                    d["extractSource"] = st.extract_source
                elif st.has_custom_extract:
                    import warnings
                    warnings.warn(
                        f"feature {st.name!r} has a custom extract function "
                        "with no source text; the reloaded model will fall "
                        "back to by-name record lookup — pass "
                        "FeatureBuilder.extract(fn, source='<expr over r>') "
                        "to persist it (≙ FeatureBuilderMacros source capture)",
                        stacklevel=3)
                raw_json.append(d)
        manifest = {
            "uid": "OpWorkflowModel",
            "resultFeaturesUids": [f.uid for f in self.result_features],
            "blacklistedFeaturesUids": [f.uid for f in self.blacklisted],
            "rawFeatures": raw_json,
            "allFeatures": [feature_to_json(f) for f in all_feats.values()],
            "stages": stages_json,
            "parameters": self.parameters,
            "rawFeatureFilterResults": (
                self.rff_results.to_json() if self.rff_results is not None else None),
        }
        with open(os.path.join(path, MODEL_JSON), "w") as fh:
            json.dump(manifest, fh, indent=2, default=str)
        np.savez_compressed(os.path.join(path, PARAMS_NPZ), **arrays)
        # training-time drift baselines (lifecycle/baselines.py): the
        # retained train batch sketches into baselines.json, digest-covered
        # by the bundle manifest.  A model with no train batch (loaded and
        # re-saved) simply ships without baselines — drift monitoring then
        # reports itself disabled for that bundle.
        try:
            from .lifecycle.baselines import build_baselines
            baselines = build_baselines(self)
            if baselines is not None:
                baselines.save(path)
        except Exception as e:  # noqa: BLE001 — baselines are observability,
            #                     never a reason to fail a model save
            from .resilience import record_failure
            record_failure("workflow.save", "swallowed", e,
                           point="checkpoint.save", detail="baselines.json")
        # the data-quality schema contract (quality.py): raw feature kinds,
        # nullability and training-range hints, digest-covered like every
        # bundle file.  Serving enforces it at assembly; a failed write
        # degrades serving to a re-derived contract, never fails the save.
        try:
            from .quality import RawSchema
            RawSchema.derive(self.raw_features,
                             batch=getattr(self, "train_batch",
                                           None)).save(path)
        except Exception as e:  # noqa: BLE001 — same rule as baselines
            from .resilience import record_failure
            record_failure("workflow.save", "swallowed", e,
                           point="checkpoint.save", detail="schema.json")
        from .telemetry import active_tracer, write_telemetry_summary
        if active_tracer() is not None:
            # traced run: bundle the run's timeline summary next to the
            # model (digested into MANIFEST.json like every bundle file)
            try:
                write_telemetry_summary(os.path.join(path, "telemetry.json"))
            except Exception as e:  # noqa: BLE001 — diagnostics only
                from .resilience import record_failure
                record_failure("workflow.save", "swallowed", e,
                               point="checkpoint.save")

    @staticmethod
    def load(path: str) -> "WorkflowModel":
        """≙ OpWorkflowModelReader: stages → features → model.

        ``path`` may be a single bundle directory or a checkpoint root
        containing versioned ``ckpt-NNNNNN`` bundles — in the latter case
        the newest bundle that passes verification is loaded (corrupt ones
        are skipped with a recorded failure).  Bundles with a
        ``MANIFEST.json`` are digest- and version-verified
        (``CorruptModelError`` / ``ModelVersionError`` name the offending
        file); legacy bundles without one still load, with a warning."""
        from .checkpoint import (CorruptModelError, find_latest_valid,
                                 is_bundle_dir, verify_bundle)
        from .resilience import record_failure
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"model directory {path!r} does not exist")
        if not is_bundle_dir(path):
            path = find_latest_valid(path)
        manifest_meta = verify_bundle(path)
        if manifest_meta is None:
            import warnings
            warnings.warn(
                f"model bundle {path!r} has no MANIFEST.json (saved by a "
                "pre-checkpointing build); loading without integrity "
                "verification", stacklevel=2)
            record_failure("checkpoint", "degraded",
                           "legacy bundle without MANIFEST",
                           point="checkpoint.load", bundle=path)
        json_path = os.path.join(path, MODEL_JSON)
        if not os.path.exists(json_path):
            raise CorruptModelError(path, MODEL_JSON,
                                    "model description file is missing")
        with open(json_path) as fh:
            manifest = json.load(fh)
        npz_path = os.path.join(path, PARAMS_NPZ)
        if os.path.exists(npz_path):
            arrays = dict(np.load(npz_path, allow_pickle=False))
        elif manifest_meta is not None and \
                PARAMS_NPZ in (manifest_meta.get("files") or {}):
            raise CorruptModelError(path, PARAMS_NPZ,
                                    "fitted-parameter file is missing")
        else:
            # legacy bundles may legitimately have no arrays
            arrays = {}

        # 1. rebuild stages
        stages_by_uid: Dict[str, PipelineStage] = {}
        layers: Dict[int, List[PipelineStage]] = {}
        for d in manifest["stages"]:
            st = stage_from_json(d, arrays)
            stages_by_uid[d["uid"]] = st
            layers.setdefault(d["layer"], []).append(st)
        # raw feature generators
        raw_gens: Dict[str, FeatureGeneratorStage] = {}
        for d in manifest["rawFeatures"]:
            gen = FeatureGeneratorStage(
                name=d["name"], kind=kind_by_name(d["type"]), uid=d["uid"],
                aggregate_window_ms=d.get("aggregateWindowMs"),
                extract_source=d.get("extractSource"))
            raw_gens[d["uid"]] = gen

        # 2. rebuild features
        feats: Dict[str, Feature] = {}
        feat_json = {d["uid"]: d for d in manifest["allFeatures"]}

        def build_feature(uid: str) -> Feature:
            if uid in feats:
                return feats[uid]
            d = feat_json[uid]
            parents = tuple(build_feature(p) for p in d.get("parents", ()))
            origin = None
            if d.get("originStage"):
                origin = (stages_by_uid.get(d["originStage"])
                          or raw_gens.get(d["originStage"]))
            f = Feature(d["name"], kind_by_name(d["type"]), d["isResponse"],
                        origin, parents, uid=uid)
            feats[uid] = f
            return f

        for uid in feat_json:
            build_feature(uid)

        # 3. wire stage inputs/outputs
        for d in manifest["stages"]:
            st = stages_by_uid[d["uid"]]
            st.input_features = tuple(feats[u] for u in d["inputFeatures"])
            outs = tuple(feats[u] for u in d.get("outputFeatures", ()))
            if outs:
                st._output = outs[0] if len(outs) == 1 else outs
                for f in outs:
                    f.origin_stage = st
        for d in manifest["rawFeatures"]:
            gen = raw_gens[d["uid"]]
            f = feats[d["outputFeature"]]
            gen._output = f
            f.origin_stage = gen

        fitted_dag = [layers[i] for i in sorted(layers)]
        model = WorkflowModel(
            result_features=tuple(feats[u] for u in manifest["resultFeaturesUids"]),
            fitted_dag=fitted_dag,
            raw_features=[f for f in feats.values() if f.is_raw and
                          f.origin_stage is not None],
            blacklisted=[feats[u] for u in manifest.get("blacklistedFeaturesUids", ())
                         if u in feats],
            parameters=manifest.get("parameters") or {})
        # 4. training-time drift baselines ride along when present;
        # manifested bundles without them predate the lifecycle subsystem —
        # they load and serve fine, drift monitoring just stays off
        try:
            from .lifecycle.baselines import load_baselines
            model.baselines = load_baselines(path)
        except Exception as e:  # noqa: BLE001 — corrupt baselines degrade
            #                     to disabled monitoring, never a load error
            record_failure("checkpoint", "degraded", e,
                           point="checkpoint.load", bundle=path,
                           detail="unreadable baselines.json")
        if model.baselines is None and manifest_meta is not None:
            record_failure("checkpoint", "degraded",
                           "bundle has no baselines.json (pre-lifecycle "
                           "build); drift monitoring disabled",
                           point="checkpoint.load", bundle=path)
        # the schema contract rides along; bundles that predate it (or with
        # an unreadable schema.json) get a contract re-derived from the
        # rebuilt raw features — serving always has one to enforce
        from .quality import RawSchema
        model.raw_schema = RawSchema.for_model(model, path)
        # 5. AOT executables (formatVersion 2 bundles): deserialize straight
        # into the score program — mismatch/corruption degrades to JIT
        from .aot import install_bundle
        model.aot_executables = install_bundle(model, path)
        # 6. fleet registry: stamp the score program with its model-content
        # family so shapes the bundle did not ship (or a bundle with no AOT
        # artifacts at all — e.g. exported on another platform) still
        # install published executables instead of compiling
        from . import aot_registry
        if aot_registry.registry_enabled():
            model.score_program().registry_family = \
                aot_registry.model_family_digest(path)
        return model
