"""Stage abstractions — the TPU-native re-design of OpPipelineStage[0-4,N]
(reference: features/src/main/scala/com/salesforce/op/stages/OpPipelineStages.scala:55)
and the Unary/Binary/Sequence Transformer/Estimator bases
(features/.../stages/base/*).

Differences from the reference, by design:
  * Stages operate on *columns* (dense arrays), not rows.  A ``Transformer``
    maps a ``ColumnBatch`` to its output ``Column`` as a pure function; when
    every input column is device-resident the function is jax-traceable, so a
    whole DAG layer fuses into one XLA program (replacing
    FitStagesUtil.applyOpTransformations' bulk row map, FitStagesUtil.scala:96).
  * ``Estimator.fit`` returns a fitted ``TransformerModel``; fits are XLA
    reductions (moments, histograms, top-K) rather than Spark jobs.
  * Arity is data, not types: ``set_input(*features)`` + ``in_kinds``
    validation replaces OpPipelineStage1..4/N.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

from ..columns import Column, ColumnBatch
from ..features import Feature, make_uid
from ..types import FeatureType


class PipelineStage:
    """Base of all stages (≙ OpPipelineStageBase).

    Subclass contract:
      * class attrs ``in_kinds`` (tuple of FeatureType classes or None for any,
        or None to skip validation) and ``out_kind``.
      * constructor params are the stage's hyper-parameters; they are captured
        automatically for serialization (≙ ctor-args-via-reflection JSON,
        OpPipelineStageReaderWriter.scala).
    """

    in_kinds: Optional[Tuple] = None
    out_kind: Type[FeatureType] = FeatureType
    num_outputs: int = 1

    def __init__(self, **params):
        self.uid = params.pop("uid", None) or make_uid(type(self).__name__)
        self._params: Dict[str, Any] = dict(params)
        self.input_features: Tuple[Feature, ...] = ()
        self._output: Optional[Any] = None

    # ---- params ------------------------------------------------------------
    def get(self, name: str, default=None):
        return self._params.get(name, default)

    def set(self, name: str, value) -> "PipelineStage":
        self._params[name] = value
        return self

    @property
    def params(self) -> Dict[str, Any]:
        return dict(self._params)

    @property
    def operation_name(self) -> str:
        return type(self).__name__

    # ---- wiring ------------------------------------------------------------
    def set_input(self, *features: Feature) -> "PipelineStage":
        self._check_input_kinds(features)
        self.input_features = tuple(features)
        self._output = None
        return self

    def _check_input_kinds(self, features: Sequence[Feature]):
        if self.in_kinds is None:
            return
        if len(self.in_kinds) != len(features) and Ellipsis not in self.in_kinds:
            raise ValueError(
                f"{self.operation_name} expects {len(self.in_kinds)} inputs, "
                f"got {len(features)}")
        for i, f in enumerate(features):
            want = (self.in_kinds[i] if i < len(self.in_kinds)
                    and self.in_kinds[i] is not Ellipsis else self.in_kinds[-2]
                    if Ellipsis in self.in_kinds else None)
            if want is not None and not issubclass(f.kind, want):
                raise TypeError(
                    f"{self.operation_name} input {i} ({f.name!r}) must be "
                    f"{want.__name__}, got {f.kind.__name__}")

    def output_name(self) -> str:
        base = "-".join(f.name for f in self.input_features[:3]) or "out"
        return f"{base}_{self.operation_name}_{self.uid[-6:]}"

    # stages that legitimately consume the label (models, sanity checker)
    # mark their outputs as predictors (≙ AllowLabelAsInput trait,
    # OpPipelineStages.scala); everything else propagates response-ness
    allow_label_as_input: bool = False

    def output_is_response(self) -> bool:
        # ≙ reference default outputIsResponse = inputs.exists(_.isResponse)
        if self.allow_label_as_input:
            return False
        return any(f.is_response for f in self.input_features)

    def make_output_features(self) -> Any:
        feats = tuple(
            Feature(name=self.output_name() if self.num_outputs == 1
                    else f"{self.output_name()}_{i}",
                    kind=self.out_kind_at(i),
                    is_response=self.output_is_response(),
                    origin_stage=self, parents=self.input_features)
            for i in range(self.num_outputs))
        return feats[0] if self.num_outputs == 1 else feats

    def out_kind_at(self, i: int) -> Type[FeatureType]:
        return self.out_kind

    def get_output(self) -> Any:
        if not self.input_features and not _is_generator(self):
            raise ValueError(f"{self.operation_name}: set_input before get_output")
        if self._output is None:
            self._output = self.make_output_features()
        return self._output

    @property
    def output_features(self) -> Tuple[Feature, ...]:
        out = self.get_output()
        return out if isinstance(out, tuple) else (out,)

    # ---- serialization -----------------------------------------------------
    def ctor_args(self) -> Dict[str, Any]:
        return dict(self._params)

    def to_json(self) -> Dict[str, Any]:
        from .serialization import stage_to_json
        return stage_to_json(self)

    def save_extra(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Hook for stages with nested state (e.g. SelectedModel's wrapped
        best model): return (json_dict, named arrays) persisted alongside the
        stage. Counterpart of ``load_extra``."""
        return {}, {}

    def load_extra(self, extra_json: Dict[str, Any], arrays: Dict[str, Any]) -> None:
        pass

    def __repr__(self):
        return f"{self.operation_name}({self.uid})"


def _is_generator(stage) -> bool:
    from .generator import FeatureGeneratorStage
    return isinstance(stage, FeatureGeneratorStage)


class Transformer(PipelineStage):
    """A fitted/stateless column function (≙ OpTransformer,
    OpPipelineStages.scala:526).

    ``transform(batch)`` returns the output Column (or tuple of Columns for
    multi-output stages).  If ``is_device_op`` is True and all inputs are
    device-resident, the workflow may trace it under jit.
    """

    is_device_op: bool = True
    # stages whose transform splits into host prologue + traceable body
    # (see transform_staged) — lets ScoreProgram fuse string-input stages
    # into device segments
    supports_staging: bool = False

    def transform(self, batch: ColumnBatch) -> Any:
        raise NotImplementedError

    def transform_staged(self, batch: ColumnBatch):
        """Host-prologue / device-body split for XLA program fusion.

        Returns ``(wire, fn)`` — ``wire`` maps names to compact arrays
        computed on host (token ids, vocab codes, packed presence; the ONLY
        data the body may read besides fitted constants) and ``fn(wire) →
        Column`` is jax-traceable — or None when no staged form applies to
        this batch.  ScoreProgram uses it to pull host-input transforms
        into fused device segments, so a whole vectorizer layer compiles
        into ONE XLA program instead of one dispatch per stage (SURVEY
        §2.6 P5; ≙ applyOpTransformations' single bulk row map,
        FitStagesUtil.scala:96).  The body must derive row counts from
        wire shapes, never close over them."""
        return None

    def input_columns(self, batch: ColumnBatch) -> List[Column]:
        return [batch[f.name] for f in self.input_features]

    def transform_batch(self, batch: ColumnBatch) -> ColumnBatch:
        out = self.transform(batch)
        feats = self.output_features
        if not isinstance(out, tuple):
            out = (out,)
        assert len(out) == len(feats), (
            f"{self.operation_name} returned {len(out)} columns for "
            f"{len(feats)} outputs")
        return batch.with_columns({f.name: c for f, c in zip(feats, out)})

    def transform_row(self, row: Dict[str, FeatureType]) -> Any:
        """Row-level transform for local scoring.  Default: build a length-1
        batch and take row 0 (stages may override with a direct value path)."""
        from ..columns import column_from_values, Column as _C
        import numpy as np
        cols = {}
        for f in self.input_features:
            v = row[f.name]
            val = v.value if isinstance(v, FeatureType) else v
            cols[f.name] = column_from_values(f.kind, [val])
        batch = ColumnBatch(cols, 1)
        out = self.transform(batch)
        feats = self.output_features
        if not isinstance(out, tuple):
            out = (out,)
        res = {f.name: c.row_value(0) for f, c in zip(feats, out)}
        return res if len(res) > 1 else next(iter(res.values()))


class TransformerModel(Transformer):
    """A fitted transformer produced by an Estimator (≙ the *Model classes).

    Fitted state lives in ``self.fitted`` — a dict of numpy/jax arrays and
    plain values, checkpointable as a pytree leaf set.
    """

    def __init__(self, **params):
        fitted = params.pop("fitted", None)
        super().__init__(**params)
        self.fitted: Dict[str, Any] = fitted or {}
        self.metadata: Dict[str, Any] = {}


class Estimator(PipelineStage):
    """Fits on a batch to produce a TransformerModel (≙ OpEstimator).

    ``fit`` must return a model wired to the same inputs/outputs.
    """

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        raise NotImplementedError

    def _finalize_model(self, model: TransformerModel) -> TransformerModel:
        model.uid = self.uid + "_model"
        model.input_features = self.input_features
        model._output = self._output  # share output feature nodes
        model.num_outputs = self.num_outputs
        return model


class LambdaTransformer(Transformer):
    """Wrap a batch-level function columns → Column (≙ Unary/Binary/...
    LambdaTransformer).  ``fn`` receives the input Columns positionally."""

    def __init__(self, fn: Callable[..., Column], out_kind: Type[FeatureType],
                 name: Optional[str] = None, is_device_op: bool = True, **params):
        super().__init__(**params)
        self.fn = fn
        self.out_kind = out_kind
        self.is_device_op = is_device_op
        self._name = name

    @property
    def operation_name(self) -> str:
        return self._name or f"Lambda[{getattr(self.fn, '__name__', 'fn')}]"

    def transform(self, batch: ColumnBatch) -> Column:
        return self.fn(*self.input_columns(batch))
