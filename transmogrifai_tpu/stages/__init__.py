from .base import (Estimator, PipelineStage, Transformer, TransformerModel)
from .generator import FeatureGeneratorStage

__all__ = ["PipelineStage", "Transformer", "Estimator", "TransformerModel",
           "FeatureGeneratorStage"]
