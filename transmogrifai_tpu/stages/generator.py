"""FeatureGeneratorStage — stage-0 of every DAG (reference:
features/src/main/scala/com/salesforce/op/stages/FeatureGeneratorStage.scala:67).

Wraps ``extract_fn: record → raw value`` plus an optional monoid aggregator and
event-time window.  Readers call ``extract_column`` over their record batches to
materialize the raw columns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Type

from ..columns import Column, column_from_values
from ..features import make_uid
from ..types import FeatureType
from .base import PipelineStage


def non_nullable_empty_value(kind: Type[FeatureType]):
    """The value a non-nullable kind takes when nothing was observed — the
    SINGLE definition of empty-aggregation semantics (≙ the reference's
    monoid zeros: SumRealNN → 0).  Prediction has no raw-empty analog."""
    from ..types import Prediction
    if issubclass(kind, Prediction):
        return {"prediction": 0.0}
    return 0.0


class FeatureGeneratorStage(PipelineStage):
    def __init__(self, name: str, kind: Type[FeatureType],
                 extract_fn: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 aggregator=None, extract_source: Optional[str] = None, **params):
        super().__init__(**params)
        self.name = name
        self.kind = kind
        self.out_kind = kind
        self.has_custom_extract = extract_fn is not None
        if extract_fn is None and extract_source:
            # rebuild from persisted source text — ``extract_source`` is a
            # Python expression over the record ``r`` (≙ the reference
            # recompiling the macro-captured source, FeatureBuilderMacros)
            extract_fn = eval(f"lambda r: ({extract_source})")  # noqa: S307
            self.has_custom_extract = True
        # default extractor = by-name lookup
        self.extract_fn = extract_fn or (lambda r, _n=name: r.get(_n))
        self.extract_source = extract_source
        from ..aggregators import default_aggregator
        self.aggregator = aggregator or default_aggregator(kind)

    @property
    def operation_name(self) -> str:
        return f"FeatureGenerator[{self.name}]"

    def output_name(self) -> str:
        return self.name

    def extract_column(self, records: Iterable[Dict[str, Any]]) -> Column:
        vals = [self.extract_fn(r) for r in records]
        if self.kind.non_nullable:
            # non-nullable features absent at scoring time (e.g. the response
            # on unlabeled data) take the monoid zero, matching the
            # reference's empty-aggregation semantics
            zero = non_nullable_empty_value(self.kind)
            vals = [zero if v is None else v for v in vals]
        return column_from_values(self.kind, vals)

    def aggregate_records(self, records: Sequence[Dict[str, Any]]) -> Any:
        """Monoid-aggregate the extracted values of pre-selected event records
        (the reader does the time-window selection; ≙ FeatureAggregator).
        Empty windows on non-nullable kinds take the monoid zero (the
        reference's SumRealNN-style empty aggregation → 0)."""
        out = self.aggregator.aggregate(
            [self.extract_fn(r) for r in records])
        if out is None and self.kind.non_nullable:
            return non_nullable_empty_value(self.kind)
        return out

    def extract_aggregated(self, grouped: Dict[Any, Sequence[Dict[str, Any]]],
                           cutoff_fn=None, is_response: bool = False) -> Column:
        """Event-time aggregation per key (≙ AggregateDataReader semantics):
        predictors aggregate events before the cutoff, responses after."""
        vals = []
        for _key, events in grouped.items():
            selected = []
            for ev in events:
                if cutoff_fn is None:
                    selected.append(ev)
                else:
                    before = cutoff_fn(ev)
                    if (not is_response and before) or (is_response and not before):
                        selected.append(ev)
            vals.append(self.aggregate_records(selected))
        return column_from_values(self.kind, vals)

    def ctor_args(self):
        return {"name": self.name, "kind": self.kind.__name__,
                "extract_source": self.extract_source}
