"""Stage / workflow-model serialization — the TPU-native re-design of
OpPipelineStageReaderWriter + OpWorkflowModelWriter (reference:
features/.../stages/OpPipelineStageReaderWriter.scala,
core/.../OpWorkflowModelWriter.scala:53-171, OpWorkflowModelReader.scala).

Format: one ``op-model.json`` manifest (uid, features, stages with ctor params,
result features, train params) + one ``params.npz`` holding every fitted array
keyed ``<stage_uid>/<name>`` — the orbax-style "pytree + manifest" layout
(SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features import Feature
from ..resilience import record_failure
from ..types import FEATURE_TYPES, FeatureType
from ..vector_meta import VectorMeta
from .base import PipelineStage, TransformerModel


def _is_array(v: Any) -> bool:
    if isinstance(v, (np.ndarray, np.generic)):
        return True
    import jax
    return isinstance(v, jax.Array)

# modules searched for stage classes on load (≙ ReflectionUtils.classForName)
_STAGE_MODULES = [
    "transmogrifai_tpu.stages.transformers",
    "transmogrifai_tpu.stages.generator",
    "transmogrifai_tpu.ops.numeric",
    "transmogrifai_tpu.ops.bucketizers",
    "transmogrifai_tpu.ops.categorical",
    "transmogrifai_tpu.ops.text",
    "transmogrifai_tpu.ops.text_specialized",
    "transmogrifai_tpu.ops.dates",
    "transmogrifai_tpu.ops.geo",
    "transmogrifai_tpu.ops.maps",
    "transmogrifai_tpu.ops.map_vectorizers",
    "transmogrifai_tpu.ops.collections",
    "transmogrifai_tpu.ops.combiner",
    "transmogrifai_tpu.models.linear",
    "transmogrifai_tpu.models.trees",
    "transmogrifai_tpu.models.external",
    "transmogrifai_tpu.preparators.sanity_checker",
    "transmogrifai_tpu.preparators.prediction_deindexer",
    "transmogrifai_tpu.selector",
]


def resolve_stage_class(class_name: str):
    for mod_name in _STAGE_MODULES:
        mod = importlib.import_module(mod_name)
        cls = getattr(mod, class_name, None)
        if cls is not None:
            return cls
    raise ValueError(f"unknown stage class {class_name!r}")


def _json_safe(v: Any, key: str = "") -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        # arrays nested inside dict/list fitted state (e.g. per-key splits)
        # round-trip as lists (0-d → scalar); top-level arrays go to
        # params.npz instead
        return _json_safe(v.tolist(), key)
    if isinstance(v, (list, tuple)):
        return [_json_safe(x, f"{key}[{i}]") for i, x in enumerate(v)]
    if isinstance(v, dict):
        return {str(k): _json_safe(x, f"{key}.{k}" if key else str(k))
                for k, x in v.items()}
    # unserializable (e.g. callable, arbitrary object) — dropped like the
    # reference drops non-ctor state, but observably: a silently-lossy save
    # is a corrupt reload waiting to happen
    record_failure("serialization", "swallowed",
                   f"dropped unserializable value of type {type(v).__name__}",
                   point="serialization.json_safe", key=key or "<anonymous>")
    return None


def stage_to_json(stage: PipelineStage) -> Dict[str, Any]:
    params: Dict[str, Any] = {}
    for k, v in stage.ctor_args().items():
        if callable(v):
            record_failure("serialization", "swallowed",
                           f"ctor param {k!r} is callable and cannot be "
                           "persisted", point="serialization.json_safe",
                           stage_uid=stage.uid, key=k)
            continue
        params[k] = _json_safe(v, key=f"{stage.uid}.{k}")
    d: Dict[str, Any] = {
        "uid": stage.uid,
        "className": type(stage).__name__,
        "params": params,
        "inputFeatures": [f.uid for f in stage.input_features],
    }
    if isinstance(stage, TransformerModel):
        fitted_json = {}
        for k, v in stage.fitted.items():
            if _is_array(v):
                continue  # arrays go to params.npz
            if isinstance(v, VectorMeta):
                fitted_json[k] = {"__vector_meta__": v.to_json()}
            else:
                fitted_json[k] = _json_safe(v, key=f"{stage.uid}.{k}")
        d["fittedJson"] = fitted_json
        d["metadata"] = _json_safe(stage.metadata, key=f"{stage.uid}.metadata")
    extra_json, _ = stage.save_extra()
    if extra_json:
        d["extra"] = extra_json
    return d


def stage_fitted_arrays(stage: PipelineStage) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(stage, TransformerModel):
        out.update({f"{stage.uid}/{k}": np.asarray(v)
                    for k, v in stage.fitted.items() if _is_array(v)})
    _, extra_arrays = stage.save_extra()
    out.update({f"{stage.uid}/{k}": np.asarray(v)
                for k, v in extra_arrays.items()})
    return out


def stage_from_json(d: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> PipelineStage:
    cls = resolve_stage_class(d["className"])
    params = dict(d.get("params") or {})
    params["uid"] = d["uid"]
    stage = cls(**params)
    if isinstance(stage, TransformerModel):
        fitted: Dict[str, Any] = {}
        for k, v in (d.get("fittedJson") or {}).items():
            if isinstance(v, dict) and "__vector_meta__" in v:
                fitted[k] = VectorMeta.from_json(v["__vector_meta__"])
            else:
                fitted[k] = v
        prefix = d["uid"] + "/"
        for k, v in arrays.items():
            if k.startswith(prefix):
                fitted[k[len(prefix):]] = v
        stage.fitted = fitted
        stage.metadata = dict(d.get("metadata") or {})
    if d.get("extra"):
        prefix = d["uid"] + "/"
        extra_arrays = {k[len(prefix):]: v for k, v in arrays.items()
                        if k.startswith(prefix)}
        stage.load_extra(d["extra"], extra_arrays)
    return stage


def feature_to_json(f: Feature) -> Dict[str, Any]:
    return {"name": f.name, "uid": f.uid, "type": f.kind.__name__,
            "isResponse": f.is_response,
            "originStage": f.origin_stage.uid if f.origin_stage else None,
            "parents": [p.uid for p in f.parents]}


def kind_by_name(name: str):
    for k, v in FEATURE_TYPES.items():
        if k == name or v.__name__ == name:
            return v
    raise ValueError(f"unknown feature type {name!r}")
