"""Generic feature transformers (reference: core/.../stages/impl/feature/
{MathTransformers,AliasTransformer,FilterTransformer,...}.scala and the unary
lambda bases).  All numeric ops are pure jnp functions over (values, mask)
pairs, so they trace under jit.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Type

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..types import (Binary, FeatureType, Integral, OPNumeric, Real, RealNN,
                     Text)
from .base import Transformer


def _as_float(col: Column):
    vals = jnp.asarray(col.values, dtype=jnp.float32)
    mask = None if col.mask is None else jnp.asarray(col.mask)
    return vals, mask


def _and_mask(m1, m2):
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    return m1 & m2


class AliasTransformer(Transformer):
    """Rename a feature (≙ AliasTransformer.scala)."""

    def __init__(self, name: str, **params):
        super().__init__(name=name, **params)
        self._alias = name

    def output_name(self) -> str:
        return self._alias

    def make_output_features(self):
        f = self.input_features[0]
        self.out_kind = f.kind
        return super().make_output_features()

    def transform(self, batch: ColumnBatch) -> Column:
        c = batch[self.input_features[0].name]
        return Column(c.kind, c.values, mask=c.mask, meta=c.meta)


class BinaryMathTransformer(Transformer):
    """Elementwise binary arithmetic on two numeric features
    (≙ MathTransformers.scala: AddTransformer, SubtractTransformer, ...).
    Empty values propagate: result is empty where either input is empty,
    except +/- which treat empty as identity like the reference."""

    in_kinds = (OPNumeric, OPNumeric)
    out_kind = Real

    OPS = {
        "plus": jnp.add, "minus": jnp.subtract,
        "multiply": jnp.multiply, "divide": jnp.divide,
    }

    def __init__(self, op: str, **params):
        super().__init__(op=op, **params)
        self.op = op

    @property
    def operation_name(self) -> str:
        return self.op

    def transform(self, batch: ColumnBatch) -> Column:
        a, b = self.input_columns(batch)
        va, ma = _as_float(a)
        vb, mb = _as_float(b)
        fn = self.OPS[self.op]
        if self.op in ("plus", "minus"):
            # treat empty as 0 (identity), present if either side present
            za = jnp.where(ma, va, 0.0) if ma is not None else va
            zb = jnp.where(mb, vb, 0.0) if mb is not None else vb
            out = fn(za, zb)
            mask = None
            if ma is not None or mb is not None:
                pa = ma if ma is not None else jnp.ones_like(za, dtype=bool)
                pb = mb if mb is not None else jnp.ones_like(zb, dtype=bool)
                mask = pa | pb
            return Column(Real, out, mask=mask)
        out = fn(va, vb)
        mask = _and_mask(ma, mb)
        if self.op == "divide":
            finite = jnp.isfinite(out)
            mask = finite if mask is None else (mask & finite)
        return Column(Real, out, mask=mask)


class UnaryMathTransformer(Transformer):
    """Elementwise unary math (abs, ceil, floor, round, exp, sqrt, log, power,
    scalar add/multiply) — ≙ MathTransformers.scala unary ops."""

    in_kinds = (OPNumeric,)
    out_kind = Real

    def __init__(self, op: str, scalar: Optional[float] = None, **params):
        super().__init__(op=op, scalar=scalar, **params)
        self.op = op
        self.scalar = scalar

    @property
    def operation_name(self) -> str:
        return self.op

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        v, m = _as_float(c)
        s = self.scalar
        fns: dict = {
            "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor,
            "round": jnp.round, "exp": jnp.exp, "sqrt": jnp.sqrt,
            "log": lambda x: jnp.log(x) / jnp.log(s if s else jnp.e),
            "power": lambda x: jnp.power(x, s),
            "addScalar": lambda x: x + s, "multiplyScalar": lambda x: x * s,
        }
        out = fns[self.op](v)
        finite = jnp.isfinite(out)
        m = finite if m is None else (m & finite)
        return Column(Real, out, mask=m)


class ExistsTransformer(Transformer):
    """feature → Binary presence flag (≙ ExistsTransformer)."""

    out_kind = Binary

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        if c.is_host_object():
            vals = np.array([v is not None and (not hasattr(v, "__len__") or len(v) > 0)
                             for v in c.values], dtype=bool)
            return Column(Binary, vals)
        n = len(c)
        m = c.mask if c.mask is not None else np.ones(n, dtype=bool)
        return Column(Binary, jnp.asarray(m))


class ToOccurTransformer(Transformer):
    """feature → RealNN 1.0/0.0 occurrence (≙ ToOccurTransformer)."""

    out_kind = RealNN

    def __init__(self, match_fn: Optional[Callable[[Any], bool]] = None, **params):
        super().__init__(**params)
        self.match_fn = match_fn

    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        if self.match_fn is not None or c.is_host_object():
            fn = self.match_fn or (lambda v: v is not None)
            if c.is_host_object():
                vals = np.array([1.0 if fn(v) else 0.0 for v in c.values], dtype=np.float32)
            else:
                m = c.mask if c.mask is not None else np.ones(len(c), bool)
                raw = np.asarray(c.values)
                vals = np.array([1.0 if (mm and fn(v)) else 0.0
                                 for v, mm in zip(raw, np.asarray(m))], dtype=np.float32)
            return Column(RealNN, vals)
        v = jnp.asarray(c.values, jnp.float32)
        m = c.mask if c.mask is not None else jnp.ones(len(c), bool)
        return Column(RealNN, jnp.where(jnp.asarray(m), (v != 0).astype(jnp.float32), 0.0))


class SubstringTransformer(Transformer):
    """Binary text op: does input2 contain input1 (≙ SubstringTransformer)."""

    in_kinds = (Text, Text)
    out_kind = Binary
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        a, b = self.input_columns(batch)
        vals, mask = [], []
        for x, y in zip(a.values, b.values):
            ok = x is not None and y is not None
            mask.append(ok)
            vals.append(bool(ok and (x.lower() in y.lower())))
        return Column(Binary, np.array(vals), mask=np.array(mask))


class ReplaceTransformer(Transformer):
    """Replace matching values (≙ ReplaceTransformer)."""

    is_device_op = False

    def __init__(self, match_value, replace_with, **params):
        super().__init__(match_value=match_value, replace_with=replace_with, **params)

    def make_output_features(self):
        self.out_kind = self.input_features[0].kind
        return super().make_output_features()

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        mv, rw = self.get("match_value"), self.get("replace_with")
        if c.is_host_object():
            vals = np.array([rw if v == mv else v for v in c.values], dtype=object)
            return Column(c.kind, vals)
        v = jnp.asarray(c.values)
        out = jnp.where(v == mv, jnp.asarray(rw, dtype=v.dtype), v)
        return Column(c.kind, out, mask=c.mask)
