"""Generic feature transformers (reference: core/.../stages/impl/feature/
{MathTransformers,AliasTransformer,FilterTransformer,...}.scala and the unary
lambda bases).  All numeric ops are pure jnp functions over (values, mask)
pairs, so they trace under jit.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Type

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..types import (Binary, FeatureType, Integral, OPNumeric, Real, RealNN,
                     Text)
from .base import Transformer


def _as_float(col: Column):
    vals = jnp.asarray(col.values, dtype=jnp.float32)
    mask = None if col.mask is None else jnp.asarray(col.mask)
    return vals, mask


def _host_values(col: Column) -> list:
    """Row python values with ONE device→host copy (``row_value`` per row
    would re-copy the whole array each time)."""
    if col.is_host_object():
        return list(col.values)
    vals = np.asarray(col.values)
    if col.mask is not None:
        m = np.asarray(col.mask)
        return [v.item() if mm else None for v, mm in zip(vals, m)]
    return [v.item() for v in vals]


def _and_mask(m1, m2):
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    return m1 & m2


class AliasTransformer(Transformer):
    """Rename a feature (≙ AliasTransformer.scala)."""

    def __init__(self, name: str, **params):
        super().__init__(name=name, **params)
        self._alias = name

    def output_name(self) -> str:
        return self._alias

    def make_output_features(self):
        f = self.input_features[0]
        self.out_kind = f.kind
        return super().make_output_features()

    def transform(self, batch: ColumnBatch) -> Column:
        c = batch[self.input_features[0].name]
        return Column(c.kind, c.values, mask=c.mask, meta=c.meta)


class BinaryMathTransformer(Transformer):
    """Elementwise binary arithmetic on two numeric features
    (≙ MathTransformers.scala: AddTransformer, SubtractTransformer, ...).
    Empty values propagate: result is empty where either input is empty,
    except +/- which treat empty as identity like the reference."""

    in_kinds = (OPNumeric, OPNumeric)
    out_kind = Real

    OPS = {
        "plus": jnp.add, "minus": jnp.subtract,
        "multiply": jnp.multiply, "divide": jnp.divide,
    }

    def __init__(self, op: str, **params):
        super().__init__(op=op, **params)
        self.op = op

    @property
    def operation_name(self) -> str:
        return self.op

    def transform(self, batch: ColumnBatch) -> Column:
        a, b = self.input_columns(batch)
        va, ma = _as_float(a)
        vb, mb = _as_float(b)
        fn = self.OPS[self.op]
        if self.op in ("plus", "minus"):
            # treat empty as 0 (identity), present if either side present
            za = jnp.where(ma, va, 0.0) if ma is not None else va
            zb = jnp.where(mb, vb, 0.0) if mb is not None else vb
            out = fn(za, zb)
            mask = None
            if ma is not None or mb is not None:
                pa = ma if ma is not None else jnp.ones_like(za, dtype=bool)
                pb = mb if mb is not None else jnp.ones_like(zb, dtype=bool)
                mask = pa | pb
            return Column(Real, out, mask=mask)
        out = fn(va, vb)
        mask = _and_mask(ma, mb)
        if self.op == "divide":
            finite = jnp.isfinite(out)
            mask = finite if mask is None else (mask & finite)
        return Column(Real, out, mask=mask)


class UnaryMathTransformer(Transformer):
    """Elementwise unary math (abs, ceil, floor, round, exp, sqrt, log, power,
    scalar add/multiply) — ≙ MathTransformers.scala unary ops."""

    in_kinds = (OPNumeric,)
    out_kind = Real

    def __init__(self, op: str, scalar: Optional[float] = None, **params):
        super().__init__(op=op, scalar=scalar, **params)
        self.op = op
        self.scalar = scalar

    @property
    def operation_name(self) -> str:
        return self.op

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        v, m = _as_float(c)
        s = self.scalar
        fns: dict = {
            "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor,
            "round": jnp.round, "exp": jnp.exp, "sqrt": jnp.sqrt,
            "log": lambda x: jnp.log(x) / jnp.log(s if s else jnp.e),
            "power": lambda x: jnp.power(x, s),
            "addScalar": lambda x: x + s, "multiplyScalar": lambda x: x * s,
        }
        out = fns[self.op](v)
        finite = jnp.isfinite(out)
        m = finite if m is None else (m & finite)
        return Column(Real, out, mask=m)


class ExistsTransformer(Transformer):
    """feature → Binary presence flag (≙ ExistsTransformer)."""

    out_kind = Binary

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        if c.is_host_object():
            vals = np.array([v is not None and (not hasattr(v, "__len__") or len(v) > 0)
                             for v in c.values], dtype=bool)
            return Column(Binary, vals)
        n = len(c)
        m = c.mask if c.mask is not None else np.ones(n, dtype=bool)
        return Column(Binary, jnp.asarray(m))


class ToOccurTransformer(Transformer):
    """feature → RealNN 1.0/0.0 occurrence (≙ ToOccurTransformer)."""

    out_kind = RealNN

    def __init__(self, match_fn: Optional[Callable[[Any], bool]] = None, **params):
        super().__init__(**params)
        self.match_fn = match_fn

    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        if self.match_fn is not None or c.is_host_object():
            fn = self.match_fn or (lambda v: v is not None)
            if c.is_host_object():
                vals = np.array([1.0 if fn(v) else 0.0 for v in c.values], dtype=np.float32)
            else:
                m = c.mask if c.mask is not None else np.ones(len(c), bool)
                raw = np.asarray(c.values)
                vals = np.array([1.0 if (mm and fn(v)) else 0.0
                                 for v, mm in zip(raw, np.asarray(m))], dtype=np.float32)
            return Column(RealNN, vals)
        v = jnp.asarray(c.values, jnp.float32)
        m = c.mask if c.mask is not None else jnp.ones(len(c), bool)
        return Column(RealNN, jnp.where(jnp.asarray(m), (v != 0).astype(jnp.float32), 0.0))


class SubstringTransformer(Transformer):
    """Binary text op: does input2 contain input1 (≙ SubstringTransformer)."""

    in_kinds = (Text, Text)
    out_kind = Binary
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        a, b = self.input_columns(batch)
        vals, mask = [], []
        for x, y in zip(a.values, b.values):
            ok = x is not None and y is not None
            mask.append(ok)
            vals.append(bool(ok and (x.lower() in y.lower())))
        return Column(Binary, np.array(vals), mask=np.array(mask))


class ReplaceTransformer(Transformer):
    """Replace matching values (≙ ReplaceTransformer)."""

    is_device_op = False

    def __init__(self, match_value, replace_with, **params):
        super().__init__(match_value=match_value, replace_with=replace_with, **params)

    def make_output_features(self):
        self.out_kind = self.input_features[0].kind
        return super().make_output_features()

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        mv, rw = self.get("match_value"), self.get("replace_with")
        if c.is_host_object():
            vals = np.array([rw if v == mv else v for v in c.values], dtype=object)
            return Column(c.kind, vals)
        v = jnp.asarray(c.values)
        out = jnp.where(v == mv, jnp.asarray(rw, dtype=v.dtype), v)
        return Column(c.kind, out, mask=c.mask)


class FilterTransformer(Transformer):
    """Keep values satisfying a predicate, else the default (≙
    FilterTransformer.scala:39-48: ``a => if (p(a)) a else default``).  The
    predicate is runtime state (like the reference's function arg) — it is
    not serialized; persisted pipelines should prefer declarative stages."""

    is_device_op = False

    def __init__(self, predicate_fn: Optional[Callable[[Any], bool]] = None,
                 default: Any = None, **params):
        super().__init__(default=default, **params)
        self.predicate_fn = predicate_fn or (lambda v: v is not None)

    def make_output_features(self):
        kind = self.input_features[0].kind
        if kind.non_nullable and self.get("default") is None:
            raise ValueError(
                f"FilterTransformer on non-nullable {kind.__name__} requires "
                "a non-None `default` (rows failing the predicate would "
                "otherwise produce empty values)")
        self.out_kind = kind
        return super().make_output_features()

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import column_from_values
        (c,) = self.input_columns(batch)
        default = self.get("default")
        rows = _host_values(c)
        out = [v if self.predicate_fn(v) else default for v in rows]
        return column_from_values(c.kind, out)


class FilterMap(Transformer):
    """Filter a map's keys by allow/block lists (≙ FilterMap.scala:45-55
    with MapPivotParams white/black key lists)."""

    is_device_op = False

    def __init__(self, white_list_keys: Sequence[str] = (),
                 black_list_keys: Sequence[str] = (),
                 clean_keys: bool = False, **params):
        super().__init__(white_list_keys=list(white_list_keys),
                         black_list_keys=list(black_list_keys),
                         clean_keys=clean_keys, **params)

    def make_output_features(self):
        self.out_kind = self.input_features[0].kind
        return super().make_output_features()

    def transform(self, batch: ColumnBatch) -> Column:
        (c,) = self.input_columns(batch)
        white = set(self.get("white_list_keys") or ())
        black = set(self.get("black_list_keys") or ())
        clean = self.get("clean_keys", False)

        def keep(k: str) -> bool:
            return (not white or k in white) and k not in black

        out = np.empty(len(c), object)
        for i, m in enumerate(c.values):
            m = m if isinstance(m, dict) else {}
            res = {}
            # clean BEFORE filtering so a blacklisted key cannot reappear in
            # cleaned form; sorted iteration makes key collisions after
            # cleaning deterministic (last sorted key wins)
            for k in sorted(m):
                ck = k.strip().lower() if clean else k
                if keep(ck):
                    res[ck] = m[k]
            out[i] = res
        return Column(c.kind, out)


class DropIndicesByTransformer(Transformer):
    """OPVector → OPVector dropping columns whose metadata matches
    (≙ DropIndicesByTransformer.scala:50-70: matchFn on
    OpVectorColumnMetadata selects columns to DROP).  Besides the callable,
    ``drop_null_indicators``/``drop_grouping`` give serializable shortcuts."""

    from ..types import OPVector as _V
    in_kinds = (_V,)
    out_kind = _V
    is_device_op = False

    def __init__(self, match_fn: Optional[Callable] = None,
                 drop_null_indicators: bool = False,
                 drop_grouping: Optional[str] = None, **params):
        super().__init__(drop_null_indicators=drop_null_indicators,
                         drop_grouping=drop_grouping, **params)
        self.match_fn = match_fn

    def _drops(self, cm) -> bool:
        from ..vector_meta import NULL_INDICATOR
        if self.match_fn is not None and self.match_fn(cm):
            return True
        if self.get("drop_null_indicators") and \
                cm.indicator_value == NULL_INDICATOR:
            return True
        g = self.get("drop_grouping")
        return g is not None and cm.grouping == g

    def transform(self, batch: ColumnBatch) -> Column:
        from ..types import OPVector
        (c,) = self.input_columns(batch)
        width = int(np.asarray(c.values).shape[1]) if len(c) or True else 0
        if c.meta is not None:
            keep = [i for i, cm in enumerate(c.meta.columns)
                    if not self._drops(cm)]
            # persist the resolved slice: row-level transforms and reloaded
            # models see plain vectors without metadata (the reference reads
            # vectorMetadata from the input schema once, at fit time)
            self.set("kept_indices", keep)
            self.set("resolved_input_width", len(c.meta.columns))
            meta = c.meta.select(keep, name=self.output_features[0].name)
        else:
            keep = self.get("kept_indices")
            if keep is None:
                raise ValueError(
                    "DropIndicesByTransformer requires vector metadata on "
                    "its input (or a prior batch transform that resolved "
                    "the kept indices)")
            expected = self.get("resolved_input_width")
            if expected is not None and width != expected:
                raise ValueError(
                    f"DropIndicesByTransformer: input width {width} does not "
                    f"match the width {expected} the kept indices were "
                    "resolved against — upstream vector layout changed; "
                    "re-apply on a metadata-bearing batch")
            meta = None
        vals = jnp.asarray(c.values)[:, np.asarray(keep, np.int64)]
        return Column(OPVector, vals, meta=meta)


class OPCollectionTransformer(Transformer):
    """Lift a unary value-level transformer over a list/set/map feature
    (≙ OPCollectionTransformer.scala:67-83: empty in → empty out, else the
    inner transform applied per element/value)."""

    is_device_op = False

    def __init__(self, transformer: Transformer,
                 out_kind: Optional[Type[FeatureType]] = None, **params):
        super().__init__(**params)
        self.transformer = transformer
        self._out_kind_override = out_kind

    def make_output_features(self):
        self.out_kind = self._out_kind_override or self.input_features[0].kind
        return super().make_output_features()

    def _ensure_inner_wired(self):
        if not self.transformer.input_features:
            from ..features import Feature
            in_kind = (self.transformer.in_kinds[0]
                       if self.transformer.in_kinds else Text)
            self.transformer.set_input(
                Feature("_elem", in_kind, False, None, parents=()))

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import ColumnBatch as _CB, column_from_values
        (c,) = self.input_columns(batch)
        self._ensure_inner_wired()
        f = self.transformer.input_features[0]
        # flatten every element of the whole batch into ONE inner transform
        # (per-element 1-row batches would pay a stage dispatch per value)
        flat: list = []
        specs: list = []            # per row: (tag, keys/None/len)
        for v in c.values:
            if v is None:
                specs.append(("none", None))
            elif isinstance(v, dict):
                keys = sorted(v)
                specs.append(("dict", keys))
                flat.extend(v[k] for k in keys)
            elif isinstance(v, (set, frozenset)):
                items = sorted(v, key=str)
                specs.append(("set", len(items)))
                flat.extend(items)
            elif isinstance(v, (list, tuple)):
                specs.append(("list", len(v)))
                flat.extend(v)
            else:
                specs.append(("scalar", 1))
                flat.append(v)
        if flat:
            col = column_from_values(f.kind, flat)
            res_col = self.transformer.transform(_CB({f.name: col}, len(flat)))
            results = [res_col.row_value(i).value for i in range(len(flat))]
        else:
            results = []
        out = np.empty(len(c), object)
        pos = 0
        for i, (tag, spec) in enumerate(specs):
            if tag == "none":
                out[i] = None
            elif tag == "dict":
                out[i] = {k: results[pos + j] for j, k in enumerate(spec)}
                pos += len(spec)
            elif tag == "set":
                out[i] = set(results[pos:pos + spec])
                pos += spec
            elif tag == "list":
                out[i] = list(results[pos:pos + spec])
                pos += spec
            else:
                out[i] = results[pos]
                pos += 1
        return Column(self.out_kind, out)


class TextListNullTransformer(Transformer):
    """N TextList features → OPVector of per-feature null indicators
    (≙ TextListNullTransformer.scala:39-58 — null tracking for hashed text
    kept outside the hashing vectorizer)."""

    from ..types import OPVector as _V2
    out_kind = _V2
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import indicator_2d
        from ..types import OPVector
        from ..vector_meta import NULL_INDICATOR, VectorColumnMeta, VectorMeta
        blocks = []
        cols_meta = []
        for f in self.input_features:
            vals = batch[f.name].values
            blocks.append(indicator_2d(
                v is None or (hasattr(v, "__len__") and len(v) == 0)
                for v in vals))
            cols_meta.append(VectorColumnMeta(
                f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        arr = np.concatenate(blocks, axis=1)
        meta = VectorMeta(self.output_name(), cols_meta)
        return Column(OPVector, jnp.asarray(arr), meta=meta)
