"""RecordInsightsLOCO — per-row prediction explanations (reference:
core/src/main/scala/com/salesforce/op/stages/impl/insights/
RecordInsightsLOCO.scala:100-240: computeDiff:147, aggregateDiffs:186).

Leave-one-covariate-out: re-score each row with each raw-feature group's
columns replaced by zero and record the prediction shift.  On TPU this is one
batched forward pass per raw feature (groups of derived columns aggregate
together, as the reference aggregates text/date indices per raw feature) —
[G, N, D] masking is pure XLA, no per-row loop.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .columns import Column, ColumnBatch
from .stages.base import Transformer
from .types import OPVector, Prediction, TextMap


class RecordInsightsLOCO(Transformer):
    """Inputs: (features OPVector); params carry the fitted model stage.
    Output: TextMap of rawFeatureName → json [[col, diff...], ...] like the
    reference's RecordInsightsParser format.
    """

    in_kinds = (OPVector,)
    out_kind = TextMap
    is_device_op = False

    def __init__(self, model=None, top_k: int = 20, strategy: str = "abs", **params):
        super().__init__(top_k=top_k, strategy=strategy, **params)
        self.model = model

    def transform(self, batch: ColumnBatch) -> Column:
        (vec_f,) = self.input_features
        col = batch[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        meta = col.meta
        groups: Dict[str, List[int]] = {}
        if meta is not None and meta.size == d:
            groups = meta.index_by_parent()
        else:
            groups = {f"f_{i}": [i] for i in range(d)}

        base = self._score(X)                                # [N]
        diffs: Dict[str, np.ndarray] = {}
        for parent, idxs in groups.items():
            Xm = X.copy()
            Xm[:, idxs] = 0.0
            diffs[parent] = base - self._score(Xm)           # [N]

        top_k = int(self.get("top_k", 20))
        strategy = self.get("strategy", "abs")
        names = list(diffs)
        D = np.stack([diffs[p] for p in names], axis=1)      # [N, G]
        if strategy == "positive":
            order = np.argsort(-D, axis=1)
        elif strategy == "negative":
            order = np.argsort(D, axis=1)
        else:
            order = np.argsort(-np.abs(D), axis=1)
        out = np.empty(n, dtype=object)
        k = min(top_k, len(names))
        for i in range(n):
            row = {}
            for j in order[i, :k]:
                row[names[j]] = float(D[i, j])
            out[i] = {p: json.dumps([[p, v]]) for p, v in row.items()}
        return Column(TextMap, out)

    def _score(self, X: np.ndarray) -> np.ndarray:
        pred = self.model.predict_arrays(X)
        prob = pred.get("probability")
        if prob is not None:
            p = np.asarray(prob)
            return p[:, -1] if p.ndim == 2 else p
        return np.asarray(pred["prediction"], dtype=np.float64)
