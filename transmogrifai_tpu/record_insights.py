"""RecordInsightsLOCO — per-row prediction explanations (reference:
core/src/main/scala/com/salesforce/op/stages/impl/insights/
RecordInsightsLOCO.scala:100-240: computeDiff:147, aggregateDiffs:186).

Leave-one-covariate-out: re-score each row with each raw-feature group's
columns zeroed and record the prediction shift.  Derived columns aggregate
per raw parent feature, and date-circle columns (descriptor ``sin(p)`` /
``cos(p)``) aggregate per (parent, time-period) — ≙ the reference's
``aggregateDiffs`` date handling (RecordInsightsLOCO.scala:186).

On a device-scorable model the whole computation is ONE jitted XLA program:
the base forward plus a ``lax.map`` over the [G, D] group masks (each step a
masked forward on the HBM-resident matrix — no [G, N, D] materialisation and
no host copies), followed by per-row top-K selection on device.  Only the
[N, K] winning (index, diff) pairs cross the host link.  Host-only models
(e.g. wrapped external estimators) fall back to an equivalent numpy loop
with the same output, so the two paths are parity-testable.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .columns import Column, ColumnBatch
from .stages.base import Estimator, Transformer, TransformerModel
from .types import OPVector, Prediction, TextMap

_PERIODS = ("HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear",
            "WeekOfMonth", "WeekOfYear", "MonthOfYear", "QuarterOfYear")


def _group_key(col_meta) -> str:
    """Raw-feature aggregation key; date-circle columns split per period."""
    parent = col_meta.parent_feature_name
    desc = col_meta.descriptor_value or ""
    if desc.startswith(("sin(", "cos(")) and desc.endswith(")"):
        return f"{parent}_{desc[4:-1]}"
    for p in _PERIODS:
        if desc == p or desc.startswith(p + "_"):
            return f"{parent}_{p}"
    return parent


class RecordInsightsLOCO(Transformer):
    """Inputs: (features OPVector); params carry the fitted model stage.
    Output: TextMap of groupKey → json [[col, diff...], ...] like the
    reference's RecordInsightsParser format.
    """

    in_kinds = (OPVector,)
    out_kind = TextMap
    is_device_op = False

    def __init__(self, model=None, top_k: int = 20, strategy: str = "abs", **params):
        super().__init__(top_k=top_k, strategy=strategy, **params)
        self.model = model
        # weak-keyed on the MODEL: entries (compiled program + device mask
        # buffer) die with the model they were traced against, so swapping
        # self.model never pins stale weights + masks in HBM
        import weakref
        self._compiled: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()

    # -- grouping ---------------------------------------------------------
    def _groups(self, meta, d: int) -> Dict[str, List[int]]:
        if meta is not None and meta.size == d:
            out: Dict[str, List[int]] = {}
            for c in meta.columns:
                out.setdefault(_group_key(c), []).append(c.index)
            return out
        if meta is not None:
            raise ValueError(
                f"RecordInsightsLOCO: vector meta covers {meta.size} columns "
                f"but the matrix has {d}")
        return {f"f_{i}": [i] for i in range(d)}

    # -- scoring ----------------------------------------------------------
    def _device_score_fn(self) -> Optional[Callable]:
        m = self.model
        sup = getattr(m, "supports_device_scores", None)
        if m is None or sup is None or not sup():
            return None
        # close over a WEAK ref: the compiled program is stored as a
        # WeakKeyDictionary VALUE keyed by the model — a strong closure on
        # the model would make the entry self-referential and immortal
        # (the jitted program retains the weight arrays as jaxpr constants;
        # it does not need the model object after tracing)
        import weakref
        mref = weakref.ref(m)

        def score(Xd):
            mm = mref()
            if mm is None:  # pragma: no cover — entry dies with the model
                raise RuntimeError("model was collected")
            out = mm.device_scores(Xd, full=False)
            s = out.get("scores")
            if s is not None:
                return s
            prob = out.get("probability")
            if prob is not None:
                return prob[:, -1]
            return out["prediction"]

        return score

    def _host_score(self, X: np.ndarray) -> np.ndarray:
        pred = self.model.predict_arrays(X)
        prob = pred.get("probability")
        if prob is not None:
            p = np.asarray(prob)
            return p[:, -1] if p.ndim == 2 else p
        return np.asarray(pred["prediction"], dtype=np.float64)

    # -- the LOCO programs ------------------------------------------------
    def _device_topk(self, xv, masks: np.ndarray, k: int,
                     strategy: str) -> Tuple[np.ndarray, np.ndarray]:
        """One jitted program: masked forwards (lax.map over groups), diffs,
        per-row top-K — returns host [N, K] (group index, diff)."""
        import jax
        import jax.numpy as jnp

        from .columns import to_device_f32

        score = self._device_score_fn()
        d = int(xv.shape[1])
        # inner key per model: mask CONTENTS included because the same stage
        # may see batches with different vector meta at identical shapes
        inner = self._compiled.setdefault(self.model, {})
        key = (strategy, k, d, len(masks), hash(masks.tobytes()))
        ent = inner.get(key)
        if ent is not None:
            prog, Md = ent
        else:
            while len(inner) >= 4:   # bound program+mask residency per model
                inner.pop(next(iter(inner)))
            def loco(Xd, Md):
                base = score(Xd)                               # [N]

                def one(m):
                    return base - score(Xd * m[None, :])       # [N]

                Dn = jax.lax.map(one, Md).T                    # [N, G]
                if strategy == "positive":
                    rank = Dn
                elif strategy == "negative":
                    rank = -Dn
                else:
                    rank = jnp.abs(Dn)
                _, idx = jax.lax.top_k(rank, k)                # [N, K]
                val = jnp.take_along_axis(Dn, idx, axis=1)
                # the [N, K] pulls are the only host traffic and the link is
                # slow: ship indices in the narrowest dtype that fits G
                # (meta-less fallbacks make one group PER COLUMN, so G can
                # exceed int16)
                itype = jnp.int16 if Md.shape[0] <= 0x7FFF else jnp.int32
                return idx.astype(itype), val

            prog = jax.jit(loco)
            # masks depend only on (grouping, d) — cache the device copy
            # with the program so repeat transforms ship nothing but X
            Md = jnp.asarray(masks)
            inner[key] = (prog, Md)
        Xd = to_device_f32(xv)
        idx, val = jax.device_get(prog(Xd, Md))
        return idx.astype(np.int64), val.astype(np.float64)

    def _host_topk(self, X: np.ndarray, masks: np.ndarray, k: int,
                   strategy: str) -> Tuple[np.ndarray, np.ndarray]:
        base = self._host_score(X)
        G = masks.shape[0]
        Dn = np.empty((len(X), G), np.float64)
        for g in range(G):
            Dn[:, g] = base - self._host_score(X * masks[g][None, :])
        if strategy == "positive":
            rank = Dn
        elif strategy == "negative":
            rank = -Dn
        else:
            rank = np.abs(Dn)
        # argpartition + per-row ordering of just the K winners
        part = np.argpartition(-rank, k - 1, axis=1)[:, :k]
        sub = np.take_along_axis(rank, part, axis=1)
        order = np.argsort(-sub, axis=1, kind="stable")
        idx = np.take_along_axis(part, order, axis=1)
        val = np.take_along_axis(Dn, idx, axis=1)
        return idx, val

    # -- stage ------------------------------------------------------------
    def transform(self, batch: ColumnBatch) -> Column:
        (vec_f,) = self.input_features
        col = batch[vec_f.name]
        xv = col.values
        n, d = int(xv.shape[0]), int(xv.shape[1])
        groups = self._groups(col.meta, d)
        names = list(groups)
        G = len(names)
        k = max(1, min(int(self.get("top_k", 20)), G))
        strategy = self.get("strategy", "abs")

        masks = np.ones((G, d), np.float32)
        for gi, idxs in enumerate(groups.values()):
            masks[gi, idxs] = 0.0

        if self._device_score_fn() is not None:
            idx, val = self._device_topk(xv, masks, k, strategy)
        else:
            X = np.asarray(xv, dtype=np.float32)
            idx, val = self._host_topk(X, masks, k, strategy)

        return Column(TextMap, _assemble_maps(idx, val, names, n))


def _assemble_maps(idx: np.ndarray, val: np.ndarray,
                   names: Sequence[str], n: int) -> np.ndarray:
    """[N, K] (group index, diff) → object array of per-row
    {name: '[["name", diff]]'} maps.  The native formatter does it in one C
    pass (interned names, snprintf payloads); the numpy fallback builds the
    payload strings with C-speed np.char ops and only loops for the dicts."""
    # fast paths need json-safe names AND finite diffs (%g / str() would emit
    # bare nan/inf, which json.loads rejects — json.dumps' NaN does parse)
    clean = (all(_json_plain(p) for p in names)
             and bool(np.isfinite(val).all()))
    if clean:
        from .native import load
        native = load("locofmt")
        if native is not None:
            return native.assemble(np.ascontiguousarray(idx, np.int64),
                                   np.ascontiguousarray(val, np.float64),
                                   list(names))
    names_u = np.asarray(names)                            # unicode [G]
    nm = names_u[idx]                                      # [N, K]
    if not clean:
        payload = np.frompyfunc(_entry_json, 2, 1)(nm, val)
    else:
        val_str = val.astype(np.str_)                      # full-width repr
        payload = np.char.add(
            np.char.add(np.char.add('[["', nm), '", '),
            np.char.add(val_str, "]]"))
    out = np.empty(n, dtype=object)
    out[:] = [dict(zip(a, b))
              for a, b in zip(nm.tolist(), payload.tolist())]
    return out


def _json_plain(name: str) -> bool:
    """True when the name needs no JSON escaping (quotes, backslashes, and
    control characters all do)."""
    return '"' not in name and "\\" not in name and name.isprintable()


def _entry_json(name: str, diff: float) -> str:
    """``[[name, diff]]`` — the reference's RecordInsightsParser payload."""
    diff = float(diff)
    if not _json_plain(name) or not np.isfinite(diff):
        return json.dumps([[name, diff]])   # NaN/Infinity parse under json
    return f'[["{name}", {diff}]]'


# ---------------------------------------------------------------------------
# RecordInsightsCorr (reference: core/src/main/scala/com/salesforce/op/
# stages/impl/insights/RecordInsightsCorr.scala:95-160 fitFn/transformFn,
# NormType:165-205, Normalizer:210-225)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _corr_fit_program_factory(spearman: bool):
    # lru_cache'd so every fit with the same correlation type reuses ONE
    # jax.jit wrapper — a fresh wrapper per call would re-trace each fit
    # even though jit's own cache keys on the wrapper identity
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fit(X, P):
        """One fused pass over the feature matrix and the score columns:
        per-feature min/max/mean/var (the Normalizer moments) plus the
        [P, D] correlation of every feature with every score column —
        ≙ Statistics.corr over the joined (scores ++ features) RDD
        (RecordInsightsCorr.scala:104-118), as a single XLA program."""
        Xf = X.astype(jnp.float32)
        Pf = P.astype(jnp.float32)
        mn = jnp.min(Xf, axis=0)
        mx = jnp.max(Xf, axis=0)
        mean = jnp.mean(Xf, axis=0)
        var = jnp.var(Xf, axis=0, ddof=1)
        if spearman:
            from .preparators.sanity_checker import _rank_transform
            Xc_src, Pc_src = _rank_transform(Xf), _rank_transform(Pf)
        else:
            Xc_src, Pc_src = Xf, Pf
        Xc = Xc_src - jnp.mean(Xc_src, axis=0)
        Pc = Pc_src - jnp.mean(Pc_src, axis=0)
        xsd = jnp.sqrt(jnp.sum(Xc * Xc, axis=0))
        psd = jnp.sqrt(jnp.sum(Pc * Pc, axis=0))
        corr = (Pc.T @ Xc) / jnp.maximum(psd[:, None] * xsd[None, :], 1e-12)
        return jnp.stack([mn, mx, mean, var]), corr

    return fit


def _scores_matrix(col: Column) -> np.ndarray:
    """[N, P] score columns from an OPVector or Prediction column (the
    reference requires regression outputs pre-packed as a 1-column vector;
    Prediction columns unpack here instead)."""
    vals = col.values
    if isinstance(vals, dict):
        v = vals.get("probability", vals.get("prediction"))
        v = np.asarray(v)
        return v if v.ndim == 2 else v[:, None]
    v = np.asarray(vals) if not hasattr(vals, "ndim") else vals
    return v if v.ndim == 2 else v[:, None]


class RecordInsightsCorr(Estimator):
    """Correlation-based record insights (≙ RecordInsightsCorr.scala:56).

    Inputs: (prediction OPVector/Prediction, features OPVector).  Fit
    computes the [P, D] score↔feature correlations plus the Normalizer
    moments in ONE device program; the model's transform emits, per record,
    the top-K features by |corr × normalized value| for each score column
    as a TextMap (RecordInsightsParser payload shape: name →
    [[scoreIndex, importance], ...]).

    Superseded by RecordInsightsLOCO in the reference itself (LOCO explains
    the actual fitted model, not a linear correlate) but included for
    parity; norm_type ∈ {minmax, znorm, minmax_centered},
    correlation_type ∈ {pearson, spearman}.
    """

    out_kind = TextMap
    is_device_op = False

    def __init__(self, top_k: int = 20, norm_type: str = "minmax",
                 correlation_type: str = "pearson", **params):
        super().__init__(top_k=top_k, norm_type=norm_type,
                         correlation_type=correlation_type, **params)

    def fit(self, batch: ColumnBatch):
        pred_f, vec_f = self.input_features
        X = batch[vec_f.name].values
        P = _scores_matrix(batch[pred_f.name])
        import jax.numpy as jnp
        spearman = self.get("correlation_type", "pearson") == "spearman"
        stats, corr = _corr_fit_program_factory(spearman)(
            jnp.asarray(X) if not hasattr(X, "dtype") else X,
            jnp.asarray(P))
        mn, mx, mean, var = np.asarray(stats, np.float64)
        norm_type = self.get("norm_type", "minmax")
        if norm_type == "minmax":
            s1, s2, offset = mn, mx - mn, 0.0
        elif norm_type == "znorm":
            s1, s2, offset = mean, np.sqrt(var), 0.0
        elif norm_type == "minmax_centered":
            s1, s2, offset = mn, (mx - mn) / 2.0, 1.0
        else:
            raise ValueError(f"unknown norm_type {norm_type!r}")
        model = RecordInsightsCorrModel(fitted={
            "corr": np.asarray(corr, np.float64), "s1": s1, "s2": s2,
            "offset": float(offset)}, top_k=int(self.get("top_k", 20)))
        return self._finalize_model(model)


@functools.lru_cache(maxsize=None)
def _corr_topk_program():
    # module-level (one jit wrapper for the process) so repeat transforms hit
    # jit's compile cache instead of re-tracing under a fresh wrapper per call
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("k",))
    def topk(X, corr, s1, s2, offset, *, k):
        Xn = jnp.where(s2 == 0.0, 0.0,
                       (X.astype(jnp.float32) - s1) / jnp.where(
                           s2 == 0.0, 1.0, s2) - offset)

        def per_pred(c):
            imp = Xn * c[None, :]                     # [N, D]
            _, idx = jax.lax.top_k(jnp.abs(imp), k)   # [N, K]
            return idx, jnp.take_along_axis(imp, idx, axis=1)

        # P is small (1-2 score columns); sequential map keeps the
        # working set at one [N, D] importance block
        return jax.lax.map(per_pred, corr)

    return topk


class RecordInsightsCorrModel(TransformerModel):
    out_kind = TextMap
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        _, vec_f = self.input_features
        col = batch[vec_f.name]
        xv = col.values
        n, d = int(xv.shape[0]), int(xv.shape[1])
        meta = col.meta
        names = (meta.column_names() if meta is not None and meta.size == d
                 else [f"f_{i}" for i in range(d)])
        corr = self.fitted["corr"]
        k = max(1, min(int(self.get("top_k", 20)), d))

        import jax.numpy as jnp

        idx, val = _corr_topk_program()(
            xv if hasattr(xv, "dtype") else jnp.asarray(xv),
            jnp.asarray(corr, jnp.float32),
            jnp.asarray(self.fitted["s1"], jnp.float32),
            jnp.asarray(self.fitted["s2"], jnp.float32),
            jnp.float32(self.fitted["offset"]), k=k)
        idx = np.asarray(idx)                              # [P, N, K]
        val = np.asarray(val, np.float64)
        P = idx.shape[0]
        out = np.empty(n, dtype=object)
        names_arr = np.asarray(names)
        for i in range(n):
            row: Dict[str, str] = {}
            ins: Dict[str, List] = {}
            for p in range(P):
                for name, v in zip(names_arr[idx[p, i]], val[p, i]):
                    ins.setdefault(str(name), []).append([p, float(v)])
            for name, pairs in ins.items():
                row[name] = json.dumps(pairs)
            out[i] = row
        return Column(TextMap, out)
