"""Generic helpers (≙ the reference utils module)."""
