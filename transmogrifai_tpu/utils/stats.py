"""Contingency statistics + mergeable streaming histogram (reference:
utils/src/main/scala/com/salesforce/op/utils/stats/OpStatistics.scala:188-345
and utils/src/main/java/com/salesforce/op/utils/stats/StreamingHistogram.java:36).

Convention matches the reference: a contingency matrix has one ROW per feature
choice and one COLUMN per label value."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# contingency statistics (≙ OpStatistics)
# ---------------------------------------------------------------------------

def _igamc(a: float, x: float) -> float:
    """Regularized upper incomplete gamma Q(a, x) — series expansion for
    x < a+1, modified-Lentz continued fraction otherwise (the classical
    numerics; |err| ~ 1e-14).  Stdlib-only on purpose: scipy's import alone
    costs ~2.6 s on the 1-core bench host, and this p-value is the only
    thing the hot path needed it for."""
    import math
    if x <= 0.0 or a <= 0.0:
        return 1.0
    norm = math.exp(-x + a * math.log(x) - math.lgamma(a))
    if x < a + 1.0:
        ap, term, total = a, 1.0 / a, 1.0 / a
        for _ in range(500):
            ap += 1.0
            term *= x / ap
            total += term
            if abs(term) < abs(total) * 1e-16:
                break
        return max(0.0, min(1.0, 1.0 - total * norm))
    tiny = 1e-300
    b = x + 1.0 - a
    c = 1.0 / tiny
    d = 1.0 / b
    h = d
    for i in range(1, 500):
        an = -i * (i - a)
        b += 2.0
        d = an * d + b
        if abs(d) < tiny:
            d = tiny
        c = b + an / c
        if abs(c) < tiny:
            c = tiny
        d = 1.0 / d
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-16:
            break
    return max(0.0, min(1.0, norm * h))


def chi2_sf(chi2: float, dof: int) -> float:
    """Chi-squared survival function P[X >= chi2] = Q(dof/2, chi2/2).

    ``dof <= 0`` matches scipy's convention: the distribution is a point
    mass at 0, so the survival probability is 0.0 for any chi2 > 0 and 1.0
    at (or below) 0 — _igamc's blanket ``a <= 0 → 1.0`` would report the
    least-significant possible p-value for a degenerate table."""
    if dof <= 0:
        return 1.0 if chi2 <= 0.0 else 0.0
    return _igamc(dof / 2.0, chi2 / 2.0)


def chi_squared_test(contingency: np.ndarray) -> Tuple[float, float, float]:
    """(chi2 statistic, p-value, Cramér's V) on a contingency matrix with
    empty rows/cols filtered (≙ chiSquaredTest, OpStatistics.scala:188)."""
    obs = np.asarray(contingency, dtype=np.float64)
    obs = obs[obs.sum(axis=1) > 0][:, obs.sum(axis=0) > 0]
    if obs.size == 0 or min(obs.shape) < 2:
        return float("nan"), float("nan"), float("nan")
    n = obs.sum()
    expected = np.outer(obs.sum(axis=1), obs.sum(axis=0)) / n
    chi2 = float(((obs - expected) ** 2 / np.maximum(expected, 1e-12)).sum())
    dof = (obs.shape[0] - 1) * (obs.shape[1] - 1)
    p = chi2_sf(chi2, dof)
    k = min(obs.shape) - 1
    v = float(np.sqrt(chi2 / (n * max(k, 1))))
    return chi2, p, v


def pointwise_mutual_info(contingency: np.ndarray
                          ) -> Tuple[Dict[str, List[float]], float]:
    """(label → per-choice PMI values in log2, total mutual information)
    (≙ OpStatistics.mutualInfo, OpStatistics.scala:234: zeros where the cell
    or a margin is empty)."""
    obs = np.asarray(contingency, dtype=np.float64)
    n = obs.sum()
    row_sum = obs.sum(axis=1)            # per choice
    col_sum = obs.sum(axis=0)            # per label
    with np.errstate(divide="ignore", invalid="ignore"):
        pmi = np.log2(np.maximum(obs, 1e-99) * n
                      / np.outer(row_sum, col_sum))
    zero = (obs == 0) | (row_sum[:, None] == 0) | (col_sum[None, :] == 0)
    pmi = np.where(zero, 0.0, pmi)
    mi = float(np.sum(pmi * obs) / n) if n > 0 else float("nan")
    pmi_map = {str(j): [float(x) for x in pmi[:, j]]
               for j in range(obs.shape[1])}
    return pmi_map, mi


def max_confidences(contingency: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-choice (max rule confidence, support) — confidence of the rule
    "choice i ⇒ label argmax" (≙ OpStatistics.maxConfidences,
    OpStatistics.scala:280)."""
    obs = np.asarray(contingency, dtype=np.float64)
    row_sum = obs.sum(axis=1)
    total = row_sum.sum()
    supports = row_sum / total if total > 0 else np.zeros_like(row_sum)
    conf = np.where(row_sum > 0, obs.max(axis=1) / np.maximum(row_sum, 1e-99),
                    0.0)
    return conf, supports


@dataclass
class ContingencyStats:
    """≙ OpStatistics.ContingencyStats."""

    cramers_v: float = float("nan")
    chi_squared_stat: float = float("nan")
    p_value: float = float("nan")
    pointwise_mutual_info: Dict[str, List[float]] = field(default_factory=dict)
    mutual_info: float = float("nan")
    max_confidences: List[float] = field(default_factory=list)
    supports: List[float] = field(default_factory=list)

    def to_json(self) -> Dict:
        return {
            "cramersV": self.cramers_v,
            "chiSquaredStat": self.chi_squared_stat,
            "pValue": self.p_value,
            "pointwiseMutualInfo": self.pointwise_mutual_info,
            "mutualInfo": self.mutual_info,
            "maxRuleConfidences": self.max_confidences,
            "supports": self.supports,
        }


def contingency_stats(contingency: np.ndarray) -> ContingencyStats:
    """All contingency-derived stats (≙ OpStatistics.contingencyStats:300)."""
    obs = np.asarray(contingency, dtype=np.float64)
    if obs.size == 0 or obs.sum() == 0:
        return ContingencyStats()
    chi2, p, v = chi_squared_test(obs)
    pmi, mi = pointwise_mutual_info(obs)
    conf, supp = max_confidences(obs)
    return ContingencyStats(
        cramers_v=v, chi_squared_stat=chi2, p_value=p,
        pointwise_mutual_info=pmi, mutual_info=mi,
        max_confidences=[float(c) for c in conf],
        supports=[float(s) for s in supp])


# ---------------------------------------------------------------------------
# mergeable streaming histogram (≙ StreamingHistogram.java — Ben-Haim/Tom-Tov)
# ---------------------------------------------------------------------------

class StreamingHistogram:
    """Ben-Haim/Tom-Tov streaming histogram: a bounded set of (centroid,
    count) bins maintained by closest-pair merging.  ``merge`` combines
    sketches built independently (shards / stream micro-batches) without a
    shared binning — the property fixed-range ``np.histogram`` lacks
    (≙ StreamingHistogram.java:36, StreamingHistogramBuilder:120, merge:269)."""

    def __init__(self, max_bins: int = 64):
        self.max_bins = int(max_bins)
        self._points: List[List[float]] = []   # sorted [centroid, count]

    # -- updates -----------------------------------------------------------
    def update(self, p: float, count: float = 1.0) -> "StreamingHistogram":
        if not np.isfinite(p):
            return self
        self._insert(float(p), float(count))
        self._compress()
        return self

    def update_all(self, values) -> "StreamingHistogram":
        values = np.asarray(values, dtype=np.float64)
        values = values[np.isfinite(values)]
        if len(values) == 0:
            return self
        # bulk path: exact value-count aggregation when cardinality is low
        # (constant/binary columns keep their exact shape), else quantile
        # pre-binning — same sketch contract, vectorized host work
        uniq, counts = np.unique(values, return_counts=True)
        if len(uniq) <= 4 * self.max_bins:
            for v, cnt in zip(uniq, counts):
                self._insert(float(v), float(cnt))
        else:
            qs = np.linspace(0, 1, 4 * self.max_bins + 1)
            edges = np.unique(np.quantile(values, qs))
            counts, edges = np.histogram(values, bins=edges)
            centers = 0.5 * (edges[:-1] + edges[1:])
            for c, cnt in zip(centers, counts):
                if cnt > 0:
                    self._insert(float(c), float(cnt))
        self._compress()
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        out = StreamingHistogram(max(self.max_bins, other.max_bins))
        for c, n in self._points + other._points:
            out._insert(c, n)
        out._compress()
        return out

    def _insert(self, p: float, count: float) -> None:
        import bisect
        idx = bisect.bisect_left([x[0] for x in self._points], p)
        if idx < len(self._points) and self._points[idx][0] == p:
            self._points[idx][1] += count
        else:
            self._points.insert(idx, [p, count])

    def _compress(self) -> None:
        while len(self._points) > self.max_bins:
            gaps = [self._points[i + 1][0] - self._points[i][0]
                    for i in range(len(self._points) - 1)]
            i = int(np.argmin(gaps))
            (p1, n1), (p2, n2) = self._points[i], self._points[i + 1]
            self._points[i] = [(p1 * n1 + p2 * n2) / (n1 + n2), n1 + n2]
            del self._points[i + 1]

    # -- queries -----------------------------------------------------------
    @property
    def bins(self) -> List[Tuple[float, float]]:
        return [(p, n) for p, n in self._points]

    @property
    def total(self) -> float:
        return float(sum(n for _, n in self._points))

    def sum_to(self, b: float) -> float:
        """Estimated count of points ≤ b (trapezoid interpolation between
        centroids, ≙ StreamingHistogram.sum)."""
        pts = self._points
        if not pts:
            return 0.0
        if b < pts[0][0]:
            return 0.0
        if b >= pts[-1][0]:
            return self.total
        s = 0.0
        for i in range(len(pts) - 1):
            p1, n1 = pts[i]
            p2, n2 = pts[i + 1]
            if b < p1:
                break
            if b >= p2:
                s += n1
                continue
            # inside trapezoid (p1, p2)
            frac = (b - p1) / (p2 - p1)
            nb = n1 + (n2 - n1) * frac
            s += n1 / 2.0 + (n1 + nb) / 2.0 * frac
            break
        return float(s)

    # -- serialization -----------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        """JSON form of the sketch — the bundle-baseline representation.
        Round-trips exactly: the points ARE the sketch state."""
        return {"maxBins": self.max_bins,
                "points": [[float(p), float(n)] for p, n in self._points]}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "StreamingHistogram":
        h = StreamingHistogram(int(d.get("maxBins", 64)))
        for p, n in d.get("points") or []:
            h._insert(float(p), float(n))
        h._compress()
        return h

    def to_fixed_bins(self, n_bins: int, lo: Optional[float] = None,
                      hi: Optional[float] = None) -> np.ndarray:
        """Export to a fixed-range density histogram (the FeatureDistribution
        representation) via cumulative differences."""
        pts = self._points
        if not pts:
            return np.zeros(n_bins)
        lo = pts[0][0] if lo is None else lo
        hi = pts[-1][0] if hi is None else hi
        if hi <= lo:
            out = np.zeros(n_bins)
            out[0] = self.total
            return out
        edges = np.linspace(lo, hi, n_bins + 1)
        cums = np.asarray([self.sum_to(e) for e in edges])
        return np.maximum(np.diff(cums), 0.0)
