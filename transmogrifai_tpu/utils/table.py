"""ASCII table renderer for summaryPretty (reference:
utils/src/main/scala/com/salesforce/op/utils/table/Table.scala)."""

from __future__ import annotations

from typing import List, Optional, Sequence


def render_table(headers: Sequence[str], rows: Sequence[Sequence],
                 title: Optional[str] = None) -> str:
    cells = [[str(c) for c in r] for r in rows]
    widths = [len(h) for h in headers]
    for r in cells:
        for i, c in enumerate(r):
            if i < len(widths):
                widths[i] = max(widths[i], len(c))

    def line(ch="-", junction="+"):
        return junction + junction.join(ch * (w + 2) for w in widths) + junction

    def fmt_row(vals):
        return "| " + " | ".join(
            v.ljust(w) for v, w in zip(vals, widths)) + " |"

    out = []
    if title:
        total = sum(widths) + 3 * len(widths) + 1
        out.append(line("="))
        out.append("|" + title.center(total - 2) + "|")
    out.append(line("="))
    out.append(fmt_row(list(headers)))
    out.append(line("="))
    for r in cells:
        out.append(fmt_row(r + [""] * (len(widths) - len(r))))
    out.append(line("-"))
    return "\n".join(out)
