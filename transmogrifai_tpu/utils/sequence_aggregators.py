"""Sequence aggregators — per-position / per-key reductions over sequence
columns (reference: utils/src/main/scala/com/salesforce/op/utils/spark/
SequenceAggregators.scala: SumNumSeq, MeanSeqNullNum, ModeSeqNullInt,
SumSeqMapDouble, MeanSeqMapDouble, CountSeqMapLong, ModeSeqMapLong).

The reference implements these as Spark SQL ``Aggregator``s consumed by
sequence estimators (fill-value computation for numeric/map vectorizers).
Here they are vectorized host reductions: sequence columns are short per-row
tuples (one slot per input feature), so the reduction is numpy over [N, S]
with NaN masks; map variants fold per (key, position).
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _to_masked(rows: Sequence[Sequence[Optional[float]]]) -> np.ndarray:
    """[N, S] float array with None → NaN."""
    return np.array([[np.nan if v is None else float(v) for v in row]
                     for row in rows], dtype=np.float64)


def sum_by_position(rows: Sequence[Sequence[Optional[float]]]) -> List[float]:
    """≙ SumNumSeq: per-position sums, nulls count as zero."""
    if not len(rows):
        return []
    a = _to_masked(rows)
    return np.nansum(a, axis=0).tolist()


def mean_by_position(rows: Sequence[Sequence[Optional[float]]]) -> List[float]:
    """≙ MeanSeqNullNum: per-position means ignoring nulls (0.0 when a
    position is all-null, matching the reference's 0-count guard)."""
    if not len(rows):
        return []
    a = _to_masked(rows)
    cnt = np.sum(~np.isnan(a), axis=0)
    s = np.nansum(a, axis=0)
    return np.where(cnt > 0, s / np.maximum(cnt, 1), 0.0).tolist()


def mode_by_position(rows: Sequence[Sequence[Optional[int]]]) -> List[int]:
    """≙ ModeSeqNullInt: per-position modal value ignoring nulls; ties break
    to the smallest value (reference: min of max-count values); all-null → 0."""
    if not len(rows):
        return []
    S = len(rows[0])
    out: List[int] = []
    for s in range(S):
        c = Counter(int(row[s]) for row in rows if row[s] is not None)
        if not c:
            out.append(0)
            continue
        top = max(c.values())
        out.append(min(v for v, n in c.items() if n == top))
    return out


def sum_maps_by_key(rows: Sequence[Sequence[Dict[str, float]]]
                    ) -> List[Dict[str, float]]:
    """≙ SumSeqMapDouble: per-(position, key) sums over a sequence of map
    columns."""
    if not len(rows):
        return []
    S = len(rows[0])
    out: List[Dict[str, float]] = []
    for s in range(S):
        acc: Dict[str, float] = defaultdict(float)
        for row in rows:
            for k, v in (row[s] or {}).items():
                acc[k] += float(v)
        out.append(dict(acc))
    return out


def mean_maps_by_key(rows: Sequence[Sequence[Dict[str, float]]]
                     ) -> List[Dict[str, float]]:
    """≙ MeanSeqMapDouble: per-(position, key) means over present entries."""
    if not len(rows):
        return []
    S = len(rows[0])
    out: List[Dict[str, float]] = []
    for s in range(S):
        acc: Dict[str, float] = defaultdict(float)
        cnt: Dict[str, int] = defaultdict(int)
        for row in rows:
            for k, v in (row[s] or {}).items():
                acc[k] += float(v)
                cnt[k] += 1
        out.append({k: acc[k] / cnt[k] for k in acc})
    return out


def count_maps_by_key(rows: Sequence[Sequence[Dict[str, Any]]]
                      ) -> List[Dict[str, int]]:
    """≙ CountSeqMapLong: per-(position, key) presence counts."""
    if not len(rows):
        return []
    S = len(rows[0])
    out: List[Dict[str, int]] = []
    for s in range(S):
        cnt: Dict[str, int] = defaultdict(int)
        for row in rows:
            for k in (row[s] or {}):
                cnt[k] += 1
        out.append(dict(cnt))
    return out


def mode_maps_by_key(rows: Sequence[Sequence[Dict[str, int]]]
                     ) -> List[Dict[str, int]]:
    """≙ ModeSeqMapLong: per-(position, key) modal value, ties to smallest."""
    if not len(rows):
        return []
    S = len(rows[0])
    out: List[Dict[str, int]] = []
    for s in range(S):
        per_key: Dict[str, Counter] = defaultdict(Counter)
        for row in rows:
            for k, v in (row[s] or {}).items():
                per_key[k][int(v)] += 1
        res = {}
        for k, c in per_key.items():
            top = max(c.values())
            res[k] = min(v for v, n in c.items() if n == top)
        out.append(res)
    return out
