"""Predictor stage bases — the TPU-native re-design of OpPredictorWrapper /
OpPredictionModel (reference: core/.../stages/sparkwrappers/specific/
OpPredictorWrapper.scala:67).

Every model estimator takes (label: RealNN, features: OPVector) and produces a
``Prediction`` column.  The split between *array-level* fit/predict functions
(pure, jittable, vmappable) and the *stage* wrappers is deliberate: the
ModelSelector's CV grid calls the array-level functions directly so that
(fold × candidate) training vectorises on the device mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Type

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, TransformerModel
from ..types import OPVector, Prediction, RealNN


def prediction_column(prediction: np.ndarray,
                      probability: Optional[np.ndarray] = None,
                      raw_prediction: Optional[np.ndarray] = None) -> Column:
    values: Dict[str, Any] = {"prediction": prediction}
    if probability is not None:
        values["probability"] = probability
    if raw_prediction is not None:
        values["rawPrediction"] = raw_prediction
    return Column(Prediction, values)


def extract_xy(batch: ColumnBatch, label_feature, features_feature
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Pull (X [N,D] float32, y [N] float32) out of a batch.  Device-resident
    feature matrices are returned AS-IS — fits consume them on device, and
    forcing a host copy here would cross the (slow) accelerator link twice."""
    import jax

    from ..sparse.matrix import SparseMatrix

    ycol = batch[label_feature.name]
    xcol = batch[features_feature.name]
    y = np.asarray(ycol.values, dtype=np.float32)
    xv = xcol.values
    if isinstance(xv, SparseMatrix):
        # sparse device representation passes through untouched — fitters
        # that understand it consume the COO entry stream directly, and
        # densifying here would be exactly the [N, num_hashes] blow-up the
        # representation exists to avoid
        return xv, y
    if isinstance(xv, jax.Array):
        # bf16 feature-matrix STORAGE passes through — fitters fuse the
        # upcast into their matmuls; forcing f32 here would materialize a
        # second full copy in HBM
        X = (xv if xv.dtype in (jnp.float32, jnp.bfloat16)
             else xv.astype(jnp.float32))
    else:
        X = np.asarray(xv, dtype=np.float32)
    return X, y


class PredictionModel(TransformerModel):
    """Base fitted model: ``predict_arrays`` on the feature matrix →
    Prediction column (≙ OpPredictionModel/OpProbabilisticClassifierModel)."""

    in_kinds = (RealNN, OPVector)
    out_kind = Prediction
    allow_label_as_input = True

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def supports_device_scores(self) -> bool:
        """True when this model can score a device-resident matrix in HBM.
        Delegating wrappers (SelectedModel) override to ask the wrapped
        model, so host-only inner models (e.g. ExternalModel) fall back to
        the host predict path instead of raising mid-transform."""
        return hasattr(self, "device_scores")

    def transform(self, batch: ColumnBatch) -> Column:
        import jax

        from ..sparse.matrix import SparseMatrix

        feats = self.input_features[1]
        xv = batch[feats.name].values
        if isinstance(xv, SparseMatrix) and self.supports_device_scores():
            out = self.device_scores(xv, full=True)
            return prediction_column(out["prediction"],
                                     out.get("probability"),
                                     out.get("rawPrediction"))
        if isinstance(xv, jax.Array) and self.supports_device_scores():
            # device-resident matrix: score in HBM and keep the per-row
            # results as device arrays — pulling X over the (slow) host link
            # to predict on numpy costs more than all the compute.
            # full=True makes device_scores mirror predict_arrays' key set,
            # so the Prediction schema is residency-independent.
            X = (xv if xv.dtype in (jnp.float32, jnp.bfloat16)
                 else xv.astype(jnp.float32))
            out = self.device_scores(X, full=True)
            return prediction_column(out["prediction"],
                                     out.get("probability"),
                                     out.get("rawPrediction"))
        X = np.asarray(xv, dtype=np.float32)
        out = self.predict_arrays(X)
        return prediction_column(
            np.asarray(out["prediction"]),
            None if out.get("probability") is None else np.asarray(out["probability"]),
            None if out.get("rawPrediction") is None else np.asarray(out["rawPrediction"]))


class PredictorEstimator(Estimator):
    """Base model estimator (label, features) → PredictionModel."""

    in_kinds = (RealNN, OPVector)
    out_kind = Prediction
    allow_label_as_input = True
    model_cls: Type[PredictionModel] = PredictionModel
    # families whose fit_arrays_grid honours aot.pretrace_mode() — inside
    # that mode the grid programs are lowered+compiled (populating the
    # persistent compile cache from a background thread) but never executed
    supports_pretrace = False

    def pretrace_arrays_grid(self, X, y, fold_weights, grids) -> None:
        """Compile-only dry run of :meth:`fit_arrays_grid` — the sweep
        submits this to a background thread (see aot.pretrace_submit) so
        ``new_compiles_during_train`` overlaps data prep instead of
        serializing the fit loop."""
        from ..aot import pretrace_scope
        with pretrace_scope():
            self.fit_arrays_grid(X, y, fold_weights, grids)

    def fit_arrays(self, X: np.ndarray, y: np.ndarray,
                   sample_weight: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Array-level fit → the ``fitted`` dict of the model.  Pure; the CV
        grid calls this (or its vectorised variant) directly."""
        raise NotImplementedError

    def fit_arrays_grid(self, X: np.ndarray, y: np.ndarray,
                        fold_weights: np.ndarray, grids) -> list:
        """Batched (fold × grid-point) training for the CV grid: returns
        fitted dicts indexed ``[fold][grid_point]``.

        ``fold_weights`` [F, N] are per-fold row weights over the SAME data
        matrix (weight 0 == row held out of training) — CV keeps one
        HBM-resident X with static shapes instead of slicing per fold.

        This default loops host-side (every estimator honours
        ``sample_weight``, so it is still slice- and recompile-free); the
        linear and tree families override it with single batched XLA programs
        (≙ OpValidator.scala:320-349's thread-pool fan-out, SURVEY §2.6 P3).
        """
        import copy as _copy
        out = []
        for k in range(fold_weights.shape[0]):
            row = []
            for params in grids:
                est = _copy.deepcopy(self)
                for pk, pv in params.items():
                    est.set(pk, pv)
                row.append(est.fit_arrays(X, y, sample_weight=fold_weights[k]))
            out.append(row)
        return out

    def fit(self, batch: ColumnBatch) -> PredictionModel:
        label, feats = self.input_features
        X, y = extract_xy(batch, label, feats)
        fitted = self.fit_arrays(X, y)
        model = self.model_cls(fitted=fitted, **self._params)
        return self._finalize_model(model)

    @property
    def label_feature(self):
        return self.input_features[0]

    @property
    def features_feature(self):
        return self.input_features[1]
