"""Pure, jittable full-batch solvers for linear-family models.

The reference trains its linear models through Spark MLlib's distributed
L-BFGS/OWLQN (wrapped at core/.../impl/classification/OpLogisticRegression.scala:46
etc.).  On TPU the whole design changes: the data matrix lives in HBM, the
gradient is one [N,D]x[D,C] matmul on the MXU, and we run an accelerated
proximal-gradient (FISTA) loop under ``lax.while_loop`` — fully jittable and
``vmap``-able over hyper-parameter grids and CV folds, which is what makes the
ModelSelector grid data-parallel (SURVEY.md §2.6 P3).

All solvers share the signature convention::

    fit_*(X, y, sample_weight, l2, l1, ...) -> params dict of arrays

with static shapes only, so a grid of (fold, reg, elastic-net) candidates can
be trained as one ``vmap``'d XLA program.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from transmogrifai_tpu.sparse.matrix import (sp_matmat, sp_matvec,
                                             sp_rmatmat, sp_rmatvec)


# --------------------------------------------------------------------------
# losses: value-and-grad of the smooth part, given margins/logits
# --------------------------------------------------------------------------

def _logistic_loss_grad(logits: jnp.ndarray, y01: jnp.ndarray, w: jnp.ndarray):
    """Binary logistic.  logits [N], y01 [N] in {0,1}, w [N] sample weights.
    Returns (mean loss, dloss/dlogits [N])."""
    ls = jax.nn.softplus(jnp.where(y01 > 0.5, -logits, logits))
    p = jax.nn.sigmoid(logits)
    wsum = jnp.sum(w)
    return jnp.sum(w * ls) / wsum, w * (p - y01) / wsum


def _softmax_loss_grad(logits: jnp.ndarray, yoh: jnp.ndarray, w: jnp.ndarray):
    """Multinomial.  logits [N,C], yoh one-hot [N,C]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    wsum = jnp.sum(w)
    loss = -jnp.sum(w * jnp.sum(yoh * logp, axis=-1)) / wsum
    return loss, (w[:, None] * (p - yoh)) / wsum


def _squared_loss_grad(pred: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray):
    r = pred - y
    wsum = jnp.sum(w)
    return 0.5 * jnp.sum(w * r * r) / wsum, w * r / wsum


def _squared_hinge_loss_grad(margin: jnp.ndarray, ypm: jnp.ndarray, w: jnp.ndarray):
    """Squared hinge for linear SVC.  ypm [N] in {-1,+1}."""
    viol = jnp.maximum(0.0, 1.0 - ypm * margin)
    wsum = jnp.sum(w)
    return jnp.sum(w * viol * viol) / wsum, w * (-2.0 * viol * ypm) / wsum


def _poisson_loss_grad(eta: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray):
    """Poisson deviance with log link: loss = mean(exp(eta) - y*eta)."""
    mu = jnp.exp(jnp.clip(eta, -30.0, 30.0))
    wsum = jnp.sum(w)
    return jnp.sum(w * (mu - y * eta)) / wsum, w * (mu - y) / wsum


def _gamma_loss_grad(eta: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray):
    """Gamma deviance with log link: loss = mean(y*exp(-eta) + eta)."""
    inv_mu = jnp.exp(jnp.clip(-eta, -30.0, 30.0))
    wsum = jnp.sum(w)
    return jnp.sum(w * (y * inv_mu + eta)) / wsum, w * (1.0 - y * inv_mu) / wsum


LOSSES = {
    "logistic": _logistic_loss_grad,
    "softmax": _softmax_loss_grad,
    "squared": _squared_loss_grad,
    "squared_hinge": _squared_hinge_loss_grad,
    "poisson": _poisson_loss_grad,
    "gamma": _gamma_loss_grad,
}

# Lipschitz constant of d²loss/dlogits² (per-row bound), used for the FISTA
# step size together with the spectral norm of X.  Exp-link losses (poisson,
# gamma) have unbounded curvature, so fista_fit runs a backtracking line
# search for them instead of trusting a constant bound.
_LOSS_CURVATURE = {
    "logistic": 0.25,
    "softmax": 0.5,
    "squared": 1.0,
    "squared_hinge": 2.0,
    "poisson": 1.0,   # initial guess only — backtracking shrinks as needed
    "gamma": 1.0,
}

_BACKTRACK_LOSSES = frozenset({"poisson", "gamma"})


class FitResult(NamedTuple):
    coef: jnp.ndarray       # [D, C]
    intercept: jnp.ndarray  # [C]
    n_iter: jnp.ndarray     # scalar int
    objective: jnp.ndarray  # final objective value


def _spectral_norm_sq_weighted(X: jnp.ndarray, wn: jnp.ndarray,
                               mean: jnp.ndarray, scale: jnp.ndarray,
                               iters: int = 16) -> jnp.ndarray:
    """λ_max of Xs^T diag(wn) Xs for the IMPLICITLY standardized matrix
    Xs = (X - mean)/scale, never materializing Xs or the weighted product —
    one shared HBM-resident X serves every (fold × grid) lane."""
    d = X.shape[1]
    v = jnp.full((d,), 1.0 / jnp.sqrt(d), jnp.float32)

    def mv(v):
        u = (X @ (v / scale)) - mean @ (v / scale)     # Xs @ v  [N]
        u = wn * u
        return (X.T @ u - mean * jnp.sum(u)) / scale   # Xs^T u  [D]

    def body(_, v):
        u = mv(v)
        return u / (jnp.linalg.norm(u) + 1e-12)

    v = jax.lax.fori_loop(0, iters, body, v)
    return jnp.vdot(v, mv(v))


def _loss_target(loss: str, y: jnp.ndarray, n_classes: int) -> jnp.ndarray:
    if loss == "softmax":
        return jax.nn.one_hot(y.astype(jnp.int32), n_classes,
                              dtype=jnp.float32)
    if loss == "squared_hinge":
        return jnp.where(y > 0.5, 1.0, -1.0).astype(jnp.float32)
    return y.astype(jnp.float32)


def _fista_loop(xs_mv: Callable, xs_tmv: Callable, target: jnp.ndarray,
                w: jnp.ndarray, l2: jnp.ndarray, l1: jnp.ndarray, *,
                loss: str, d: int, n_classes: int, fit_intercept: bool,
                max_iter: int, tol: float, sigma_sq: jnp.ndarray) -> FitResult:
    """The FISTA iteration shared by the dense and sparse fitters: the data
    matrix enters ONLY through the ``xs_mv``/``xs_tmv`` closures, so the same
    loop serves both the implicit-standardized dense matmuls and the
    take+segment_sum flat-COO matvecs."""
    C = n_classes
    loss_fn = LOSSES[loss]
    L = _LOSS_CURVATURE[loss] * sigma_sq + l2
    step0 = 1.0 / jnp.maximum(L, 1e-12)
    backtrack = loss in _BACKTRACK_LOSSES

    shape = (d, C) if C > 1 else (d,)
    b_shape = (C,) if C > 1 else ()

    def smooth_grad(coef, intercept):
        """Value and gradient of the smooth part (loss + l2 ridge)."""
        lin = xs_mv(coef) + intercept
        lval, glin = loss_fn(lin, target, w)
        gcoef = xs_tmv(glin) + l2 * coef
        gint = (jnp.sum(glin, axis=0) if C > 1 else jnp.sum(glin))
        return lval + 0.5 * l2 * jnp.sum(coef * coef), gcoef, gint

    def smooth_val(coef, intercept):
        lin = xs_mv(coef) + intercept
        lval, _ = loss_fn(lin, target, w)
        return lval + 0.5 * l2 * jnp.sum(coef * coef)

    def prox(u, s):
        return jnp.sign(u) * jnp.maximum(jnp.abs(u) - s * l1, 0.0)

    def cond(state):
        k, _, _, _, _, _, _, delta = state
        return jnp.logical_and(k < max_iter, delta > tol)

    def body(state):
        k, coef, intercept, z_c, z_i, t, step, _ = state
        f_z, g_c, g_i = smooth_grad(z_c, z_i)

        def attempt(s):
            nc = prox(z_c - s * g_c, s)
            ni = z_i - s * g_i if fit_intercept else z_i
            return nc, ni

        if backtrack:
            # Beck–Teboulle backtracking: shrink the step until the smooth
            # part is majorized by its quadratic model at z (exp-link losses
            # have unbounded curvature, so the fixed bound is unreliable)
            def sufficient(s):
                nc, ni = attempt(s)
                dc = nc - z_c
                di = jnp.atleast_1d(ni - z_i)
                quad = (f_z + jnp.sum(dc * g_c)
                        + jnp.sum(di * jnp.atleast_1d(g_i))
                        + (jnp.sum(dc * dc) + jnp.sum(di * di)) / (2.0 * s))
                return smooth_val(nc, ni) <= quad + 1e-12

            def bt_cond(bs):
                s, ok, it = bs
                return jnp.logical_and(~ok, it < 30)

            def bt_body(bs):
                s, _, it = bs
                s = s * 0.5
                return s, sufficient(s), it + 1

            step, _, _ = jax.lax.while_loop(
                bt_cond, bt_body,
                (step, sufficient(step), jnp.zeros((), jnp.int32)))

        new_c, new_i = attempt(step)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t_new
        # adaptive restart on non-descent direction
        restart = jnp.sum((z_c - new_c) * (new_c - coef)) > 0.0
        beta = jnp.where(restart, 0.0, beta)
        t_new = jnp.where(restart, 1.0, t_new)
        zc_next = new_c + beta * (new_c - coef)
        zi_next = new_i + beta * (new_i - intercept)
        delta = jnp.max(jnp.abs(new_c - coef)) + jnp.max(
            jnp.abs(jnp.atleast_1d(new_i - intercept)))
        return k + 1, new_c, new_i, zc_next, zi_next, t_new, step, delta

    init = (jnp.zeros((), jnp.int32), jnp.zeros(shape, jnp.float32),
            jnp.zeros(b_shape, jnp.float32), jnp.zeros(shape, jnp.float32),
            jnp.zeros(b_shape, jnp.float32), jnp.ones((), jnp.float32),
            step0.astype(jnp.float32), jnp.full((), jnp.inf, jnp.float32))
    k, coef, intercept, *_ = jax.lax.while_loop(cond, body, init)
    obj = smooth_val(coef, intercept) + l1 * jnp.sum(jnp.abs(coef))
    return FitResult(coef, jnp.atleast_1d(intercept), k, obj)


@functools.partial(
    jax.jit,
    static_argnames=("loss", "fit_intercept", "max_iter", "n_classes"))
def fista_fit(X: jnp.ndarray, y: jnp.ndarray, sample_weight: jnp.ndarray,
              l2: jnp.ndarray, l1: jnp.ndarray, *, loss: str = "logistic",
              fit_intercept: bool = True, max_iter: int = 100,
              tol: float = 1e-6, n_classes: int = 1,
              mean: Optional[jnp.ndarray] = None,
              scale: Optional[jnp.ndarray] = None,
              sigma_sq: Optional[jnp.ndarray] = None) -> FitResult:
    """Accelerated proximal gradient with adaptive restart.

    minimises  mean_loss(Xs w + b) + l2/2 ||w||² + l1 ||w||₁  (no penalty on b)
    where Xs = (X - mean)/scale is the IMPLICITLY standardized matrix when
    ``mean``/``scale`` are given — the standardized copy is never
    materialized, so every (fold × grid) vmap lane shares the single
    HBM-resident ``X`` and XLA batches the lanes' matvecs into one matmul.
    The returned coefficients live in the standardized basis (caller
    un-scales, matching Spark ML's internal-standardization contract).

    ``l2``/``l1`` may be traced scalars → vmap over a regularisation grid.
    ``sigma_sq`` (λ_max of the weighted Gram) may be shared across grid
    lanes; computed here when absent.
    """
    n, d = X.shape
    C = n_classes
    w = sample_weight.astype(jnp.float32)
    target = _loss_target(loss, y, C)

    std = scale is not None
    mu = mean if std else jnp.zeros((d,), jnp.float32)
    sc = scale if std else jnp.ones((d,), jnp.float32)

    def xs_mv(coef):
        """Xs @ coef without materializing Xs ([N] or [N, C])."""
        v = coef / (sc[:, None] if coef.ndim == 2 else sc)
        return X @ v - mu @ v

    def xs_tmv(glin):
        """Xs^T @ glin ([D] or [D, C])."""
        if glin.ndim == 2:
            sg = jnp.sum(glin, axis=0)
            num = X.T @ glin - mu[:, None] * sg[None, :]
            return num / sc[:, None]
        return (X.T @ glin - mu * jnp.sum(glin)) / sc

    # step size from Lipschitz bound: c * sigma_max(Xs_w)^2 (+ l2)
    wn = w / jnp.sum(w)
    if sigma_sq is None:
        sigma_sq = _spectral_norm_sq_weighted(X, wn, mu, sc)
    return _fista_loop(xs_mv, xs_tmv, target, w, l2, l1, loss=loss, d=d,
                       n_classes=C, fit_intercept=fit_intercept,
                       max_iter=max_iter, tol=tol, sigma_sq=sigma_sq)


@functools.partial(jax.jit, static_argnames=("fit_intercept",))
def ridge_fit(X: jnp.ndarray, y: jnp.ndarray, sample_weight: jnp.ndarray,
              l2: jnp.ndarray, *, fit_intercept: bool = True) -> FitResult:
    """Closed-form weighted ridge regression via normal equations (the l1=0
    fast path for OpLinearRegression): one X^T X matmul on the MXU + a [D,D]
    Cholesky solve."""
    n, d = X.shape
    w = sample_weight.astype(jnp.float32)
    wsum = jnp.sum(w)
    if fit_intercept:
        xm = (w @ X) / wsum
        ym = jnp.sum(w * y) / wsum
        Xc = X - xm
        yc = y - ym
    else:
        Xc, yc = X, y
    Xw = Xc * w[:, None]
    A = (Xc.T @ Xw) / wsum + l2 * jnp.eye(d, dtype=jnp.float32)
    b = (Xw.T @ yc) / wsum
    coef = jax.scipy.linalg.solve(A, b, assume_a="pos")
    intercept = (ym - xm @ coef) if fit_intercept else jnp.zeros((), jnp.float32)
    resid = yc - Xc @ coef
    obj = 0.5 * jnp.sum(w * resid * resid) / wsum + 0.5 * l2 * jnp.sum(coef * coef)
    return FitResult(coef, jnp.atleast_1d(intercept), jnp.zeros((), jnp.int32), obj)


@functools.partial(jax.jit, static_argnames=("n_classes",))
def naive_bayes_fit(X: jnp.ndarray, y: jnp.ndarray, sample_weight: jnp.ndarray,
                    smoothing: jnp.ndarray, *, n_classes: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Multinomial naive Bayes (≙ OpNaiveBayes): class-conditional log
    likelihoods from per-class feature sums.  Expects non-negative features.
    Returns (log_prior [C], log_prob [C, D])."""
    yoh = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=jnp.float32)  # [N,C]
    w = sample_weight.astype(jnp.float32)
    cls_count = (w @ yoh)                                 # [C]
    feat_count = (yoh * w[:, None]).T @ jnp.maximum(X, 0.0)  # [C,D]
    log_prior = jnp.log(cls_count + 1e-12) - jnp.log(jnp.sum(cls_count) + 1e-12)
    sm = feat_count + smoothing
    log_prob = jnp.log(sm) - jnp.log(jnp.sum(sm, axis=1, keepdims=True))
    return log_prior, log_prob


@functools.partial(
    jax.jit,
    static_argnames=("loss", "fit_intercept", "standardization", "max_iter",
                     "n_classes"))
def linear_grid_fit(X: jnp.ndarray, y: jnp.ndarray, fold_weights: jnp.ndarray,
                    l2s: jnp.ndarray, l1s: jnp.ndarray, *,
                    loss: str = "logistic", fit_intercept: bool = True,
                    standardization: bool = True, max_iter: int = 100,
                    tol: float = 1e-6, n_classes: int = 1) -> FitResult:
    """The whole (fold × grid-point) CV matrix as ONE XLA program.

    ``fold_weights`` [F, N] are per-fold row weights (weight 0 == row held
    out), so every candidate shares the single HBM-resident ``X`` — CV folds
    are weight masks, not slices, which kills both the host↔device ping-pong
    and the per-fold-shape recompiles.  ``l2s``/``l1s`` [G] give the penalty
    grid.  Standardisation moments are computed once per fold and shared by
    the grid points.  Returns a FitResult with [F, G, ...]-stacked leaves.

    ≙ the reference's thread-pool fan-out of k×Σ|grid| Spark jobs
    (OpValidator.scala:320-349), re-expressed as nested vmap (SURVEY §2.6 P3).
    """
    d = X.shape[1]

    def one_fold(w):
        if standardization:
            mean, scale = standardize_moments(X, w, center=fit_intercept)
        else:
            mean, scale = (jnp.zeros((d,), jnp.float32), jnp.ones((d,), jnp.float32))
        # λ_max of the fold's weighted Gram is grid-independent: compute it
        # once per fold and share it across the vmapped grid lanes
        wn = w / jnp.sum(w)
        sigma_sq = _spectral_norm_sq_weighted(X, wn, mean, scale)

        def one_pt(l2, l1):
            res = fista_fit(X, y, w, l2, l1, loss=loss,
                            fit_intercept=fit_intercept, max_iter=max_iter,
                            tol=tol, n_classes=n_classes,
                            mean=mean, scale=scale, sigma_sq=sigma_sq)
            return unscale_params(res, mean, scale, n_classes)

        return jax.vmap(one_pt)(l2s, l1s)

    return jax.vmap(one_fold)(fold_weights)


@functools.partial(
    jax.jit, static_argnames=("fit_intercept", "standardization"))
def ridge_grid_fit(X: jnp.ndarray, y: jnp.ndarray, fold_weights: jnp.ndarray,
                   l2s: jnp.ndarray, *, fit_intercept: bool = True,
                   standardization: bool = True) -> FitResult:
    """Closed-form ridge over the (fold × l2-grid) matrix in one program
    (the l1=0 fast path of the OpLinearRegression grid).

    Works on per-fold Gram statistics of ONE shared matrix: when an
    intercept is fit, X is first shifted by its global column means (a single
    [N, D] copy total — algebraic Gram centering of raw data would
    catastrophically cancel in f32 for large-mean features), then each fold's
    (X^T W X)/s is one matmul; the residual per-fold centering and the
    standardization now act on O(variance)-magnitude Gram entries, which is
    numerically safe."""
    d = X.shape[1]
    if fit_intercept:
        g = jnp.mean(X, axis=0)
        X = X - g
    else:
        g = jnp.zeros((d,), jnp.float32)

    def one_fold(w):
        s = jnp.sum(w)
        Xw = X * w[:, None]                      # fold-local scratch [N, D]
        G = (X.T @ Xw) / s                       # (X^T W X)/s  [D, D]
        p = (X.T @ (w * y)) / s                  # (X^T W y)/s  [D]
        m = (w @ X) / s                          # weighted mean [D]
        ym = jnp.sum(w * y) / s
        yy = jnp.sum(w * y * y) / s
        if standardization:
            var = jnp.diagonal(G) - m * m
            scale = jnp.sqrt(jnp.maximum(var, 1e-12))
        else:
            scale = jnp.ones((d,), jnp.float32)
        if fit_intercept:
            # center by the weighted mean: Gc = G - m m^T, bc = p - m*ym
            Gc = G - jnp.outer(m, m)
            bc = p - m * ym
            y0 = ym
            mean_u = m
        else:
            Gc, bc, y0 = G, p, jnp.zeros((), jnp.float32)
            mean_u = jnp.zeros((d,), jnp.float32)
        # standardized basis: A = D^-1 Gc D^-1, b = D^-1 bc
        A0 = Gc / (scale[:, None] * scale[None, :])
        b = bc / scale

        def one_pt(l2):
            A = A0 + l2 * jnp.eye(d, dtype=jnp.float32)
            coef = jax.scipy.linalg.solve(A, b, assume_a="pos")
            obj = 0.5 * (yy - y0 * y0 - 2.0 * b @ coef + coef @ (A0 @ coef)
                         ) + 0.5 * l2 * jnp.sum(coef * coef)
            res = FitResult(coef, jnp.atleast_1d(y0),
                            jnp.zeros((), jnp.int32), obj)
            res = unscale_params(res, mean_u, scale, 1)
            # undo the global shift: predictions are X@coef + (b - g@coef)
            return FitResult(res.coef, res.intercept - g @ res.coef,
                             res.n_iter, res.objective)

        return jax.vmap(one_pt)(l2s)

    # lax.map (not vmap) over folds: the weighted Gram scratch Xw is [N, D]
    # per fold — batching folds would materialize an [F, N, D] operand
    # (~3.7 GiB at the 11M-row scale this path exists for)
    return jax.lax.map(one_fold, fold_weights)


def standardize_moments(X: jnp.ndarray, sample_weight: jnp.ndarray,
                        center: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted standardisation moments (mean, scale) — consumers apply them
    IMPLICITLY inside their matvecs; the standardized matrix itself is never
    materialized (a per-(fold × grid) copy of X would dominate HBM)."""
    w = sample_weight / jnp.sum(sample_weight)
    mean = w @ X
    var = w @ (X * X) - mean * mean
    scale = jnp.sqrt(jnp.maximum(var, 1e-12))
    mu = mean if center else jnp.zeros_like(mean)
    return mu, scale


def standardize(X: jnp.ndarray, sample_weight: jnp.ndarray,
                center: bool) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Weighted feature standardisation (Spark ML standardizes internally and
    un-scales the coefficients; we do the same).  Returns (Xs, mean, scale)."""
    mu, scale = standardize_moments(X, sample_weight, center)
    return (X - mu) / scale, mu, scale


# --------------------------------------------------------------------------
# sparse (flat-COO) fitters: same FISTA loop, matvecs via take + segment_sum
# --------------------------------------------------------------------------

def _sp_col_scale(values, indices, row_ids, wn, n_cols):
    """Weighted per-column scale sqrt(E[x²] - E[x]²) from COO entries only.

    Sparse standardization is SCALE-ONLY (Spark's ``withMean=False``
    convention for sparse vectors): subtracting the mean would densify
    every row, defeating the representation.  Absent columns have
    variance 0 and clamp to scale 1e-6-ish — their coefficients stay 0.
    """
    mean = sp_rmatvec(values, indices, row_ids, wn, n_cols=n_cols)
    ex2 = sp_rmatvec(values * values, indices, row_ids, wn, n_cols=n_cols)
    var = jnp.maximum(ex2 - mean * mean, 0.0)
    return jnp.sqrt(jnp.maximum(var, 1e-12))


def _sp_spectral_norm_sq(values, indices, row_ids, wn, scale,
                         n_rows: int, n_cols: int,
                         iters: int = 16) -> jnp.ndarray:
    """λ_max of Xs^T diag(wn) Xs for the implicitly scaled sparse matrix."""
    v = jnp.full((n_cols,), 1.0 / jnp.sqrt(n_cols), jnp.float32)

    def mv(v):
        u = wn * sp_matvec(values, indices, row_ids, v / scale, n_rows=n_rows)
        return sp_rmatvec(values, indices, row_ids, u, n_cols=n_cols) / scale

    def body(_, v):
        u = mv(v)
        return u / (jnp.linalg.norm(u) + 1e-12)

    v = jax.lax.fori_loop(0, iters, body, v)
    return jnp.vdot(v, mv(v))


@functools.partial(
    jax.jit,
    static_argnames=("loss", "fit_intercept", "standardization", "max_iter",
                     "n_classes", "n_rows", "n_cols"))
def sparse_linear_grid_fit(values, indices, row_ids, y, fold_weights,
                           l2s, l1s, *, n_rows: int, n_cols: int,
                           loss: str = "logistic", fit_intercept: bool = True,
                           standardization: bool = True, max_iter: int = 100,
                           tol: float = 1e-6, n_classes: int = 1) -> FitResult:
    """``linear_grid_fit`` for a flat-COO matrix: the whole (fold × grid) CV
    block as one XLA program, with every lane sharing the single device-
    resident entry stream — nothing in the program is ever [N, n_cols].

    Pad entries (value 0.0) and zero-weight pad rows both contribute
    nothing to any segment sum, so the ladder padding is exact here just
    like in the dense weighted path.  Standardization is scale-only (see
    ``_sp_col_scale``); coefficients are returned un-scaled.
    """
    C = n_classes
    target = _loss_target(loss, y, C)
    zeros_d = jnp.zeros((n_cols,), jnp.float32)

    def one_fold(w):
        w = w.astype(jnp.float32)
        wn = w / jnp.sum(w)
        if standardization:
            scale = _sp_col_scale(values, indices, row_ids, wn, n_cols)
        else:
            scale = jnp.ones((n_cols,), jnp.float32)
        sigma_sq = _sp_spectral_norm_sq(values, indices, row_ids, wn, scale,
                                        n_rows, n_cols)

        def xs_mv(coef):
            if coef.ndim == 2:
                return sp_matmat(values, indices, row_ids,
                                 coef / scale[:, None], n_rows=n_rows)
            return sp_matvec(values, indices, row_ids, coef / scale,
                             n_rows=n_rows)

        def xs_tmv(glin):
            if glin.ndim == 2:
                return sp_rmatmat(values, indices, row_ids, glin,
                                  n_cols=n_cols) / scale[:, None]
            return sp_rmatvec(values, indices, row_ids, glin,
                              n_cols=n_cols) / scale

        def one_pt(l2, l1):
            res = _fista_loop(xs_mv, xs_tmv, target, w, l2, l1, loss=loss,
                              d=n_cols, n_classes=C,
                              fit_intercept=fit_intercept, max_iter=max_iter,
                              tol=tol, sigma_sq=sigma_sq)
            return unscale_params(res, zeros_d, scale, C)

        return jax.vmap(one_pt)(l2s, l1s)

    return jax.vmap(one_fold)(fold_weights)


def sparse_fista_fit(sm, y, sample_weight, l2: float, l1: float, *,
                     loss: str = "logistic", fit_intercept: bool = True,
                     standardization: bool = True, max_iter: int = 100,
                     tol: float = 1e-6, n_classes: int = 1) -> FitResult:
    """Single-point sparse fit: the G=1, F=1 slice of the grid program (one
    code path to test, and the single-fit case replays the grid executable
    when shapes match).  ``sm`` is a ``sparse.matrix.SparseMatrix``."""
    w = jnp.asarray(sample_weight, jnp.float32)
    res = sparse_linear_grid_fit(
        sm.values, sm.indices, sm.row_ids, jnp.asarray(y), w[None, :],
        jnp.asarray([l2], jnp.float32), jnp.asarray([l1], jnp.float32),
        n_rows=sm.n_rows, n_cols=sm.n_cols, loss=loss,
        fit_intercept=fit_intercept, standardization=standardization,
        max_iter=max_iter, tol=tol, n_classes=n_classes)
    return FitResult(res.coef[0, 0], res.intercept[0, 0],
                     res.n_iter[0, 0], res.objective[0, 0])


def unscale_params(res: FitResult, mean: jnp.ndarray, scale: jnp.ndarray,
                   n_classes: int) -> FitResult:
    if n_classes > 1:
        coef = res.coef / scale[:, None]
        intercept = res.intercept - mean @ coef
    else:
        coef = res.coef / scale
        intercept = res.intercept - jnp.atleast_1d(mean @ coef)
    return FitResult(coef, intercept, res.n_iter, res.objective)
